"""Whole-plan fusion: stage-IR nodes, the donation-aware stage compiler, and
the fused join→aggregate pipeline stage.

The per-family device programs in ``exec/device.py`` stitch a streamed
chunk's Filter→Project→Join-probe→Agg/TopK chain together with host Python:
every seam pays a dispatch, a device round-trip, and fresh buffers for the
fold state. This module is the composable alternative:

* **Stage IR** — a chunk pipeline is described as a :class:`StagePlan` of
  frozen op nodes (:class:`FilterOp`, :class:`ProjectOp`,
  :class:`JoinProbeOp`, :class:`GroupAggOp`, :class:`TopKOp`). The plan's
  ``skeleton()`` is the program-cache identity: ONE jitted executable per
  (pipeline skeleton, shape bucket, mesh fingerprint), exactly like
  ``device._program_key`` but spanning the whole stage instead of one
  family.

* **Donation-aware program cache** — :func:`compile_stage` is
  ``device._cached_predicate_jit`` plus ``donate_argnums``: streamed fold
  states (the grouped-agg partial table, the top-k candidate matrix, the
  join candidate index buffers) are donated to XLA so the update happens in
  place instead of reallocating every chunk. The donation vector is part of
  the cache key — flipping ``hyperspace.exec.fusion.donation`` never aliases
  executables.

* **Fused join→aggregate stage** — :func:`fused_join_agg_program` compiles
  hash-probe span walk, capacity-bounded pair expansion, exact key
  verification, the post-join predicate, the grouped segment reduction AND
  the running-state merge into one XLA program; :func:`stream_join_aggregate`
  drives it over a broadcast join's probe stream. Capacity overflows (pair
  count or group cardinality beyond the compiled buckets) are detected *in
  program*: the donated state round-trips unchanged (`jnp.where` selects the
  original state into the aliased outputs) and the chunk is redone on the
  per-family path, counted as ``hs_device_fallback_total{op="fusion"}``.

Everything here is gated behind ``hyperspace.exec.fusion.enabled`` and
byte-identical to the per-family path (proved by tests/test_fusion.py); the
per-family path remains both the default and the fallback.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.check import hlo_lint as _hlo_lint

# --------------------------------------------------------------------------
# conf gates
# --------------------------------------------------------------------------


def fusion_wanted(conf) -> bool:
    """Whole-plan fusion master switch (``hyperspace.exec.fusion.enabled``)."""
    try:
        return bool(conf.fusion_enabled)
    except Exception:
        return False


def donation_wanted(conf) -> bool:
    """Fold-state donation, consulted only when fusion is on."""
    try:
        return bool(conf.fusion_enabled) and bool(conf.fusion_donation)
    except Exception:
        return False


# --------------------------------------------------------------------------
# observability: dispatch counts and the device high-water mark
# --------------------------------------------------------------------------


def count_dispatch(program: str) -> None:
    """Count one jitted device-program dispatch. Called at EVERY jitted call
    site (per-family and fused), so the fusion win is measurable as a
    dispatch-count delta, not just wall clock."""
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_device_dispatches_total",
        "Jitted device-program dispatches, by program family",
        program=program,
    ).inc()


def note_peak_bytes() -> int:
    """Sample total live device-array bytes (``jax.live_arrays``) and fold it
    into the ``hs_device_peak_bytes`` high-water gauge. Called after fold
    steps — the moment both the old and new state could coexist, which is
    exactly the allocation donation exists to avoid."""
    import jax

    from hyperspace_tpu.obs.metrics import REGISTRY

    total = 0
    for a in jax.live_arrays():
        try:
            total += int(a.nbytes)
        except Exception:
            continue
    g = REGISTRY.gauge(
        "hs_device_peak_bytes",
        "High-water total bytes of live device arrays, sampled after "
        "streamed fold steps",
    )
    if total > g.value:
        g.set(total)
    return total


# --------------------------------------------------------------------------
# stage IR
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class FilterOp:
    """Fused predicate over the chunk (skeleton = structure + column kinds,
    literal-free: same identity discipline as ``predicate_skeleton``)."""

    skeleton: str

    def token(self) -> str:
        return f"F({self.skeleton})"


@dataclass(frozen=True)
class ProjectOp:
    columns: Tuple[str, ...]

    def token(self) -> str:
        return f"P({','.join(self.columns)})"


@dataclass(frozen=True)
class JoinProbeOp:
    """Broadcast hash-probe against a resident build table: span walk +
    bounded pair expansion + exact key verification, ``pair_cap`` pairs."""

    n_keys: int
    pair_cap: int

    def token(self) -> str:
        return f"J(k{self.n_keys}:p{self.pair_cap})"


@dataclass(frozen=True)
class GroupAggOp:
    """Grouped segment reduction folded into a donated running partial."""

    key_specs: Tuple[Tuple[str, str], ...]  # (column, 'i'|'f')
    slot_specs: Tuple[Tuple[str, Optional[str], bool], ...]
    cap: int

    def token(self) -> str:
        k = ",".join(f"{n}:{t}" for n, t in self.key_specs)
        s = ",".join(f"{kind}:{c}:{int(i)}" for kind, c, i in self.slot_specs)
        return f"G[{self.cap}](k:{k}|s:{s})"


@dataclass(frozen=True)
class TopKOp:
    """Chunk top-k select merged into a donated candidate matrix."""

    num_keys: int
    cap: int

    def token(self) -> str:
        return f"T(k{self.num_keys}:c{self.cap})"


@dataclass(frozen=True)
class StagePlan:
    """One streamed pipeline stage: the ordered op chain a chunk flows
    through. ``skeleton()`` is the whole-stage program identity — the string
    ``device._program_key`` combines with the mesh fingerprint, while the
    shape bucket stays the jit cache's own shape signature."""

    ops: Tuple[object, ...]

    def skeleton(self) -> str:
        return "fuse[" + ">".join(op.token() for op in self.ops) + "]"


# --------------------------------------------------------------------------
# donation-aware program cache
# --------------------------------------------------------------------------

_STAGE_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_STAGE_CACHE_MAX = 256


def compile_stage(skeleton: str, fn, *, donate_argnums: Tuple[int, ...] = ()):
    """``device._cached_predicate_jit`` with a donation vector: one jitted
    stage program per (skeleton, donate_argnums). Donated positional args
    hand their buffers to XLA for output aliasing — callers MUST NOT touch a
    donated argument after the call (the ``donated-buffer-reuse`` lint rule
    enforces this repo-wide) and rebind their state to the returned arrays
    instead."""
    import jax

    donate = tuple(int(i) for i in donate_argnums)
    key = (skeleton, donate)
    jitted = _STAGE_CACHE.get(key)
    if jitted is None:
        while len(_STAGE_CACHE) >= _STAGE_CACHE_MAX:
            _STAGE_CACHE.popitem(last=False)
        jitted = jax.jit(fn, donate_argnums=donate) if donate else jax.jit(fn)
        _STAGE_CACHE[key] = jitted
    else:
        _STAGE_CACHE.move_to_end(key)
    return jitted


def clear_stage_cache() -> None:
    _STAGE_CACHE.clear()


# --------------------------------------------------------------------------
# fused join -> grouped-aggregate stage (the q3 shape)
# --------------------------------------------------------------------------

# Declared HLO contracts: the fused stage is ONE executable (single_fusion),
# host-callback-free and collective-free — any collective means the
# broadcast build side leaked onto the mesh path.
_hlo_lint.register_contract(
    "fused-stage-join-agg",
    collectives={},
    description=(
        "whole-stage probe+verify+filter+group+merge program: one executable, "
        "device-local, donated fold state"
    ),
    single_fusion=True,
)


def fused_join_agg_program(
    vmodes: Tuple[str, ...],
    pred_fn,
    needed: Tuple[str, ...],
    on_probe: Dict[str, bool],
    gkey_specs: Tuple[Tuple[str, str], ...],
    slot_specs,
    cap: int,
    pair_cap: int,
):
    """Build the whole fused stage: hash span walk → bounded pair expansion →
    exact key verify (``vmodes[i]`` = 'i' exact int64 / 'f' float64 with
    NaN-matches-NaN, mirroring ``join_stream._pairs_equal``) → post-join
    predicate → grouped segment reduction over the kept pairs → merge into
    the donated running partial.

    Returns ``(total_pairs, n_chunk_groups, n_merged, n_kept, n_out, fs_out,
    keys_out, slots_out)``. Overflow (``total_pairs > pair_cap`` or a group
    count beyond ``cap``) is detected in-program: the rank-compressed counts
    are exact even above capacity, and every state output selects the
    ORIGINAL state via ``jnp.where`` so the donated buffers round-trip
    unchanged for the host to redo the chunk per-family."""
    import jax.numpy as jnp
    from jax import ops as jops

    from hyperspace_tpu.exec import device as D
    from hyperspace_tpu.ops.hashing import combine_hashes_jnp

    def program(
        state_keys, state_slots, state_fs, state_n,
        table_h, border, n_build, bkenc, pplanes, pkenc, pcols, bcols,
        lits, n_valid, row_base,
    ):
        n_probe = pplanes[0].shape[0]
        t_len = table_h.shape[0]
        b_len = bkenc[0].shape[0]
        # 1. probe spans (the hash-probe family's body)
        h = combine_hashes_jnp(list(pplanes))
        lo = jnp.minimum(jnp.searchsorted(table_h, h, side="left").astype(jnp.int64), n_build)
        hi = jnp.minimum(jnp.searchsorted(table_h, h, side="right").astype(jnp.int64), n_build)
        rvalid = jnp.arange(n_probe, dtype=jnp.int64) < n_valid
        counts = jnp.where(rvalid, hi - lo, jnp.int64(0))
        cum = jnp.cumsum(counts)
        total = cum[-1]
        # 2. capacity-bounded pair expansion (the host repeat/cumsum walk,
        # in-program): pair j belongs to the first probe row whose cumulative
        # count exceeds j
        j = jnp.arange(pair_cap, dtype=jnp.int64)
        pvalid = j < jnp.minimum(total, jnp.int64(pair_cap))
        cand_p = jnp.clip(
            jnp.searchsorted(cum, j, side="right").astype(jnp.int64), 0, n_probe - 1
        )
        start = cum[cand_p] - counts[cand_p]
        slot = jnp.clip(j - start + lo[cand_p], 0, t_len - 1)
        cand_b = jnp.clip(border[slot], 0, b_len - 1)
        # 3. exact key verification (32-bit hash collisions removed)
        keep = pvalid
        for pe, be, mode in zip(pkenc, bkenc, vmodes):
            a = pe[cand_p]
            b = be[cand_b]
            if mode == "i":
                keep = keep & (a == b)
            else:
                af = a.astype(jnp.float64)
                bf = b.astype(jnp.float64)
                keep = keep & ((af == bf) | (jnp.isnan(af) & jnp.isnan(bf)))
        # 4. pair-space column gather + post-join predicate
        cols = {}
        for name in needed:
            src = pcols if on_probe[name] else bcols
            cols[name] = src[name][cand_p if on_probe[name] else cand_b]
        if pred_fn is not None:
            keep = keep & pred_fn(cols, lits)
        rank = jnp.cumsum(keep.astype(jnp.int64)) - 1  # kept-pair position
        n_kept = keep.sum().astype(jnp.int64)
        # 5. grouped segment reduction over the kept pairs (the
        # grouped-agg-chunk family's body, in pair space). fs is the
        # kept-pair position — exactly the row index the per-family path
        # sees after assembling only the kept pairs.
        codes = [D._key_code(cols[name], tag) for name, tag in gkey_specs]
        order, ms, n_chunk, segs = D._segment_ids(codes, keep, cap)
        rep = jops.segment_min(
            jnp.where(ms, order.astype(jnp.int64), jnp.int64(pair_cap)),
            segs, num_segments=cap, indices_are_sorted=True,
        )
        repc = jnp.clip(rep, 0, pair_cap - 1)
        fs_b = jnp.where(rep < pair_cap, rank[repc] + row_base, D._FS_SENTINEL)
        key_b = tuple(cols[name][repc] for name, _ in gkey_specs)
        cols_sorted = {c: cols[c][order] for _, c, _ in slot_specs if c is not None}
        slot_b = D._segment_reduce_slots(cols_sorted, ms, segs, cap, slot_specs)
        # 6. merge into the running partial (the grouped-merge family's body)
        idx = jnp.arange(cap)
        mask = jnp.concatenate([idx < state_n, idx < n_chunk])
        kcat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_keys, key_b))
        scat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_slots, slot_b))
        fs_cat = jnp.concatenate([state_fs, fs_b])
        n_m, fs_m, key_m, slot_m = D._merge_concat_parts(
            gkey_specs, slot_specs, cap, kcat, scat, fs_cat, mask
        )
        # 7. overflow guard: on ANY capacity overflow the (donated,
        # output-aliased) state round-trips unchanged
        ok = (total <= pair_cap) & (n_chunk <= cap) & (n_m <= cap)
        n_out = jnp.where(ok, n_m, state_n)
        fs_out = jnp.where(ok, fs_m, state_fs)
        keys_out = tuple(jnp.where(ok, m, s) for m, s in zip(key_m, state_keys))
        slots_out = tuple(jnp.where(ok, m, s) for m, s in zip(slot_m, state_slots))
        return total, n_chunk, n_m, n_kept, n_out, fs_out, keys_out, slots_out

    return program


def _verify_modes(probe_dtypes, build_dtypes) -> Tuple[str, ...]:
    """Per-key device verification mode, or DeviceUnsupported when the exact
    host semantics (``_pairs_equal``) don't map onto encoded planes: strings
    and objects need the host loop, unsigned ints promote weirdly, and
    mixed-unit datetimes compare at the finest common unit host-side."""
    from hyperspace_tpu.exec.device import DeviceUnsupported

    modes: List[str] = []
    for pd, bd in zip(probe_dtypes, build_dtypes):
        pk, bk = pd.kind, bd.kind
        if pk in "OUS" or bk in "OUS":
            raise DeviceUnsupported("string join keys verify host-side")
        if pk == "u" or bk == "u":
            raise DeviceUnsupported("unsigned join keys verify host-side")
        if pk == "M" or bk == "M":
            if pk != bk or pd != bd:
                raise DeviceUnsupported("mixed datetime join keys verify host-side")
            modes.append("i")
        elif pk in "ib" and bk in "ib":
            modes.append("i")
        else:
            modes.append("f")
    return tuple(modes)


class _JoinAggState:
    """Host-side driver state of one fused join→aggregate stream."""

    __slots__ = ("pair_cap", "bdev", "pred", "refs", "sources", "probe_is_left")

    def __init__(self, pair_cap, bdev, pred, refs, sources, probe_is_left):
        self.pair_cap = pair_cap
        self.bdev = bdev
        self.pred = pred
        self.refs = refs
        self.sources = sources
        self.probe_is_left = probe_is_left


def stream_join_aggregate(executor, join_plan, spec, post_filter, group_keys, aggs):
    """Whole-plan fused execution of a q3-shaped chain — Aggregate over
    (Filter over) an inner broadcast Join: ONE donated XLA program folds each
    probe chunk straight into the device-resident grouped partial.

    Per-family equivalent of one chunk: hash-probe dispatch + host verify +
    fused-postjoin dispatch + grouped-agg-chunk dispatch + grouped-merge
    dispatch. Here: one dispatch, with the fold state donated. Byte-identical
    output (tests/test_fusion.py proves it against the fusion-off path).

    Raises DeviceUnsupported before any fold when the shape doesn't fuse;
    mid-stream capacity overflows redo the offending chunk per-family
    (``hs_device_fallback_total{op="fusion"}``)."""
    import jax

    from hyperspace_tpu.exec import device as D
    from hyperspace_tpu.exec import join_stream as J
    from hyperspace_tpu.exec import trace
    from hyperspace_tpu.exec import batch as B
    from hyperspace_tpu.plan import logical as L
    from hyperspace_tpu.plan.expr import as_bool_mask
    from hyperspace_tpu.utils.x64 import ensure_x64

    ensure_x64()
    session = executor.session
    conf = session.conf
    if join_plan.how != "inner":
        raise D.DeviceUnsupported("fused join-agg stage covers inner joins")

    build_plan = join_plan.left if spec.build_is_left else join_plan.right
    probe_plan = join_plan.right if spec.build_is_left else join_plan.left
    bkeys = spec.lkeys if spec.build_is_left else spec.rkeys
    pkeys = spec.rkeys if spec.build_is_left else spec.lkeys
    probe_is_left = not spec.build_is_left
    lout = join_plan.left.output_columns
    rout = join_plan.right.output_columns

    refs = sorted(post_filter.references()) if post_filter is not None else []
    agg_inputs = sorted({c for _, _, c in aggs if c is not None})
    needed = tuple(dict.fromkeys(refs + list(group_keys) + agg_inputs))
    sources = {name: D._join_column_source(name, lout, rout) for name in needed}
    on_probe = {
        name: (is_left == probe_is_left) for name, (is_left, _) in sources.items()
    }

    bset = set(build_plan.output_columns)
    pset = set(probe_plan.output_columns)
    need_b = {c for (il, c) in sources.values() if il == spec.build_is_left and c in bset}
    need_p = {c for (il, c) in sources.values() if il == probe_is_left and c in pset}
    build_cols = [c for c in build_plan.output_columns if c in need_b or c in bkeys]
    probe_cols = [c for c in probe_plan.output_columns if c in need_p or c in pkeys]

    build = J._shared_build_side(session, build_plan, build_cols, bkeys)
    J._count_broadcast()
    trace.record("join", "broadcast-hash-stream")

    # the grouped fold state + finalization semantics live in
    # GroupedAggStream; this stage drives its device partial directly. The
    # capacity hint keys on the probe leaf files so repeat runs over the same
    # lake start at the settled capacity instead of overflowing chunk one.
    from hyperspace_tpu.exec.executor import _chain_to_scan, _leaf_files

    _, probe_leaf = _chain_to_scan(probe_plan)
    hint_key = (
        ("fused-join-agg",) + tuple(_leaf_files(probe_leaf))
        if probe_leaf is not None else None
    )
    gs = D.GroupedAggStream(
        session, list(group_keys), list(aggs),
        max_groups=conf.agg_max_groups, cap_floor=conf.agg_capacity_floor,
        hint_key=hint_key,
    )
    build_dtype = {name: build.batch[col].dtype for name, (il, col) in sources.items()
                   if il == spec.build_is_left}

    def orient(p_i, b_i):
        return (p_i, b_i) if probe_is_left else (b_i, p_i)

    def classic_chunk(chunk: Dict[str, np.ndarray]) -> None:
        """Per-family fold of one probe chunk (also the overflow redo)."""
        p_i, b_i = J._probe_chunk(session, build, chunk, pkeys, bkeys)
        if post_filter is not None and p_i.shape[0]:
            mask = None
            if conf.device_execution_enabled:
                try:
                    mask = J._device_postjoin_mask(
                        session, post_filter, chunk, build, p_i, b_i,
                        refs, sources, probe_is_left,
                    )
                except D.DeviceUnsupported:
                    trace.fallback("join", "postjoin_device")
            if mask is None:
                lidx, ridx = orient(p_i, b_i)
                lb, rb = (chunk, build.batch) if probe_is_left else (build.batch, chunk)
                refbatch = J._gather_pairs(refs, sources, lb, rb, lidx, ridx, {}, {})
                raw = as_bool_mask(post_filter.eval(refbatch))
                mask = np.broadcast_to(np.asarray(raw, dtype=bool), (p_i.shape[0],))
            p_i, b_i = p_i[mask], b_i[mask]
        if p_i.shape[0] == 0:
            return
        lidx, ridx = orient(p_i, b_i)
        lb, rb = (chunk, build.batch) if probe_is_left else (build.batch, chunk)
        joined = J._gather_pairs(list(needed), sources, lb, rb, lidx, ridx, {}, {})
        gs.update(joined, None)

    # seed the stream's schema from zero-row columns of the joined dtypes
    # (inner join: no null promotion, dtypes pass through the gather)
    probe_exec = probe_plan
    if set(probe_cols) != set(probe_plan.output_columns):
        probe_exec = L.Project(probe_cols, probe_plan)

    from hyperspace_tpu.exec.executor import Executor

    state = _JoinAggState(0, None, None, refs, sources, probe_is_left)
    chunks = 0
    probe_iter = Executor(session).execute_stream(probe_exec)
    try:
        for chunk in probe_iter:
            chunk = {k: np.asarray(v) for k, v in chunk.items()}
            n = B.num_rows(chunk)
            if n == 0:
                continue
            if chunks == 0:
                # fusability gates raise DeviceUnsupported here, before any
                # fold: the caller redoes the query on the materialized path
                sample = {
                    name: np.empty(0, dtype=(
                        build_dtype[name] if not on_probe[name]
                        else chunk[sources[name][1]].dtype
                    ))
                    for name in needed
                }
                gs._check_schema(sample)
                keys_schema, _ = gs._schema
                if any(tag == "s" for tag, _, _ in keys_schema):
                    raise D.DeviceUnsupported("string group keys stay per-family")
                _verify_modes(
                    [np.asarray(chunk[pk]).dtype for pk in pkeys],
                    [build.key_dtypes[bk] for bk in bkeys],
                )
            chunks += 1
            try:
                folded = _fused_fold_chunk(
                    session, gs, build, chunk, pkeys, bkeys, post_filter,
                    needed, on_probe, sources, state,
                )
            except D.DeviceUnsupported:
                folded = False
            if not folded:
                # capacity overflow (or an unfusable chunk dtype): the state
                # round-tripped unchanged, redo this one chunk per-family
                trace.fallback("fusion", "join-agg-overflow")
                classic_chunk(chunk)
            p = gs._partial
            if p is not None and int(p["n"]) > gs.max_groups:
                raise D.DeviceUnsupported(
                    f"group cardinality {int(p['n'])} exceeds "
                    f"maxGroups {gs.max_groups}"
                )
    finally:
        probe_iter.close()
    if not gs.has_data:
        # nothing ever folded (no probe chunks, or every chunk redone
        # per-family with zero kept pairs): punt to the materialized path
        # rather than hand-crafting empty dtypes here
        raise D.DeviceUnsupported("fused join-agg stream folded no groups")
    trace.record("agg", "fused-join-agg-stream")
    return gs.finalize()


def _fused_fold_chunk(session, gs, build, chunk, pkeys, bkeys, post_filter,
                      needed, on_probe, sources, state) -> bool:
    """Fold one probe chunk with the single fused program. Returns False on
    capacity overflow (state preserved; caller redoes the chunk per-family);
    raises DeviceUnsupported when this chunk's dtypes don't fuse."""
    import time as _ptime

    import jax

    from hyperspace_tpu.exec import device as D
    from hyperspace_tpu.exec import batch as B
    from hyperspace_tpu.ops.encode import hash_input_uint32

    conf = session.conf
    n = B.num_rows(chunk)
    vmodes = _verify_modes(
        [np.asarray(chunk[pk]).dtype for pk in pkeys],
        [build.key_dtypes[bk] for bk in bkeys],
    )

    # build-side device encodings (cached on the BuildSide across chunks)
    if state.bdev is None:
        bkenc = []
        for bk in bkeys:
            got = build.enc.get(bk)
            if got is None:
                got = D.encode_column(build.batch[bk])
                build.enc[bk] = got
            bkenc.append(jax.device_put(got[0]))
        bcols = {}
        bcodecs = {}
        for name in needed:
            if on_probe[name]:
                continue
            col = sources[name][1]
            got = build.enc.get(col)
            if got is None:
                got = D.encode_column(build.batch[col])
                build.enc[col] = got
            if got[1].kind == "string" and col in {c for _, _, c in gs.aggs if c}:
                raise D.DeviceUnsupported("string aggregate inputs stay host-side")
            bcols[name] = jax.device_put(got[0])
            bcodecs[name] = got[1]
        border = np.zeros(int(build.table.shape[0]), dtype=np.int64)
        border[: build.n] = build.order
        state.bdev = (tuple(bkenc), bcols, bcodecs, jax.device_put(border))
    bkenc, bcols, bcodecs, border = state.bdev

    # probe-side per-chunk encodings, padded to the sqrt(2) row bucket
    pplanes = []
    for pk, bk in zip(pkeys, bkeys):
        arr = np.asarray(chunk[pk])
        bdt = build.key_dtypes[bk]
        if arr.dtype.kind == "M" and bdt.kind == "M" and arr.dtype != bdt:
            arr = arr.astype(bdt)
        pplanes.append(D._pad_to_bucket(hash_input_uint32(arr), 1, np.uint32(0)))
    pkenc = []
    for pk in pkeys:
        enc, _ = D.encode_column(np.asarray(chunk[pk]))
        pkenc.append(D._pad_to_bucket(enc, 1, 0 if enc.dtype != np.float64 else np.nan))
    pcols = {}
    codecs = dict(bcodecs)
    for name in needed:
        if not on_probe[name]:
            continue
        col = sources[name][1]
        enc, codec = D.encode_column(np.asarray(chunk[col]))
        if codec.kind == "string" and name in {c for _, _, c in gs.aggs if c}:
            raise D.DeviceUnsupported("string aggregate inputs stay host-side")
        pcols[name] = D._pad_to_bucket(enc, 1, 0 if enc.dtype != np.float64 else np.nan)
        codecs[name] = codec

    if post_filter is not None:
        pred_fn, lits = D.compile_predicate(post_filter, codecs)
        pred_sk = D.predicate_skeleton(post_filter, codecs)
    else:
        pred_fn, lits = None, ()
        pred_sk = "<none>"

    keys_schema, _ = gs._schema
    gkey_specs = tuple(
        (name, "f" if tag == "f" else "i")
        for name, (tag, _, _) in zip(gs.group_keys, keys_schema)
    )

    # capacity buckets: pairs start at one-match-per-row, groups at the hint
    if state.pair_cap <= 0:
        state.pair_cap = D.bucket_rows(n)
    pair_cap = state.pair_cap
    p = gs._partial
    cap = D.group_capacity(max(gs._cap_hint, 1), gs.cap_floor)
    if p is not None:
        cap = max(cap, p["cap"])
    state_keys, state_slots, state_fs, state_n = _ensure_grouped_state(
        gs, gkey_specs, cap
    )

    plan = StagePlan((
        FilterOp(pred_sk),
        JoinProbeOp(len(pkeys), pair_cap),
        GroupAggOp(gkey_specs, tuple(gs._slots), cap),
    ))
    donate = donation_wanted(conf)
    skeleton = plan.skeleton() + f"|v:{','.join(vmodes)}" + ("|don" if donate else "")
    key = D._program_key(skeleton, session.mesh)
    program = fused_join_agg_program(
        vmodes, pred_fn, needed, on_probe, gkey_specs, tuple(gs._slots),
        cap, pair_cap,
    )
    jitted = compile_stage(key, program, donate_argnums=(0, 1, 2) if donate else ())
    shapes = (pplanes[0].shape, int(build.table.shape[0]), cap, pair_cap)
    first = D._note_compile(key, shapes)
    args = (
        state_keys, state_slots, state_fs, np.int64(state_n),
        build.table, border, np.int64(build.n), bkenc,
        tuple(jax.device_put(pl) for pl in pplanes),
        tuple(jax.device_put(k) for k in pkenc),
        {k: jax.device_put(v) for k, v in pcols.items()}, bcols,
        tuple(lits), np.int64(n), np.int64(gs._row_base),
    )
    _hlo_lint.maybe_verify(conf, "fused-stage-join-agg", key, jitted, args)
    t0 = _ptime.perf_counter()
    total_d, n_chunk_d, n_m_d, n_kept_d, n_out_d, fs_out, keys_out, slots_out = jitted(*args)
    count_dispatch("fused-stage-join-agg")
    total, n_chunk, n_m, n_kept, n_out = (
        int(total_d), int(n_chunk_d), int(n_m_d), int(n_kept_d), int(n_out_d)
    )
    D._observe_program("fused-stage-join-agg", first, t0)
    # the donated state is gone: rebind to the returned (aliased) arrays
    # whether the fold took or overflowed (overflow returns the original
    # state values through the same buffers)
    gs._partial = {
        "cap": cap, "n": n_out, "fs": fs_out,
        "keys": list(keys_out), "slots": list(slots_out),
    }
    note_peak_bytes()
    if total > pair_cap or n_chunk > cap or n_m > cap:
        state.pair_cap = D.bucket_rows(max(total, 1))
        gs._cap_hint = max(gs._cap_hint, n_chunk, n_m)
        return False
    gs._cap_hint = max(gs._cap_hint, n_m)
    gs._row_base += n_kept
    return True


def _ensure_grouped_state(gs, gkey_specs, cap):
    """The running partial as (keys, slots, fs, n) device arrays padded to
    ``cap`` — zero-filled when the stream is fresh (the fused program's merge
    masks them out via ``state_n == 0``)."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.exec import device as D

    p = gs._partial
    if p is None:
        keys = tuple(
            jnp.zeros(cap, dtype=jnp.float64 if tag == "f" else jnp.int64)
            for _, tag in gkey_specs
        )
        slots = tuple(
            jnp.zeros(cap, dtype=jnp.int64 if (kind in ("cntm", "cnt") or (isint and kind in ("min", "max", "sum"))) else jnp.float64)
            for kind, _, isint in gs._slots
        )
        fs = jnp.full(cap, D._FS_SENTINEL, dtype=jnp.int64)
        return keys, slots, fs, 0
    if p["cap"] < cap:
        p["fs"] = D._dev_pad(p["fs"], cap, D._FS_SENTINEL)
        p["keys"] = [
            D._dev_pad(k, cap, 0 if k.dtype != np.float64 else np.nan) for k in p["keys"]
        ]
        p["slots"] = [D._dev_pad(s, cap, 0) for s in p["slots"]]
        p["cap"] = cap
    return tuple(p["keys"]), tuple(p["slots"]), p["fs"], int(p["n"])
