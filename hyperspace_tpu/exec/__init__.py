"""Execution engine: host/device executors, streaming folds, stage IR.

Everything here loads lazily (PEP 562): ``exec.device`` imports
``exec.stage_ir`` at module level, and ``stage_ir`` reaches back into
``device``'s program machinery from inside its functions — an eager
import from this package would freeze one direction of that cycle and
break the other. Lazy attributes also keep ``import hyperspace_tpu``
cheap for callers that never execute a query (jax loads on first use,
not at import).

Public surface (mirrors ``parallel/__init__``):

- ``Executor`` — the logical-plan executor (materialized + streaming).
- ``GroupedAggStream``, ``TopKStream``, ``DeviceUnsupported`` — the
  streamed device folds and their fallback signal (``exec.device`` /
  ``exec.topk``).
- ``stream_broadcast_join``, ``BroadcastSpec``, ``broadcast_spec`` — the
  streaming broadcast hash join (``exec.join_stream``).
- Stage IR (``exec.stage_ir``): ``StagePlan`` + ``FilterOp`` /
  ``ProjectOp`` / ``JoinProbeOp`` / ``GroupAggOp`` / ``TopKOp`` nodes,
  the donation-aware ``compile_stage`` program cache, and
  ``stream_join_aggregate`` — the whole-plan fused q3 entry point.
"""

from __future__ import annotations

__all__ = [
    "BroadcastSpec",
    "DeviceUnsupported",
    "Executor",
    "FilterOp",
    "GroupAggOp",
    "GroupedAggStream",
    "JoinProbeOp",
    "ProjectOp",
    "StagePlan",
    "TopKOp",
    "TopKStream",
    "broadcast_spec",
    "compile_stage",
    "stream_broadcast_join",
    "stream_join_aggregate",
]

_HOMES = {
    "Executor": "executor",
    "GroupedAggStream": "device",
    "DeviceUnsupported": "device",
    "TopKStream": "topk",
    "BroadcastSpec": "join_stream",
    "broadcast_spec": "join_stream",
    "stream_broadcast_join": "join_stream",
    "StagePlan": "stage_ir",
    "FilterOp": "stage_ir",
    "ProjectOp": "stage_ir",
    "JoinProbeOp": "stage_ir",
    "GroupAggOp": "stage_ir",
    "TopKOp": "stage_ir",
    "compile_stage": "stage_ir",
    "stream_join_aggregate": "stage_ir",
}


def __getattr__(name):
    home = _HOMES.get(name)
    if home is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{home}"), name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
