"""Broadcast hash join: build once on device, stream the probe side.

The bucketed SMJ (exec/device.py) needs BOTH sides to be equally-bucketed
index scans; everything else used to fall back to the materialize-both-sides
pandas merge. This module covers the asymmetric case that dominates star
schemas: one side small enough to *broadcast* (conf
``hyperspace.exec.join.broadcastMaxBytes``, estimated from leaf file sizes).

The small side builds ONE device-resident sorted hash table — per-column
uint32 hash planes (``ops/encode.hash_input_uint32``, value-consistent
across int/float/NaN representations) combined by ``combine_hashes_jnp`` and
argsorted in a single fused jitted program — and the probe side streams
chunk-by-chunk through the executor's scan pipeline. Each probe chunk runs
one jitted probe program (combine + two ``searchsorted`` walks into the
sorted table) sized to a sqrt(2) shape bucket, so a whole probe stream
compiles at most ~3 probe executables; 32-bit hash collisions are removed by
an exact host verification over the candidate pairs. Because the probe side
is *any* streamable plan — including another join's streamed output — q3/q10
multi-join chains stay streaming end-to-end with no intermediate
materialization.

A Filter directly above the join fuses into the chunk walk: matched pairs
evaluate the predicate BEFORE payload columns gather (on device, as the
``fused-postjoin`` gather+predicate program, when every referenced column is
device-encodable; on host over the slim referenced columns otherwise), so
Filter->Project above a Join never round-trips the full join output through
host numpy.

Build sides are shared under serving via ``serving/build_cache.py``: keyed
by (build-plan identity, keys, data-version brand) in a byte-budgeted LRU,
invalidated on brand rotation like the result cache.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

import numpy as np

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.exec.device import (
    DeviceUnsupported,
    _join_column_source,
    _note_compile,
    _program_key,
    bucket_rows,
    compile_predicate,
    encode_column,
    predicate_skeleton,
    stream_bucketed_join,  # noqa: F401  (re-exported: the streaming join surface)
)
from hyperspace_tpu.ops.encode import hash_input_uint32
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import as_bool_mask, extract_equi_join_keys
from hyperspace_tpu.utils.x64 import ensure_x64

from hyperspace_tpu.check import hlo_lint as _hlo_lint

# --- declared HLO contracts (see exec/device.py's block): the broadcast
# join's three program families are single-device and shuffle-free by
# construction — a collective in any of them means the build side leaked
# onto the mesh path.
_hlo_lint.register_contract(
    "hash-build",
    collectives={},
    description="broadcast build: combine key hash planes + stable argsort, device-local",
)
_hlo_lint.register_contract(
    "hash-probe",
    collectives={},
    description="broadcast probe: combine + two searchsorted walks into the sorted table, device-local",
)
_hlo_lint.register_contract(
    "fused-postjoin",
    collectives={},
    description="post-join filter fused over pair-gathered columns, device-local",
)


def _count_broadcast() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_join_broadcast_total",
        "Joins executed by the broadcast-hash streaming path",
    ).inc()


# --------------------------------------------------------------------------
# applicability
# --------------------------------------------------------------------------


class BroadcastSpec:
    __slots__ = ("build_is_left", "lkeys", "rkeys")

    def __init__(self, build_is_left: bool, lkeys: List[str], rkeys: List[str]):
        self.build_is_left = build_is_left
        self.lkeys = lkeys
        self.rkeys = rkeys


def _plan_leaf_bytes(plan: L.LogicalPlan) -> Optional[int]:
    """Estimated input bytes of ``plan`` from its leaf files; None when any
    leaf is not file-backed (no estimate -> no broadcast decision)."""
    leaves = L.collect(plan, lambda p: isinstance(p, (L.Scan, L.FileScan, L.IndexScan)))
    if not leaves:
        return None
    total = 0
    for leaf in leaves:
        try:
            if isinstance(leaf, L.Scan):
                total += sum(int(fi.size) for fi in leaf.relation.all_file_infos())
            else:
                if not leaf.files:
                    return None
                total += sum(os.stat(f).st_size for f in leaf.files)
        except Exception as exc:
            # no estimate -> no broadcast decision; count the swallow so a
            # flaky mount degrading every join to SMJ is visible in metrics
            from hyperspace_tpu.reliability.errors import count_io_error

            count_io_error("join.stat", exc, swallowed=True)
            return None
    return total


def broadcast_spec(session, plan: L.Join) -> Optional[BroadcastSpec]:
    """Which side (if any) broadcasts: the smaller side whose estimated leaf
    bytes fit under ``hyperspace.exec.join.broadcastMaxBytes``."""
    if not isinstance(plan, L.Join) or plan.residual is not None:
        return None
    if plan.how not in ("inner", "left", "right", "outer"):
        return None
    max_bytes = session.conf.join_broadcast_max_bytes
    if max_bytes <= 0:
        return None
    pairs = extract_equi_join_keys(plan.condition)
    if not pairs:
        return None
    lcols = set(plan.left.output_columns)
    rcols = set(plan.right.output_columns)
    lkeys: List[str] = []
    rkeys: List[str] = []
    for a, b in pairs:
        if a in lcols and b in rcols:
            lkeys.append(a)
            rkeys.append(b)
        elif b in lcols and a in rcols:
            lkeys.append(b)
            rkeys.append(a)
        else:
            return None
    lb = _plan_leaf_bytes(plan.left)
    rb = _plan_leaf_bytes(plan.right)
    cands = []
    if lb is not None and lb <= max_bytes:
        cands.append((lb, True))
    if rb is not None and rb <= max_bytes:
        cands.append((rb, False))
    if not cands:
        return None
    # both fit -> broadcast the smaller, probe the larger
    _, build_is_left = min(cands, key=lambda t: t[0])
    return BroadcastSpec(build_is_left, lkeys, rkeys)


# --------------------------------------------------------------------------
# device programs
# --------------------------------------------------------------------------


def _pad_plane(arr: np.ndarray, fill) -> np.ndarray:
    target = bucket_rows(arr.shape[0])
    if target == arr.shape[0]:
        return arr
    pad = np.full(target - arr.shape[0], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


@lru_cache(maxsize=8)
def _hash_build_program(nkeys: int):
    """One fused jitted build: combine hash planes, mask padding to the max
    hash so it sorts last (stable, so real rows with the max hash still come
    first), stable-argsort. jit's own cache handles shape buckets."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hashing import combine_hashes_jnp

    @jax.jit
    def build(planes, n):
        h = combine_hashes_jnp(list(planes))
        idx = jnp.arange(h.shape[0], dtype=jnp.int64)
        h = jnp.where(idx < n, h, jnp.uint32(0xFFFFFFFF))
        order = jnp.argsort(h, stable=True)
        return h[order], order

    return build


@lru_cache(maxsize=8)
def _hash_probe_program(nkeys: int):
    """Per-chunk probe: combine the chunk's hash planes, then the [lo, hi)
    candidate span per probe row via two searchsorted walks into the sorted
    table. Spans clamp to the table's live length so padding (max-hash
    slots) never produces candidates."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.hashing import combine_hashes_jnp

    @jax.jit
    def probe(table_h, n_build, planes):
        h = combine_hashes_jnp(list(planes))
        lo = jnp.searchsorted(table_h, h, side="left").astype(jnp.int64)
        hi = jnp.searchsorted(table_h, h, side="right").astype(jnp.int64)
        return jnp.minimum(lo, n_build), jnp.minimum(hi, n_build)

    return probe


class BuildSide:
    """Device-resident sorted hash table + host payload of the broadcast
    side. ``order`` maps sorted-table slot -> build row; ``enc`` lazily
    caches device encodings of payload columns for the fused post-join
    program."""

    __slots__ = ("batch", "n", "table", "order", "key_dtypes", "nbytes", "enc")

    def __init__(self, batch: B.Batch, n: int, table, order: np.ndarray,
                 key_dtypes: Dict[str, np.dtype], nbytes: int):
        self.batch = batch
        self.n = n
        self.table = table
        self.order = order
        self.key_dtypes = key_dtypes
        self.nbytes = nbytes
        self.enc: Dict[str, tuple] = {}


def build_hash_side(session, build_plan: L.LogicalPlan, build_cols: List[str],
                    bkeys: List[str]) -> BuildSide:
    """Materialize the broadcast side and build its device hash table."""
    ensure_x64()
    from hyperspace_tpu.exec.executor import Executor

    batch = Executor(session).execute(build_plan, required_columns=build_cols)
    batch = {k: np.asarray(v) for k, v in batch.items()}
    n = B.num_rows(batch)
    planes = tuple(_pad_plane(hash_input_uint32(batch[k]), np.uint32(0)) for k in bkeys)
    prog = _hash_build_program(len(bkeys))
    table, order = prog(planes, np.int64(n))
    from hyperspace_tpu.exec import stage_ir as _stage_ir

    _stage_ir.count_dispatch("hash-build")
    sig = (len(bkeys), planes[0].shape[0])
    _note_compile("hash-build", sig)
    _hlo_lint.maybe_verify(
        session.conf, "hash-build",
        _program_key(f"hash-build/{sig}", session.mesh), prog, (planes, np.int64(n)),
    )
    order_host = np.asarray(order)[:n].astype(np.int64)
    nbytes = sum(int(a.nbytes) for a in batch.values())
    nbytes += sum(int(p.nbytes) for p in planes) + int(planes[0].shape[0] * 12)
    return BuildSide(
        batch, n, table, order_host,
        {k: batch[k].dtype for k in bkeys}, nbytes,
    )


# --------------------------------------------------------------------------
# null-aware key verification (pandas-merge semantics: NaN matches NaN,
# NaT matches NaT, None matches None)
# --------------------------------------------------------------------------


def _null_mask_obj(arr: np.ndarray) -> np.ndarray:
    return np.array(
        [v is None or (isinstance(v, float) and v != v) for v in arr], dtype=bool
    )


def _pairs_equal(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    ka, kb = a.dtype.kind, b.dtype.kind
    if ka in "OUS" or kb in "OUS":
        if not (ka in "OUS" and kb in "OUS"):
            return np.zeros(a.shape[0], dtype=bool)  # object vs numeric never matches
        ao, bo = a.astype(object), b.astype(object)
        an, bn = _null_mask_obj(ao), _null_mask_obj(bo)
        eq = np.asarray(ao == bo, dtype=bool)
        return (eq & ~an & ~bn) | (an & bn)
    if ka == "M" or kb == "M":
        if ka != kb:
            return np.zeros(a.shape[0], dtype=bool)
        dt = np.promote_types(a.dtype, b.dtype)
        return a.astype(dt).view("int64") == b.astype(dt).view("int64")  # NaT==NaT
    if ka in "iub" and kb in "iub":
        return a == b
    af, bf = a.astype(np.float64), b.astype(np.float64)
    return (af == bf) | (np.isnan(af) & np.isnan(bf))


def _probe_chunk(session, build: BuildSide, chunk: B.Batch,
                 pkeys: List[str], bkeys: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    """(probe row, build row) matched pairs of one probe chunk: device
    candidate spans by hash, exact host verification over the candidates."""
    n = B.num_rows(chunk)
    planes = []
    for pk, bk in zip(pkeys, bkeys):
        arr = np.asarray(chunk[pk])
        bdt = build.key_dtypes[bk]
        if arr.dtype.kind == "M" and bdt.kind == "M" and arr.dtype != bdt:
            # hash in the build side's epoch unit (a pure function of the
            # value, so equal keys still collide); verification below
            # compares at the finest common unit
            arr = arr.astype(bdt)
        planes.append(hash_input_uint32(arr))
    padded = tuple(_pad_plane(p, np.uint32(0)) for p in planes)
    prog = _hash_probe_program(len(planes))
    lo_d, hi_d = prog(build.table, np.int64(build.n), padded)
    from hyperspace_tpu.exec import stage_ir as _stage_ir

    _stage_ir.count_dispatch("hash-probe")
    sig = (len(planes), int(build.table.shape[0]), padded[0].shape[0])
    _note_compile("hash-probe", sig)
    _hlo_lint.maybe_verify(
        session.conf, "hash-probe",
        _program_key(f"hash-probe/{sig}", session.mesh), prog,
        (build.table, np.int64(build.n), padded),
    )
    lo = np.asarray(lo_d)[:n]
    hi = np.asarray(hi_d)[:n]
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    cand_p = np.repeat(np.arange(n, dtype=np.int64), counts)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot = np.arange(total, dtype=np.int64) - np.repeat(offsets, counts) + np.repeat(lo, counts)
    cand_b = build.order[slot]
    keep = np.ones(total, dtype=bool)
    for pk, bk in zip(pkeys, bkeys):
        keep &= _pairs_equal(np.asarray(chunk[pk])[cand_p], build.batch[bk][cand_b])
    return cand_p[keep], cand_b[keep]


# --------------------------------------------------------------------------
# pair gather (single-pair-space sibling of device._expand_join_pairs:
# same null promotion, same USING coalesce)
# --------------------------------------------------------------------------


def _null_value(dt: np.dtype):
    if dt.kind == "M":
        return np.datetime64("NaT")
    if dt.kind == "m":
        return np.timedelta64("NaT")
    return np.nan


def _out_dtype(base: np.dtype, nullable: bool) -> np.dtype:
    if nullable and base.kind == "b":
        return np.dtype(object)  # pandas merge: bool + NULL -> object
    if nullable and base.kind in ("i", "u"):
        return np.dtype(np.float64)  # pandas-merge null promotion
    return base


def _gather_pairs(
    out_names: List[str],
    sources: Dict[str, Tuple[bool, str]],
    lbatch: Optional[B.Batch],
    rbatch: Optional[B.Batch],
    lidx: np.ndarray,
    ridx: np.ndarray,
    coalesce_from: Dict[str, str],
    fallback_dtypes: Dict[str, np.dtype],
) -> B.Batch:
    nrows = int(lidx.shape[0])
    out: B.Batch = {}
    for name in out_names:
        is_left, col = sources[name]
        src = lbatch if is_left else rbatch
        idx = lidx if is_left else ridx
        arr = None
        if src is not None and col in src:
            arr = np.asarray(src[col])
        if arr is None or arr.shape[0] == 0:
            base = arr.dtype if arr is not None else fallback_dtypes.get(name)
            if base is None:
                raise DeviceUnsupported(f"no dtype for empty join column {name!r}")
            dt = _out_dtype(base, True)
            vals = np.full(nrows, _null_value(dt), dtype=dt)
            nulls = np.ones(nrows, dtype=bool)
        else:
            nulls = idx < 0
            dt = _out_dtype(arr.dtype, bool(nulls.any()))
            if nulls.any():
                vals = np.empty(nrows, dtype=dt)
                vals[:] = arr[np.clip(idx, 0, arr.shape[0] - 1)].astype(dt, copy=False)
                vals[nulls] = _null_value(dt)
            else:
                vals = arr[idx]
                if vals.dtype != dt:
                    vals = vals.astype(dt)
        alt = coalesce_from.get(name) if is_left else None
        if alt is not None and nulls.any() and rbatch is not None and alt in rbatch:
            # left-null rows from right-unmatched emissions: the USING key
            # shows the RIGHT side's value (Spark coalesce semantics)
            ralt = np.asarray(rbatch[alt])
            fill = np.asarray(ridx)[nulls]
            ok = fill >= 0
            if ralt.shape[0] and ok.any():
                sel = np.nonzero(nulls)[0][ok]
                vals[sel] = ralt[fill[ok]].astype(vals.dtype, copy=False)
        out[name] = vals
    return out


# --------------------------------------------------------------------------
# fused post-join filter
# --------------------------------------------------------------------------

_POSTJOIN_CACHE: "OrderedDict[tuple, object]" = OrderedDict()
_POSTJOIN_CACHE_MAX = 64


def _postjoin_program(cache_key, refs: List[str], ref_on_probe: Dict[str, bool], fn):
    import jax

    jitted = _POSTJOIN_CACHE.get(cache_key)
    if jitted is None:
        while len(_POSTJOIN_CACHE) >= _POSTJOIN_CACHE_MAX:
            _POSTJOIN_CACHE.popitem(last=False)

        def prog(pcols, bcols, pidx, bidx, lits):
            cols = {}
            for name in refs:
                if ref_on_probe[name]:
                    cols[name] = pcols[name][pidx]
                else:
                    cols[name] = bcols[name][bidx]
            return fn(cols, lits)

        jitted = jax.jit(prog)
        _POSTJOIN_CACHE[cache_key] = jitted
    else:
        _POSTJOIN_CACHE.move_to_end(cache_key)
    return jitted


def _device_postjoin_mask(session, condition, pbatch: B.Batch, build: BuildSide,
                          pidx: np.ndarray, bidx: np.ndarray,
                          refs: List[str], sources, probe_is_left: bool) -> np.ndarray:
    """Predicate over matched pairs as ONE device program: gather each
    referenced column by its pair indices, then the compiled predicate —
    payload never round-trips through host numpy for the filtered-out rows.
    Raises DeviceUnsupported outside the device expression language."""
    codecs = {}
    pcols: Dict[str, np.ndarray] = {}
    bcols: Dict[str, np.ndarray] = {}
    ref_on_probe: Dict[str, bool] = {}
    for name in refs:
        is_left, col = sources[name]
        on_probe = is_left == probe_is_left
        ref_on_probe[name] = on_probe
        if on_probe:
            enc, codec = encode_column(np.asarray(pbatch[col]))
            pcols[name] = _pad_plane(enc, enc[0] if enc.shape[0] else 0)
        else:
            got = build.enc.get(col)
            if got is None:
                got = encode_column(build.batch[col])
                build.enc[col] = got
            enc, codec = got
            bcols[name] = enc
        codecs[name] = codec
    fn, lits = compile_predicate(condition, codecs)
    skeleton = predicate_skeleton(condition, codecs)
    sides = tuple(sorted(ref_on_probe.items()))
    n = int(pidx.shape[0])
    pidx_pad = _pad_plane(pidx, 0)
    bidx_pad = _pad_plane(bidx, 0)
    jitted = _postjoin_program((skeleton, sides), list(refs), ref_on_probe, fn)
    args = (pcols, bcols, pidx_pad, bidx_pad, tuple(lits))
    sig = (skeleton, sides, pidx_pad.shape[0])
    _note_compile("fused-postjoin", sig)
    _hlo_lint.maybe_verify(
        session.conf, "fused-postjoin",
        _program_key(f"fused-postjoin/{hash(sig)}", session.mesh), jitted, args,
    )
    mask = jitted(*args)
    from hyperspace_tpu.exec import stage_ir as _stage_ir

    _stage_ir.count_dispatch("fused-postjoin")
    return np.asarray(mask)[:n]


# --------------------------------------------------------------------------
# the stream
# --------------------------------------------------------------------------


def stream_broadcast_join(executor, plan: L.Join, spec: Optional[BroadcastSpec] = None,
                          post_filter=None, project: Optional[List[str]] = None):
    """Yield the broadcast hash join's output one probe chunk at a time.

    ``post_filter`` (a Filter condition directly above the join) fuses into
    the chunk walk; ``project`` restricts the gathered output columns. Both
    together make Filter->Project over a Join a streaming, fused shape.
    Raises DeviceUnsupported BEFORE the first yield when the join can't take
    this path (callers then fall back with nothing consumed).
    """
    ensure_x64()
    session = executor.session
    if spec is None:
        spec = broadcast_spec(session, plan)
    if spec is None:
        raise DeviceUnsupported("join has no broadcastable side")

    build_plan = plan.left if spec.build_is_left else plan.right
    probe_plan = plan.right if spec.build_is_left else plan.left
    bkeys = spec.lkeys if spec.build_is_left else spec.rkeys
    pkeys = spec.rkeys if spec.build_is_left else spec.lkeys
    probe_is_left = not spec.build_is_left
    how = plan.how
    keep_probe = how in (("left", "outer") if probe_is_left else ("right", "outer"))
    keep_build = how in (("left", "outer") if spec.build_is_left else ("right", "outer"))

    out_names = list(project) if project is not None else list(plan.output_columns)
    lout = plan.left.output_columns
    rout = plan.right.output_columns
    refs = sorted(post_filter.references()) if post_filter is not None else []
    sources = {
        name: _join_column_source(name, lout, rout)
        for name in dict.fromkeys(out_names + refs)
    }
    coalesce_from: Dict[str, str] = {}
    if how in ("right", "outer") and plan.using_pairs:
        for lk, rk in plan.using_pairs:
            if lk in out_names and rk in rout:
                coalesce_from[lk] = rk

    bset = set(plan.left.output_columns if spec.build_is_left else plan.right.output_columns)
    pset = set(probe_plan.output_columns)
    need_b = {c for (il, c) in sources.values() if il == spec.build_is_left and c in bset}
    need_p = {c for (il, c) in sources.values() if il == probe_is_left and c in pset}
    build_cols = [c for c in (plan.left if spec.build_is_left else plan.right).output_columns
                  if c in need_b or c in bkeys]
    probe_cols = [c for c in probe_plan.output_columns if c in need_p or c in pkeys]

    build = _shared_build_side(session, build_plan, build_cols, bkeys)
    _count_broadcast()
    trace.record("join", "broadcast-hash-stream")

    probe_exec = probe_plan
    if set(probe_cols) != set(probe_plan.output_columns):
        probe_exec = L.Project(probe_cols, probe_plan)

    from hyperspace_tpu.exec.device import _count_join_stream_chunk
    from hyperspace_tpu.exec.executor import Executor

    matched_build = np.zeros(build.n, dtype=bool) if keep_build else None
    probe_dtypes: Dict[str, np.dtype] = {}
    empty64 = np.empty(0, dtype=np.int64)

    def orient(p_i, b_i):
        return (p_i, b_i) if probe_is_left else (b_i, p_i)

    def pair_fallback_dtypes(pbatch: Optional[B.Batch]) -> Dict[str, np.dtype]:
        fb: Dict[str, np.dtype] = {}
        for name, (is_left, col) in sources.items():
            if is_left == probe_is_left and pbatch is None and col in probe_dtypes:
                fb[name] = probe_dtypes[col]
        return fb

    def filter_pairs(pbatch: Optional[B.Batch], p_i: np.ndarray, b_i: np.ndarray):
        if post_filter is None or p_i.shape[0] == 0:
            return p_i, b_i
        mask = None
        if (
            session.conf.device_execution_enabled
            and pbatch is not None
            and bool((p_i >= 0).all())
            and bool((b_i >= 0).all())
        ):
            try:
                mask = _device_postjoin_mask(
                    session, post_filter, pbatch, build, p_i, b_i,
                    refs, sources, probe_is_left,
                )
            except DeviceUnsupported:
                trace.fallback("join", "postjoin_device")
                mask = None
        if mask is None:
            lidx, ridx = orient(p_i, b_i)
            lb, rb = (pbatch, build.batch) if probe_is_left else (build.batch, pbatch)
            refbatch = _gather_pairs(
                refs, sources, lb, rb, lidx, ridx, {}, pair_fallback_dtypes(pbatch)
            )
            raw = as_bool_mask(post_filter.eval(refbatch))
            mask = np.broadcast_to(np.asarray(raw, dtype=bool), (p_i.shape[0],))
        return p_i[mask], b_i[mask]

    def assemble(pbatch: Optional[B.Batch], p_i: np.ndarray, b_i: np.ndarray) -> B.Batch:
        lidx, ridx = orient(p_i, b_i)
        lb, rb = (pbatch, build.batch) if probe_is_left else (build.batch, pbatch)
        return _gather_pairs(
            out_names, sources, lb, rb, lidx, ridx, coalesce_from,
            pair_fallback_dtypes(pbatch),
        )

    yielded = False
    probe_iter = Executor(session).execute_stream(probe_exec)
    try:
        for chunk in probe_iter:
            chunk = {k: np.asarray(v) for k, v in chunk.items()}
            for c, a in chunk.items():
                probe_dtypes.setdefault(c, a.dtype)
            n = B.num_rows(chunk)
            if n == 0:
                continue
            p_i, b_i = _probe_chunk(session, build, chunk, pkeys, bkeys)
            if matched_build is not None and b_i.size:
                matched_build[b_i] = True
            if keep_probe:
                hit = np.zeros(n, dtype=bool)
                hit[p_i] = True
                miss = np.nonzero(~hit)[0]
                if miss.size:
                    p_i = np.concatenate([p_i, miss])
                    b_i = np.concatenate([b_i, np.full(miss.size, -1, dtype=np.int64)])
            p_i, b_i = filter_pairs(chunk, p_i, b_i)
            if p_i.shape[0] == 0:
                continue
            out = assemble(chunk, p_i, b_i)
            _count_join_stream_chunk()
            yielded = True
            yield out
    finally:
        probe_iter.close()

    if matched_build is not None:
        miss_b = np.nonzero(~matched_build)[0]
        if miss_b.size:
            if not probe_dtypes and any(
                il == probe_is_left for il, _ in sources.values()
            ):
                if yielded:  # can't abandon a started stream
                    raise RuntimeError("broadcast join lost probe dtypes mid-stream")
                raise DeviceUnsupported("probe side yielded no chunks to type NULL columns")
            p_i = np.full(miss_b.size, -1, dtype=np.int64)
            p_i, b_i = filter_pairs(None, p_i, miss_b.astype(np.int64))
            if p_i.shape[0]:
                out = assemble(None, p_i, b_i)
                _count_join_stream_chunk()
                yielded = True
                yield out

    if not yielded:
        # type an EMPTY result from the observed dtypes so callers never
        # fall back to a materialize-both-sides path for a no-match join
        if not probe_dtypes and any(il == probe_is_left for il, _ in sources.values()):
            raise DeviceUnsupported("probe side yielded no chunks to type an empty result")
        pb = {c: np.empty(0, dtype=dt) for c, dt in probe_dtypes.items()}
        yield assemble(pb, empty64, empty64)


def _build_identity(build_plan: L.LogicalPlan, build_cols: List[str], bkeys: List[str]):
    """Cache identity of a built hash table: the plan text (filters included)
    + every leaf file's (path, mtime, size) + columns + keys. None (= don't
    cache) when a leaf can't be stat'ed."""
    files = []
    for leaf in L.collect(
        build_plan, lambda p: isinstance(p, (L.Scan, L.FileScan, L.IndexScan))
    ):
        names = (
            [fi.name for fi in leaf.relation.all_file_infos()]
            if isinstance(leaf, L.Scan)
            else list(leaf.files)
        )
        for f in names:
            try:
                st = os.stat(f)
            except OSError:
                return None
            files.append((f, st.st_mtime_ns, st.st_size))
    return (build_plan.pretty(), tuple(files), tuple(build_cols), tuple(bkeys))


def _shared_build_side(session, build_plan, build_cols: List[str], bkeys: List[str]) -> BuildSide:
    """Build via the session's shared build cache when one is attached
    (QueryServer start()); outside serving every join builds privately."""
    cache = getattr(session, "join_build_cache", None)
    if cache is None:
        return build_hash_side(session, build_plan, build_cols, bkeys)
    key = _build_identity(build_plan, build_cols, bkeys)
    brand = None
    if key is not None:
        try:
            from hyperspace_tpu.serving.result_cache import version_brand

            brand = version_brand(session, build_plan, enabled=True)
        except Exception:
            brand = None
    if key is None or brand is None:
        return build_hash_side(session, build_plan, build_cols, bkeys)
    return cache.get_or_build(
        key, brand,
        lambda: build_hash_side(session, build_plan, build_cols, bkeys),
        lambda b: b.nbytes,
    )


def dispatch_broadcast_join(executor, plan: L.Join) -> B.Batch:
    """Materialized entry point (executor._exec_join's middle tier, between
    the bucketed SMJ and the generic pandas merge): fold the stream
    incrementally, closing the generator on any exit."""
    spec = broadcast_spec(executor.session, plan)
    if spec is None:
        raise DeviceUnsupported("join has no broadcastable side")
    gen = stream_broadcast_join(executor, plan, spec)
    merged = None
    merged_bytes = 0
    pending: List[B.Batch] = []
    pending_bytes = 0

    def nbytes(batch: B.Batch) -> int:
        return sum(int(np.asarray(a).nbytes) for a in batch.values())

    try:
        for chunk in gen:
            pending.append(chunk)
            pending_bytes += nbytes(chunk)
            if merged is None or pending_bytes >= merged_bytes:
                batches = ([merged] if merged is not None else []) + pending
                merged = batches[0] if len(batches) == 1 else B.concat(batches)
                merged_bytes = nbytes(merged)
                pending, pending_bytes = [], 0
    finally:
        gen.close()
    if pending:
        batches = ([merged] if merged is not None else []) + pending
        merged = batches[0] if len(batches) == 1 else B.concat(batches)
    if merged is None:  # the stream always yields >= 1 (possibly empty) chunk
        raise DeviceUnsupported("broadcast join produced no chunks")
    return merged
