"""Columnar batch: the unit of data flowing between physical operators.

A batch is a dict ``column name -> numpy array`` (object dtype for strings on
the host path). Device execution dictionary-encodes string columns into int32
codes so everything on TPU is dense numeric (see exec/device.py) — covering
indexes carry arbitrary included columns, and TPU has no native variable-length
type (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

Batch = Dict[str, np.ndarray]


class DictBackedArray(np.ndarray):
    """Object array of strings that remembers the dictionary encoding it was
    decoded from (parquet RLE_DICTIONARY via the native row-group reader).

    ``hs_dict_codes`` is an int32 array (-1 = null) indexing into
    ``hs_dict_uniques`` (object array, file order — NOT sorted). Host
    operators see a plain object array of str/None; the device staging path
    (exec/device.py) spots the attributes and ships the narrow codes +
    dictionary instead of bytes×rows, expanding on-device.

    Derived arrays (slices, masks, concat) intentionally do NOT inherit the
    attributes — numpy only propagates them through an __array_finalize__
    that copies, which we omit — so any reshaped view degrades to plain
    value semantics instead of carrying stale codes.
    """

    hs_dict_codes: Optional[np.ndarray] = None
    hs_dict_uniques: Optional[np.ndarray] = None


def dict_backed(values: np.ndarray, codes: np.ndarray, uniques: np.ndarray) -> DictBackedArray:
    arr = values.view(DictBackedArray)
    arr.hs_dict_codes = codes
    arr.hs_dict_uniques = uniques
    return arr


def table_to_batch(table: pa.Table) -> Batch:
    out: Batch = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except pa.ArrowInvalid:
            out[name] = np.asarray(col.to_pylist(), dtype=object)
    return out


def batch_to_table(batch: Batch, column_order: Optional[List[str]] = None) -> pa.Table:
    names = column_order if column_order is not None else list(batch)
    arrays = []
    for n in names:
        v = batch[n]
        if v.dtype == object or v.dtype.kind in ("U", "S"):
            arrays.append(pa.array([None if x is None else str(x) for x in v.tolist()], type=pa.string()))
        else:
            arrays.append(pa.array(v))
    return pa.table(dict(zip(names, arrays)))


def num_rows(batch: Batch) -> int:
    for v in batch.values():
        return len(v)
    return 0


def take(batch: Batch, indices: np.ndarray) -> Batch:
    return {k: v[indices] for k, v in batch.items()}


def mask_rows(batch: Batch, mask: np.ndarray) -> Batch:
    mask = np.asarray(mask)
    if mask.ndim == 0:
        # a scalar predicate (e.g. comparison against a NULL scalar subquery)
        # applies uniformly; 0-d boolean indexing would instead add an axis
        mask = np.broadcast_to(mask, (num_rows(batch),))
    return {k: v[mask] for k, v in batch.items()}


def concat(batches: List[Batch]) -> Batch:
    if not batches:
        return {}
    names = list(batches[0])
    return {n: np.concatenate([b[n] for b in batches]) for n in names}


def select(batch: Batch, columns: List[str]) -> Batch:
    from hyperspace_tpu.plan.expr import get_column

    out: Batch = {}
    for c in columns:
        got = batch[c] if c in batch else get_column(batch, c)
        if got is None:
            raise KeyError(f"Column {c!r} not found in batch with columns {list(batch)}")
        out[c] = got
    return out
