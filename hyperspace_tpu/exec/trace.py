"""Execution-dispatch trace: which physical path each operator actually took.

The reference approves a *simplified executedPlan* tree per TPC-DS query
(ref: goldstandard/PlanStabilitySuite.scala:83-290), so falling off a fast
path (bucketed SMJ -> generic merge, codegen -> interpreted) is a test
failure. This framework's physical dispatch is decided at runtime (device vs
host by row-count gates, native vs pyarrow decode per file, DeviceUnsupported
fallbacks), so the equivalent pin is a recorded trace: decision points call
:func:`record`, and the golden tests approve the counted summary alongside
the optimized plan.

Recording is off by default (one ``is None`` check per event) and
process-global, NOT thread-local: the parquet decode pool's worker threads
must land their events in the caller's recording. One recording at a time;
list.append is atomic under the GIL. Enable with::

    with trace.recording() as events:
        q.collect()
    print(trace.summarize(events))
"""

from __future__ import annotations

import contextlib
import threading
from collections import Counter
from typing import Iterator, List, Optional

from hyperspace_tpu.obs import spans as _spans

_events: Optional[List] = None

# QueryServers currently running in this process. A process-global recording
# under concurrent serving would interleave events from unrelated requests —
# recording() refuses to start instead (the obs span tracer is the
# per-request surface; see docs/observability.md).
_servers_running = 0
_servers_lock = threading.Lock()


def server_started() -> None:
    global _servers_running
    with _servers_lock:
        _servers_running += 1


def server_stopped() -> None:
    global _servers_running
    with _servers_lock:
        _servers_running = max(0, _servers_running - 1)


def record(kind: str, detail: str) -> None:
    """Append a dispatch event (e.g. ``record("join", "device-smj")``) to the
    active recorder, if any — and annotate the context's current obs span, so
    dispatch decisions land inside the per-request span tree too."""
    events = _events
    if events is not None:
        events.append((kind, detail))
    sp = _spans.current_span()
    if sp is not None:
        sp.event(kind, detail)


def fallback(op: str, reason: str) -> None:
    """Count a device-path fallback in the process metrics registry.

    Dispatch traces already *name* every fallback, but a recording must be
    active to see them; the ``hs_device_fallback_total{op,reason}`` counter
    makes the same decisions visible in Prometheus scrapes and query
    profiles without one.
    """
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_device_fallback_total",
        "Device-path fallbacks to host execution, by operator and reason",
        op=op,
        reason=reason,
    ).inc()


def active() -> bool:
    return _events is not None


@contextlib.contextmanager
def recording() -> Iterator[List]:
    """Collect dispatch events for the duration of the block.

    Raises ``RuntimeError`` while a ``QueryServer`` is running: this recorder
    is process-global, so it would interleave events from every concurrent
    request. Use span traces (``hyperspace.obs.tracing.enabled`` + per-request
    profiles) under serving instead.
    """
    global _events
    with _servers_lock:
        if _servers_running:
            raise RuntimeError(
                "exec.trace.recording() is process-global and cannot run while "
                f"{_servers_running} QueryServer(s) are serving concurrent "
                "requests; use obs span tracing (hyperspace.obs.tracing.enabled) "
                "for per-request dispatch visibility"
            )
    prev = _events
    _events = []
    try:
        yield _events
    finally:
        _events = prev


def summarize(events: List) -> str:
    """Stable text form for goldens: one ``kind: detail xN`` line per distinct
    event, sorted."""
    counts = Counter(events)
    lines = [f"{kind}: {detail} x{n}" for (kind, detail), n in sorted(counts.items())]
    return "\n".join(lines) if lines else "(no dispatch events)"


def summarize_span_events(root) -> str:
    """Dispatch summary of one finished span tree: the same counted form
    :func:`summarize` produces for a recording, but sourced from the
    per-request events :func:`record` annotated onto obs spans. This is how
    the slow-query flight recorder shows "which physical paths this request
    took" without a process-global recording."""
    events: List = []
    for sp in root.walk():
        events.extend(sp.events)
    return summarize(events)
