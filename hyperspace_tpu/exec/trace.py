"""Execution-dispatch trace: which physical path each operator actually took.

The reference approves a *simplified executedPlan* tree per TPC-DS query
(ref: goldstandard/PlanStabilitySuite.scala:83-290), so falling off a fast
path (bucketed SMJ -> generic merge, codegen -> interpreted) is a test
failure. This framework's physical dispatch is decided at runtime (device vs
host by row-count gates, native vs pyarrow decode per file, DeviceUnsupported
fallbacks), so the equivalent pin is a recorded trace: decision points call
:func:`record`, and the golden tests approve the counted summary alongside
the optimized plan.

Recording is off by default (one ``is None`` check per event) and
process-global, NOT thread-local: the parquet decode pool's worker threads
must land their events in the caller's recording. One recording at a time;
list.append is atomic under the GIL. Enable with::

    with trace.recording() as events:
        q.collect()
    print(trace.summarize(events))
"""

from __future__ import annotations

import contextlib
from collections import Counter
from typing import Iterator, List, Optional

_events: Optional[List] = None


def record(kind: str, detail: str) -> None:
    """Append a dispatch event (e.g. ``record("join", "device-smj")``) to the
    active recorder, if any."""
    events = _events
    if events is not None:
        events.append((kind, detail))


def active() -> bool:
    return _events is not None


@contextlib.contextmanager
def recording() -> Iterator[List]:
    """Collect dispatch events for the duration of the block."""
    global _events
    prev = _events
    _events = []
    try:
        yield _events
    finally:
        _events = prev


def summarize(events: List) -> str:
    """Stable text form for goldens: one ``kind: detail xN`` line per distinct
    event, sorted."""
    counts = Counter(events)
    lines = [f"{kind}: {detail} x{n}" for (kind, detail), n in sorted(counts.items())]
    return "\n".join(lines) if lines else "(no dispatch events)"
