"""Streaming device top-k: ORDER BY ... LIMIT k without materialization.

``TopKStream`` is the order-by analog of device.GroupedAggStream: the
executor folds one *executed chain batch* per file group into a
device-resident candidate buffer, so an ``ORDER BY ... LIMIT k`` over a
multi-chunk scan never materializes more than one chunk plus ``O(cap)``
candidate rows on the host.

Per chunk the stream

  1. encodes every ORDER BY key into a signed-order int64 plane
     (ops/encode.order_plane — NULLS LAST, stable-tie semantics identical to
     executor._key_codes) plus a global-row-id plane that doubles as the
     stable tiebreak,
  2. runs the fused select-top-k program (ops/sort.topk_chunk_fn) over the
     padded plane matrix — one compile per (key count, capacity, shape
     bucket) via the (skeleton, mesh fingerprint) program cache,
  3. merges the chunk's candidates into the running buffer with the
     collective-free pairwise merge (ops/sort.topk_merge_fn), and
  4. keeps only the candidate *rows* on the host, pruned to the buffer after
     every merge.

String planes are chunk-local dense ranks, so whenever a string key is
present the merge re-encodes both candidate sets over their combined raw
values host-side (the ``_remap_string_key`` analog) — ``O(cap)`` work, never
``O(rows)``.

The running k-th candidate's primary-key value is exposed as a conservative
``threshold_condition()`` predicate (``col <= v`` ascending, ``>=``
descending) that the executor pushes into row-group min/max pruning for
not-yet-decoded chunks — the dynamic-filter feedback loop of the tentpole.

With a ``ShardedExecutor`` the chunk select runs as a shard_map program:
per-shard top-k, then EXACTLY one fixed-size all_gather of candidate planes
(never payload rows) under the registered ``sharded-topk`` HLO contract.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import device as D
from hyperspace_tpu.obs.metrics import REGISTRY
from hyperspace_tpu.ops.encode import ORDER_PLANE_SENTINEL, order_plane

_SENT = np.int64(ORDER_PLANE_SENTINEL)

_STRING_KINDS = ("U", "S", "O")


def _chunks_total():
    return REGISTRY.counter(
        "hs_topk_chunks_total",
        "Chunks folded into streaming device top-k candidate buffers",
    )


def _merges_total():
    return REGISTRY.counter(
        "hs_topk_merges_total",
        "Pairwise candidate-buffer merges run by streaming device top-k",
    )


def _threshold_updates_total():
    return REGISTRY.counter(
        "hs_topk_threshold_updates_total",
        "Dynamic k-th-value threshold updates fed back into row-group pruning",
    )


def _merge_seconds_total():
    return REGISTRY.counter(
        "hs_topk_merge_seconds_total",
        "Wall seconds spent in top-k candidate encode/select/merge steps",
    )


def _is_missing_scalar(v) -> bool:
    if v is None:
        return True
    try:
        if isinstance(v, float) and v != v:
            return True
        if isinstance(v, np.floating) and np.isnan(v):
            return True
        if isinstance(v, np.datetime64) and np.isnat(v):
            return True
    except (TypeError, ValueError):
        return False
    return False


class TopKStream:
    """Device-resident streaming top-k fold over executed chunk batches.

    The candidate buffer is a ``(num_keys + 1, cap)`` int64 device matrix
    (one order plane per key + the global row-id plane); the matching raw
    rows live host-side in ``_pool``, always stored best-first so the k-th
    candidate (the threshold row) is ``_pool[...][k - 1]``.
    """

    def __init__(self, session, keys: Sequence[Tuple[str, bool]], k: int, parallel=None):
        self.session = session
        self.keys: List[Tuple[str, bool]] = [(str(c), bool(a)) for c, a in keys]
        self.k = int(k)
        self.cap = D.topk_capacity(self.k)
        self.parallel = parallel
        self.mesh = parallel.mesh if parallel is not None else session.mesh
        self.rows_seen = 0          # global row-id base for the next chunk
        self.chunks = 0
        self._state = None          # (K+1, cap) device candidate matrix
        self._order: Optional[np.ndarray] = None  # candidate rids, best-first
        self._pool: Optional[B.Batch] = None      # candidate rows, best-first
        self._string_keys: Optional[List[bool]] = None
        self._threshold = None      # raw primary-key value of the k-th candidate

    # -- public state ---------------------------------------------------------

    @property
    def has_data(self) -> bool:
        return self._pool is not None and self._order is not None and self._order.size > 0

    def threshold_condition(self):
        """Conservative ``primary_key <= v`` (ascending; ``>=`` descending)
        predicate over the current k-th candidate, or None before the buffer
        holds k definite candidates. Safe as a row-group pruning filter for
        chunks not yet folded: rows it rejects cannot enter the final top-k."""
        if self._threshold is None:
            return None
        from hyperspace_tpu.plan.expr import BinaryOp, Col, Lit

        name, asc = self.keys[0]
        return BinaryOp("<=" if asc else ">=", Col(name), Lit(self._threshold))

    def pool_rows_with_rid(self, rid_column: str) -> B.Batch:
        """Current candidate rows plus their global row ids, for the host
        fallback path: the pool is a superset of the top-k of every row the
        stream has folded, so (pool + remaining chunks) re-sorted on host is
        byte-identical to sorting the full input."""
        out = {c: np.asarray(v) for c, v in (self._pool or {}).items()}
        out[rid_column] = np.asarray(self._order, dtype=np.int64)
        return out

    # -- fold -----------------------------------------------------------------

    def update(self, batch: B.Batch) -> None:
        """Fold one executed chunk batch into the candidate buffer.

        Raises DeviceUnsupported (key column missing / unsupported dtype) —
        the caller switches to the host candidate-fallback mid-stream."""
        n = B.num_rows(batch)
        if n == 0:
            return
        t0 = time.perf_counter()
        from hyperspace_tpu.plan.expr import get_column

        key_arrays = []
        for c, _ in self.keys:
            arr = get_column(batch, c)
            if arr is None:
                raise D.DeviceUnsupported(f"sort key {c!r} missing from chunk batch")
            key_arrays.append(np.asarray(arr))
        try:
            planes = [order_plane(a, asc) for a, (_, asc) in zip(key_arrays, self.keys)]
        except TypeError as e:
            raise D.DeviceUnsupported(str(e))
        if self._string_keys is None:
            self._string_keys = [a.dtype.kind in _STRING_KINDS for a in key_arrays]

        base = self.rows_seen
        self.rows_seen += n
        rid = base + np.arange(n, dtype=np.int64)

        from hyperspace_tpu.exec import stage_ir as _stage_ir

        # whole-stage fold: chunk select + state merge in one dispatch, the
        # candidate state donated. String keys stay per-family (their merge
        # needs the host re-encode between select and merge).
        use_fused = (
            self._state is not None
            and not any(self._string_keys)
            and _stage_ir.fusion_wanted(self.session.conf)
        )
        if use_fused:
            merged, cand = self._run_fused(planes + [rid])
        else:
            cand = self._run_chunk(planes + [rid])
        crid = np.asarray(cand[-1])
        valid = crid < _SENT
        add_rid = crid[valid]
        local = (add_rid - base).astype(np.int64)
        add_pool: B.Batch = {c: np.asarray(v)[local] for c, v in batch.items()}

        if use_fused:
            pool_all = B.concat([self._pool, add_pool]) if self._pool else add_pool
            rid_all = (
                np.concatenate([self._order, add_rid])
                if self._order is not None else add_rid
            )
            self._state = merged
            _merges_total().inc()
            mrid = np.asarray(merged[-1])
            merged_rid = mrid[mrid < _SENT]
        elif self._state is None:
            self._state = cand
            merged_rid = add_rid
            pool_all, rid_all = add_pool, add_rid
        else:
            merged_rid, pool_all, rid_all = self._merge(cand, add_pool, add_rid)
        # prune the host pool to the merged candidates, stored best-first
        srt = np.argsort(rid_all, kind="stable")
        pos = srt[np.searchsorted(rid_all[srt], merged_rid)]
        self._order = merged_rid
        self._pool = {c: np.asarray(v)[pos] for c, v in pool_all.items()}

        self.chunks += 1
        _chunks_total().inc()
        self._update_threshold()
        _merge_seconds_total().inc(time.perf_counter() - t0)

    def _run_chunk(self, mat_rows: List[np.ndarray]):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hyperspace_tpu.check import hlo_lint as _hlo_lint
        from hyperspace_tpu.ops import sort as S

        mesh = self.mesh
        n_dev = mesh.devices.size
        nk = len(self.keys)
        padded = [D._pad_to_bucket(r, n_dev, _SENT) for r in mat_rows]
        mat = np.stack(padded)  # (K+1, P), P a √2 shape bucket
        axis = mesh.axis_names[0]
        sharding = NamedSharding(mesh, P(None, axis))
        dev = jax.device_put(mat, sharding)

        if self.parallel is not None:
            from hyperspace_tpu.parallel import collectives as C

            fn = C.sharded_topk_chunk_program(mesh, axis, nk, self.cap)
            family = "sharded-topk"
            self.parallel.note_op("topk")
        else:
            fn = S.topk_chunk_fn(nk, self.cap)
            family = "topk-chunk"
        key = D._program_key(f"topk[{nk}:{self.cap}]", mesh, sharded=self.parallel is not None)
        jitted = D._cached_predicate_jit(key, fn)
        D._note_compile(key, (mat.shape,))
        _hlo_lint.maybe_verify(self.session.conf, family, key, jitted, (dev,))
        out = jitted(dev)
        from hyperspace_tpu.exec import stage_ir as _stage_ir

        _stage_ir.count_dispatch(family)
        return out

    def _run_fused(self, mat_rows: List[np.ndarray]):
        """One-dispatch whole-stage fold: the chunk's select-top-k and the
        merge with the running candidate state as a single program, state
        donated (``hyperspace.exec.fusion.donation``). Returns
        ``(merged, cand)``; the caller MUST rebind ``self._state`` to
        ``merged`` before touching the old state again."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hyperspace_tpu.check import hlo_lint as _hlo_lint
        from hyperspace_tpu.exec import stage_ir as _stage_ir
        from hyperspace_tpu.ops import sort as S

        mesh = self.mesh
        n_dev = mesh.devices.size
        nk = len(self.keys)
        padded = [D._pad_to_bucket(r, n_dev, _SENT) for r in mat_rows]
        mat = np.stack(padded)
        axis = mesh.axis_names[0]
        dev = jax.device_put(mat, NamedSharding(mesh, P(None, axis)))

        sharded = self.parallel is not None
        # donation stays off under shard_map (same stance as the grouped
        # fold: replicated-state aliasing there is not reliably honored)
        donate = _stage_ir.donation_wanted(self.session.conf) and not sharded
        if sharded:
            from hyperspace_tpu.parallel import collectives as C

            fn = C.sharded_fused_topk_program(mesh, axis, nk, self.cap)
            family = "fused-stage-topk-sharded"
            self.parallel.note_op("topk")
        else:
            fn = S.fused_topk_fn(nk, self.cap)
            family = "fused-stage-topk"
        plan = _stage_ir.StagePlan((_stage_ir.TopKOp(nk, self.cap),))
        key = D._program_key(
            f"{plan.skeleton()}{'+d' if donate else ''}", mesh, sharded=sharded
        )
        jitted = _stage_ir.compile_stage(
            key, fn, donate_argnums=(0,) if donate else ()
        )
        D._note_compile(key, (mat.shape,))
        state = self._state
        _hlo_lint.maybe_verify(self.session.conf, family, key, jitted, (state, dev))
        merged, cand = jitted(state, dev)
        _stage_ir.count_dispatch(family)
        _stage_ir.note_peak_bytes()
        return merged, cand

    def _merge(self, cand, add_pool: B.Batch, add_rid: np.ndarray):
        """Merge the chunk's candidate matrix into the running buffer.

        Returns ``(merged_rid, pool_all, rid_all)`` where ``pool_all`` /
        ``rid_all`` concatenate the old pool with the chunk additions (the
        superset the merged rids index into)."""
        import jax

        from hyperspace_tpu.check import hlo_lint as _hlo_lint
        from hyperspace_tpu.ops import sort as S

        nk = len(self.keys)
        a, b = self._state, cand
        pool_all = B.concat([self._pool, add_pool]) if self._pool else add_pool
        rid_all = (
            np.concatenate([self._order, add_rid]) if self._order is not None else add_rid
        )
        if any(self._string_keys):
            # chunk-local string ranks are not comparable across chunks:
            # rebuild BOTH candidate matrices from raw pooled values over one
            # combined encoding (O(cap) host work) before the device merge
            a, b = self._rebuild_matrices(add_pool, add_rid)
            a, b = jax.device_put(a), jax.device_put(b)
        mkey = D._program_key(f"topkmerge[{nk}:{self.cap}]", self.mesh, sharded=False)
        mjit = D._cached_predicate_jit(mkey, S.topk_merge_fn(nk, self.cap))
        D._note_compile(mkey, ((nk + 1, self.cap),))
        _hlo_lint.maybe_verify(self.session.conf, "topk-merge", mkey, mjit, (a, b))
        merged = mjit(a, b)
        from hyperspace_tpu.exec import stage_ir as _stage_ir

        _stage_ir.count_dispatch("topk-merge")
        self._state = merged
        _merges_total().inc()
        mrid = np.asarray(merged[-1])
        return mrid[mrid < _SENT], pool_all, rid_all

    def _rebuild_matrices(self, add_pool: B.Batch, add_rid: np.ndarray):
        """Host-rebuilt (K+1, cap) plane matrices for both merge sides, with
        every key plane re-encoded over the combined raw values so string
        ranks (and every other plane, trivially) are mutually comparable."""
        n_a = int(self._order.size)
        mats = []
        sides = [
            ({c: np.asarray(v) for c, v in self._pool.items()}, self._order),
            (add_pool, add_rid),
        ]
        planes_ab: List[List[np.ndarray]] = [[], []]
        for c, asc in self.keys:
            both = np.concatenate(
                [np.asarray(sides[0][0][c]), np.asarray(sides[1][0][c])]
            )
            pl = order_plane(both, asc)
            planes_ab[0].append(pl[:n_a])
            planes_ab[1].append(pl[n_a:])
        for (pool, rid), planes in zip(sides, planes_ab):
            rows = [
                np.concatenate([p, np.full(self.cap - p.shape[0], _SENT, dtype=np.int64)])
                if p.shape[0] < self.cap
                else p[: self.cap]
                for p in planes + [np.asarray(rid, dtype=np.int64)]
            ]
            mats.append(np.stack(rows))
        return mats[0], mats[1]

    def _update_threshold(self) -> None:
        if self._order is None or self._order.size < self.k:
            return
        name, _asc = self.keys[0]
        col = self._pool.get(name)
        if col is None:
            return
        v = np.asarray(col)[self.k - 1]
        if _is_missing_scalar(v):
            return
        if isinstance(v, np.generic) and v.dtype.kind not in ("M", "m"):
            v = v.item()
        if self._threshold is None or v != self._threshold:
            self._threshold = v
            _threshold_updates_total().inc()

    # -- result ---------------------------------------------------------------

    def finalize(self) -> Optional[B.Batch]:
        """The top-k rows, best-first — byte-identical to the host stable
        sort + slice (ties resolved by the row-id plane = original order)."""
        if not self.has_data:
            return None
        return {c: np.asarray(v)[: self.k] for c, v in self._pool.items()}
