"""Device-side lineage delete filtering for the hybrid-scan path.

When an index carries deleted source files, the rewritten plan filters the
index side with ``NOT (lineage_id IN deleted_ids)`` (rules/utils.py
``_hybrid_scan_plan``). The host evaluates that as a NumPy set-op per row
batch; this module replaces it with a fused device anti-semi-join: the
deleted-id list is sorted, padded and replicated, the lineage column is
row-sharded, and membership is a ``searchsorted`` lookup — the same
sorted-lookup machinery the bucketed SMJ span search uses
(exec/join_stream.py), fused into a single elementwise program.

Properties the HLO contract pins down (``lineage-antijoin``):

- **zero collectives** — the lookup is elementwise over the resident column
  shard against a replicated id table; GSPMD must not shuffle rows;
- inherits the global forbidden-op rules (no host callbacks, no bounded
  dynamic shapes).

The id table pads to a geometric bucket with an int64-max sentinel so the
program skeleton stays stable as deletes accumulate; correctness does not
rely on the sentinel (a ``pos < n_ids`` guard with the *live* id count rides
along as a traced scalar). The lineage column shares the device residency
cache with the predicate path — same ``(scan_key, column, mesh_fp)`` keys,
same codec format — so commit-driven purges cover it for free.

Fallbacks (unsupported dtype, missing column, device-disabled) are counted
by the caller as ``hs_device_fallback_total{op="lineage"}`` via
``exec.trace.fallback`` and the host NOT-IN oracle serves the batch.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from hyperspace_tpu.check import hlo_lint as _hlo_lint
from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec.device import (
    DeviceUnsupported,
    _cached_predicate_jit,
    _device_cache_get,
    _device_cache_put,
    _mesh_fp,
    _note_compile,
    _pad_to_bucket,
    _program_key,
    bucket_rows,
    encode_column,
    ensure_x64,
)

_hlo_lint.register_contract(
    "lineage-antijoin",
    collectives={},
    description="hybrid-scan delete filter: sorted-lookup anti-semi-join, shuffle-free",
)

#: sorted-ascending pad value for the replicated id table — strictly greater
#: than any real lineage file id, so padding preserves sort order and can
#: never report a false membership
_ID_SENTINEL = np.iinfo(np.int64).max

#: id tables are tiny relative to columns; a small geometric floor keeps the
#: number of distinct table shapes (and hence retraces) logarithmic in the
#: delete count without padding 3 ids to 4096
_ID_BUCKET_FLOOR = 64


def _antijoin_fn(col, ids, n_ids):
    import jax.numpy as jnp

    c = col.astype(jnp.int64)
    pos = jnp.searchsorted(ids, c)
    pos_c = jnp.clip(pos, 0, ids.shape[0] - 1)
    found = (pos < n_ids) & (jnp.take(ids, pos_c) == c)
    return ~found  # keep-mask: True for rows NOT in the deleted set


def lineage_delete_mask(
    session,
    batch: B.Batch,
    column: str,
    deleted_ids,
    scan_key=None,
    parallel=None,
) -> np.ndarray:
    """Keep-mask for ``NOT (column IN deleted_ids)`` computed on device;
    byte-identical to the host NumPy oracle. Raises
    :class:`DeviceUnsupported` when the column is absent or non-integral —
    the caller falls back to the host path and counts the fallback."""
    ensure_x64()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if column not in batch:
        raise DeviceUnsupported(f"lineage column {column!r} missing from batch")
    n = B.num_rows(batch)
    if n == 0:
        return np.zeros(0, dtype=bool)
    col_np = batch[column]
    if col_np.dtype.kind not in ("i", "u"):
        raise DeviceUnsupported(f"lineage column dtype {col_np.dtype} is not integral")

    ids = np.unique(np.asarray(list(deleted_ids), dtype=np.int64))
    if ids.size == 0:
        return np.ones(n, dtype=bool)

    mesh = parallel.mesh if parallel is not None else session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    row_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    fp = _mesh_fp(mesh)

    # column residency: same key + value format as device_filter_mask, so
    # staging, predicate evaluation and lineage filtering share one entry
    ckey = (scan_key, column, fp) if scan_key is not None else None
    cached = _device_cache_get(ckey) if ckey is not None else None
    if cached is not None and cached[2] == n:
        dev_col = cached[0]
    else:
        arr, codec = encode_column(col_np)
        padded = _pad_to_bucket(arr, n_dev, 0)
        dev_col = jax.device_put(padded, row_sharding)
        if ckey is not None:
            _device_cache_put(ckey, (dev_col, codec, n), int(padded.nbytes))

    m = bucket_rows(int(ids.size), floor=_ID_BUCKET_FLOOR)
    ids_padded = np.full(m, _ID_SENTINEL, dtype=np.int64)
    ids_padded[: ids.size] = ids
    dev_ids = jax.device_put(ids_padded, replicated)
    n_ids = jax.device_put(np.int64(ids.size), replicated)

    key = _program_key("lineage-antijoin", mesh)
    jitted = _cached_predicate_jit(key, _antijoin_fn)
    _note_compile(key, (dev_col.shape, dev_ids.shape))
    _hlo_lint.maybe_verify(
        session.conf, "lineage-antijoin", key, jitted, (dev_col, dev_ids, n_ids)
    )
    mask = jitted(dev_col, dev_ids, n_ids)
    return np.asarray(mask)[:n]
