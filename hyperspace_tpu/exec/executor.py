"""Host-path physical executor.

Executes a (possibly index-rewritten) logical plan over pyarrow + numpy. This
is the correctness baseline and the non-indexed fallback; index-accelerated
scans and joins are dispatched to the TPU device path (exec/device.py) when a
session mesh is available.

The reference delegates all of this to Spark's physical planner/executors;
here the framework owns it (SURVEY.md §7 design stance).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow.dataset as pads

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs import spans
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    INPUT_FILE_NAME,
    Expr,
    InputFileName,
    as_bool_mask,
    extract_equi_join_keys,
)


#: synthetic global-row-id column carried by the top-k host fallback
_TOPK_RID = "__hs_topk_rid__"


def _scan_identity(scan):
    """Stable identity of a scan's file set for device-side caching: any
    rewrite of a file (new index version, compaction) changes mtime/size and
    naturally invalidates. Returns None (= don't cache) when any file can't
    be stat'ed — a path-only key could serve stale device columns after an
    in-place rewrite."""
    import os

    parts = []
    for f in scan.files:
        try:
            st = os.stat(f)
        except OSError:
            return None
        parts.append((f, st.st_mtime_ns, st.st_size))
    return tuple(parts)


def _maybe_parallel(session, n_rows: Optional[int] = None):
    """The session's ``ShardedExecutor`` when ``hyperspace.parallel.enabled``
    is on (and, when a row count is known, the chunk clears
    ``hyperspace.parallel.minRows``); None routes to the single-device path."""
    if not session.conf.parallel_enabled:
        return None
    from hyperspace_tpu.parallel.executor import ShardedExecutor

    px = ShardedExecutor.maybe(session)
    if px is not None and n_rows is not None and not px.rows_ok(n_rows):
        return None
    return px


def _plan_needs_file_names(plan: L.LogicalPlan) -> bool:
    def expr_has(e: Expr) -> bool:
        if isinstance(e, InputFileName):
            return True
        return any(expr_has(c) for c in e.children())

    if isinstance(plan, L.Filter) and expr_has(plan.condition):
        return True
    return any(_plan_needs_file_names(c) for c in plan.children())


def _read_files(
    files: List[str],
    file_format: str,
    columns: Optional[List[str]],
    with_file_names: bool,
    partition_values: Optional[dict] = None,
    partition_dtypes: Optional[dict] = None,
    format_options: Optional[dict] = None,
    predicate=None,
) -> B.Batch:
    """Read ``files`` into one batch. ``partition_values`` ({file -> {col ->
    typed value}}) attaches hive-partition columns — constant per file, absent
    from the file bytes — to each file's rows. ``predicate`` (the scan's
    pushed-down filter, re-applied by the Filter above) enables parquet
    row-group min/max pruning in the reader."""
    from hyperspace_tpu.exec.io import _decode_pool, read_parquet_batch

    if not files:
        # every file pruned (e.g. data-skipping removed all of them): empty
        # batch with the requested columns; dtype-less object arrays compare
        # fine against any literal on zero rows
        cols = list(columns or [])
        if with_file_names:
            cols.append(INPUT_FILE_NAME)
        return {c: np.empty(0, dtype=object) for c in cols}

    part_cols = set()
    if partition_values:
        for v in partition_values.values():
            part_cols.update(v)

    file_columns = columns
    attach: Optional[List[str]] = None
    if part_cols:
        if columns is None:
            attach = sorted(part_cols)
        else:
            attach = [c for c in columns if c in part_cols]
            file_columns = [c for c in columns if c not in part_cols]

    def read_one(f: str) -> B.Batch:
        from hyperspace_tpu.sources import formats as F

        if file_columns is not None and not file_columns:
            # every requested column is a partition column: the file is never
            # decoded, but its row count still shapes the output
            b: B.Batch = {}
            n = F.count_rows(f, file_format, format_options)
        elif file_format == "parquet":
            b = read_parquet_batch([f], file_columns, predicate=predicate)
            n = B.num_rows(b)
        else:
            b = B.table_to_batch(F.read_table(f, file_format, file_columns, format_options))
            n = B.num_rows(b)
        if attach:
            from hyperspace_tpu.sources import partitions as P

            values = partition_values.get(f, {})
            for c in attach:
                dt = (partition_dtypes or {}).get(c, np.dtype(object))
                b[c] = P.column_array(values.get(c), dt, n)
        if with_file_names:
            b[INPUT_FILE_NAME] = np.full(B.num_rows(b), f, dtype=object)
        return b

    if with_file_names or attach:
        if len(files) > 1:
            # same fan-out as the plain-parquet path: per-file decode +
            # partition/file-name attachment are independent, and both the
            # native decoder and pyarrow release the GIL. spans.wrap carries
            # the caller's span context into the pool workers.
            from hyperspace_tpu.obs import spans

            return B.concat(list(_decode_pool().map(spans.wrap(read_one), files)))
        return B.concat([read_one(f) for f in files])
    if file_format == "parquet":
        return read_parquet_batch(list(files), columns, predicate=predicate)
    from hyperspace_tpu.sources import formats as F

    t = F.open_dataset(list(files), file_format, format_options).to_table(columns=columns)
    return B.table_to_batch(t)


def _prune_partitions(scan: L.Scan, condition) -> Optional[List[str]]:
    """Files of ``scan`` surviving the partition-column conjuncts of
    ``condition`` (None = no partitioning / nothing prunable)."""
    from hyperspace_tpu.plan.expr import split_conjunctive

    rel = scan.relation
    part_cols = set(getattr(rel, "partition_columns", []) or [])
    if not part_cols:
        return None
    terms = [t for t in split_conjunctive(condition) if set(t.references()) and set(t.references()) <= part_cols]
    if not terms:
        return None
    files = [fi.name for fi in rel.all_file_infos()]
    # vectorized: one "row" per file holding its partition values
    dtypes = getattr(rel, "partition_dtypes", {}) or {}
    from hyperspace_tpu.sources import partitions as P

    pvs = [rel.partition_values_for(f) for f in files]
    file_batch = {}
    for c in sorted(part_cols):
        dt = dtypes.get(c, np.dtype(object))
        vals = [pv.get(c) for pv in pvs]
        if dt == np.dtype(object):
            arr = np.empty(len(vals), dtype=object)
            arr[:] = vals
        else:
            arr = np.array([P.typed_value(None, dt) if v is None else v for v in vals], dtype=dt)
        file_batch[c] = arr
    mask = np.ones(len(files), dtype=bool)
    for t in terms:
        mask &= as_bool_mask(t.eval(file_batch))
    return [f for f, keep in zip(files, mask) if keep]


def _key_codes(arr: np.ndarray, asc: bool) -> np.ndarray:
    """Per-row int64 sort codes for one key column: rank by value (negated
    for descending), missing values (NaN/NaT/None) last in BOTH directions.
    The single ordering definition shared by the Sort node and windows."""
    n = arr.shape[0]
    if arr.dtype == object:
        missing = np.array(
            [v is None or (isinstance(v, float) and v != v) for v in arr], dtype=bool
        )
        conv = np.where(missing, "", arr.astype(str))
    elif arr.dtype.kind == "f":
        missing = np.isnan(arr)
        conv = np.where(missing, 0.0, arr)
    elif arr.dtype.kind == "M":
        missing = np.isnat(arr)
        fill = arr[~missing][0] if (~missing).any() else arr
        conv = np.where(missing, fill, arr)
    else:
        missing = np.zeros(n, dtype=bool)
        conv = arr
    _, codes = np.unique(conv, return_inverse=True)
    keyvals = (codes if asc else -codes).astype(np.int64)
    keyvals[missing] = np.iinfo(np.int64).max
    return keyvals


def _composite_codes(per_key: List[np.ndarray]) -> np.ndarray:
    """Collapse per-key int64 codes into one composite code per row (equal
    tuples share a code, ordering lexicographic)."""
    n = per_key[0].shape[0] if per_key else 0
    sort_order = np.lexsort(per_key[::-1])
    changed = np.zeros(n, dtype=bool)
    if n:
        changed[0] = False
        for kv in per_key:
            s = kv[sort_order]
            changed[1:] |= s[1:] != s[:-1]
    composite = np.cumsum(changed)
    out = np.empty(n, dtype=np.int64)
    out[sort_order] = composite
    return out


def _gather_spec(idx: np.ndarray):
    """Precompute the per-side gather inputs ONCE per join (the NaN mask and
    int cast are O(rows); recomputing them per payload column would waste
    exactly the work the slim merge saves): (direct_idx, None, None) for an
    all-matched int index, (shape, valid, ii) for a float index with NaN
    unmatched marks."""
    idx = np.asarray(idx)
    if idx.dtype.kind != "f":
        return (idx.astype(np.int64, copy=False), None, None)
    valid = ~np.isnan(idx)
    return (None, valid, idx[valid].astype(np.int64))


def _gather_with_missing(arr: np.ndarray, spec) -> np.ndarray:
    """Gather ``arr`` rows by a ``_gather_spec``; unmatched rows (pandas'
    outer merge marks them NaN) null-extend with the same dtype promotion
    pandas itself applies — ints to float64 NaN, bools to object, datetimes
    keep their unit with NaT."""
    direct, valid, ii = spec
    if direct is not None:
        return arr[direct]
    idx = valid  # shape source
    kind = arr.dtype.kind
    if kind in ("i", "u"):
        res = np.full(idx.shape, np.nan, dtype=np.float64)
        res[valid] = arr[ii].astype(np.float64)
    elif kind == "f":
        res = np.full(idx.shape, np.nan, dtype=arr.dtype)
        res[valid] = arr[ii]
    elif kind == "M":
        res = np.full(idx.shape, np.datetime64("NaT"), dtype=arr.dtype)
        res[valid] = arr[ii]
    elif kind == "m":
        res = np.full(idx.shape, np.timedelta64("NaT"), dtype=arr.dtype)
        res[valid] = arr[ii]
    else:  # strings/objects/bools null-extend as object NaN, like pandas
        res = np.full(idx.shape, np.nan, dtype=object)
        res[valid] = arr[ii]
    return res


def _order_codes(child: B.Batch, keys) -> np.ndarray:
    """One int64 composite code per row whose ordering equals the
    lexicographic (column, ascending) ordering — equal tuples share a code."""
    return _composite_codes([_key_codes(child[name], asc) for name, asc in keys])


def _window_column(child: B.Batch, spec, caches=None) -> np.ndarray:
    """Evaluate one window spec over the batch (pandas per-partition ops).
    ``caches`` memoizes partition ngroups and order codes across the sibling
    specs of one Window node (q47/q57 compute several windows over the same
    keys)."""
    import pandas as pd

    part_cache, codes_cache = caches if caches is not None else ({}, {})
    out_name, fn, arg, pcols, orders, cumulative = spec
    n = B.num_rows(child)
    # one int per row identifying its partition
    part = part_cache.get(tuple(pcols))
    if part is None:
        if pcols:
            part = pd.DataFrame({c: child[c] for c in pcols}).groupby(
                list(pcols), dropna=False, sort=False
            ).ngroup().to_numpy()
        else:
            part = np.zeros(n, dtype=np.int64)
        part_cache[tuple(pcols)] = part

    def order_codes():
        key = tuple(orders)
        got = codes_cache.get(key)
        if got is None:
            got = codes_cache[key] = _order_codes(child, orders)
        return got

    if fn in ("rank", "dense_rank", "row_number"):
        method = {"rank": "min", "dense_rank": "dense", "row_number": "first"}[fn]
        s = pd.Series(order_codes())
        return s.groupby(part).rank(method=method).astype(np.int64).to_numpy()

    pd_fn = {"sum": "sum", "min": "min", "max": "max", "avg": "mean", "count": "count"}[fn]
    vals = pd.Series(child[arg]) if arg is not None else pd.Series(np.ones(n, dtype=np.int64))
    if cumulative and orders:
        # explicit ROWS UNBOUNDED PRECEDING .. CURRENT ROW
        codes = order_codes()
        pos = np.lexsort((np.arange(n), part, codes))
        inv = np.empty(n, dtype=np.int64)
        inv[pos] = np.arange(n)
        sv = vals.iloc[pos].reset_index(drop=True)
        sp = part[pos]
        if fn == "count":
            cum = sv.notna().groupby(sp).cumsum()
        elif fn == "sum":
            # running sum skips NULLs (cumsum would leave NaN holes)
            cum = sv.fillna(0).groupby(sp).cumsum()
            all_null = (~sv.notna()).groupby(sp).cummin()  # NULL until a value
            cum[all_null.astype(bool)] = np.nan
        else:
            # expanding() emits rows grouped by partition: drop the group
            # level and sort back to sv's positional order before inverting
            cum = (
                sv.groupby(sp)
                .expanding()
                .agg(pd_fn)
                .reset_index(level=0, drop=True)
                .sort_index()
            )
        return np.asarray(cum)[inv]
    if fn == "count" and arg is None:
        return pd.Series(np.ones(n, dtype=np.int64)).groupby(part).transform("size").to_numpy()
    return vals.groupby(part).transform(pd_fn).to_numpy()


def _chain_to_scan(plan: L.LogicalPlan):
    """(wrappers, leaf) when ``plan`` is a chain of row-wise nodes
    (Project/Compute/Filter/Rename) over a single Scan/FileScan/IndexScan
    leaf — the shape the streaming executor can partition by files; (None,
    None) otherwise."""
    chain = []
    node = plan
    while isinstance(node, (L.Project, L.Compute, L.Filter, L.Rename)):
        chain.append(node)
        node = node.child
    if isinstance(node, (L.Scan, L.FileScan, L.IndexScan)):
        return chain, node
    return None, None


def _chain_needed_columns(chain, aggs=None, keys=None):
    """Source columns a scan chain references (roots of dotted paths
    included), for pruning the per-chunk scan."""
    needed = set()
    for node in chain:
        if isinstance(node, L.Project):
            needed |= set(node.columns)
        elif isinstance(node, L.Compute):
            for _, e in node.exprs:
                needed |= set(e.references())
        elif isinstance(node, L.Filter):
            needed |= set(node.condition.references())
        elif isinstance(node, L.Rename):
            needed |= set(node.mapping.keys())
    if aggs:
        needed |= {c for _, _, c in aggs if c is not None}
    if keys:
        needed |= set(keys)
    needed |= {n.split(".")[0] for n in needed if "." in n}
    return needed


def _chain_pushdown_condition(chain):
    """AND of the chain's Filter conditions that sit over only Projects —
    still expressed in source-column terms, so the scan's row-group pruning
    can evaluate them against file statistics. Compute/Rename rebind the
    namespace, so conditions above them don't push."""
    from hyperspace_tpu.plan.expr import BinaryOp

    cond = None
    for node in reversed(chain):  # leaf-most wrapper first
        if isinstance(node, L.Project):
            continue
        if isinstance(node, L.Filter):
            cond = node.condition if cond is None else BinaryOp("AND", cond, node.condition)
            continue
        break
    return cond


def _pruned_scan_key(key, pruned_by):
    """Brand a device-cache scan key with the predicate whose row-group
    pruning shaped the batch: two predicates can prune the same files to
    EQUAL row counts but DIFFERENT rows, and the device cache's (key, col,
    n_rows) check alone would alias them."""
    if key is None or pruned_by is None:
        return key
    return key + (("rg-pred", str(pruned_by)),)


def _rebuild_chain(chain, leaf: L.LogicalPlan) -> L.LogicalPlan:
    """Clone the row-wise wrappers over a replacement leaf (bottom-up)."""
    node = leaf
    for wrapper in reversed(chain):
        node = wrapper.with_children([node])
    return node


def _leaf_files(leaf: L.LogicalPlan) -> List[str]:
    if isinstance(leaf, L.Scan):
        return [fi.name for fi in leaf.relation.all_file_infos()]
    return list(leaf.files)


def _leaf_subset(leaf: L.LogicalPlan, files: List[str], needed=None) -> L.LogicalPlan:
    """A scan leaf over only ``files``; a relation-backed Scan becomes a
    FileScan carrying the relation's format/partition metadata (and pruned
    to ``needed`` columns — chunked decode pays per chunk, so decoding
    unreferenced columns would multiply the waste)."""
    import copy

    if isinstance(leaf, (L.FileScan, L.IndexScan)):
        clone = copy.copy(leaf)
        clone.files = list(files)
        return clone
    rel = leaf.relation
    cols = list(leaf.output_columns)
    if needed is not None:
        lowered = {n.lower() for n in needed}
        kept = [c for c in cols if c.lower() in lowered]
        cols = kept or cols
    pv = pd_ = None
    part_cols = list(getattr(rel, "partition_columns", []) or [])
    if part_cols:
        pv = {f: rel.partition_values_for(f) for f in files}
        dts = getattr(rel, "partition_dtypes", None)
        pd_ = dict(dts) if dts else None
    return L.FileScan(
        files,
        rel.physical_format,
        cols,
        partition_values=pv,
        partition_dtypes=pd_,
        format_options=getattr(rel, "options", None) or None,
    )


def _chunk_files_by_bytes(files: List[str], target_bytes: int) -> List[List[str]]:
    """Greedy size-bounded file groups (a single file above the target forms
    its own group)."""
    import os

    groups: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for f in files:
        try:
            sz = os.stat(f).st_size
        except OSError:
            sz = target_bytes  # unknown -> isolate conservatively
        if cur and cur_bytes + sz > target_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(f)
        cur_bytes += sz
    if cur:
        groups.append(cur)
    return groups


#: aggregate functions with a decomposable partial state (Spark's
#: partial/final split); distinct forms accumulate uniques (bounded by
#: distinct cardinality, not row count)
_STREAMABLE_AGGS = {
    "count", "sum", "min", "max", "avg", "stddev_samp",
    "count_distinct", "sum_distinct", "avg_distinct",
}


def host_aggregate(batch: B.Batch, keys: List[str], aggs) -> B.Batch:
    """The host pandas aggregate over an in-memory batch — the semantic
    reference every device/streamed aggregate path must reproduce byte-for-
    byte (NULL sums via min_count=1, dropna=False grouping, appearance-
    ordered groups via sort=False)."""
    import pandas as pd

    batch = {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
    n = B.num_rows(batch)

    def series(col_name: str) -> np.ndarray:
        from hyperspace_tpu.plan.expr import get_column

        got = batch.get(col_name)
        if got is None:
            got = get_column(batch, col_name)
        if got is None:
            raise KeyError(f"Aggregate input column {col_name!r} not found")
        return got

    _PD_FN = {"avg": "mean", "sum": "sum", "min": "min", "max": "max"}

    def _global_agg(fn: str, col_name: Optional[str]):
        if fn == "count":
            return n if col_name is None else int(pd.Series(series(col_name)).count())
        s = pd.Series(series(col_name))
        if fn == "count_distinct":
            return int(s.nunique(dropna=True))
        if fn in ("sum_distinct", "avg_distinct"):
            d = s.dropna().drop_duplicates()
            return d.sum(min_count=1) if fn == "sum_distinct" else d.mean()
        if fn == "stddev_samp":
            return s.std(ddof=1)
        if fn == "sum":
            # SQL: SUM over zero rows (or all NULLs) is NULL, not 0 —
            # pandas' min_count=0 default returns 0
            return s.sum(min_count=1)
        return getattr(s, _PD_FN[fn])()

    if not keys:
        out: B.Batch = {}
        for name, fn, col_name in aggs:
            out[name] = np.asarray([_global_agg(fn, col_name)])
        return out

    # object/string group keys factorize to int codes BEFORE entering the
    # frame: pandas' (Arrow-backed) string column construction was the
    # top cost of TPC-H q1's aggregate at sf=1 (0.6 s of 3.0 s), and the
    # groupby only needs key IDENTITY — real values map back at the end.
    # use_na_sentinel=False gives NaN its own code, matching dropna=False.
    key_uniques = {}
    frame_cols = {}
    agg_inputs = {c for _, _, c in aggs if c is not None}
    for k in keys:  # series(): dotted keys too
        arr = series(k)
        # a key that also feeds an aggregate (min(x) ... GROUP BY x)
        # must keep its real values — codes order by appearance
        if arr.dtype.kind in ("O", "U", "S") and k not in agg_inputs:
            codes, uniques = pd.factorize(arr, use_na_sentinel=False)
            frame_cols[k] = codes
            key_uniques[k] = uniques
        else:
            frame_cols[k] = arr
    for name, fn, col_name in aggs:
        if col_name is not None and col_name not in frame_cols:
            frame_cols[col_name] = series(col_name)
    df = pd.DataFrame(frame_cols)
    grouped = df.groupby(keys, dropna=False, sort=False)
    out = {}
    pieces = {}
    for name, fn, col_name in aggs:
        if fn == "count" and col_name is None:
            pieces[name] = grouped.size()
        elif fn == "count":
            pieces[name] = grouped[col_name].count()
        elif fn == "count_distinct":
            pieces[name] = grouped[col_name].nunique(dropna=True)
        elif fn == "sum_distinct":
            pieces[name] = grouped[col_name].agg(
                lambda s: s.dropna().drop_duplicates().sum(min_count=1)
            )
        elif fn == "avg_distinct":
            pieces[name] = grouped[col_name].agg(lambda s: s.dropna().drop_duplicates().mean())
        elif fn == "stddev_samp":
            pieces[name] = grouped[col_name].std(ddof=1)
        elif fn == "sum":
            # an all-NULL group must sum to NULL (SQL), not pandas' 0
            pieces[name] = grouped[col_name].sum(min_count=1)
        else:
            pieces[name] = getattr(grouped[col_name], _PD_FN[fn])()
    result = pd.DataFrame(pieces).reset_index()
    for k in keys:
        vals = result[k].to_numpy()
        uniq = key_uniques.get(k)
        out[k] = uniq[vals] if uniq is not None else vals
    for name, _, _ in aggs:
        out[name] = result[name].to_numpy()
    return out


def aggregate_batch(session, keys, aggs, batch: B.Batch) -> B.Batch:
    """Aggregate an already-materialized batch — the serving micro-batch
    path's final step. Grouped shapes try the device segment-reduction
    engine (``scan_key=None``: the batch is transient, nothing to cache);
    everything else, and every fallback, runs the host pandas path."""
    conf = session.conf
    keys = list(keys)
    aggs = list(aggs)
    if (
        keys
        and conf.device_execution_enabled
        and conf.agg_device_grouped_enabled
        and B.num_rows(batch) >= conf.device_exec_min_rows
    ):
        try:
            from hyperspace_tpu.exec import device as D
        except ImportError:
            D = None
        if D is not None:
            try:
                got = D.device_grouped_aggregate(
                    session,
                    batch,
                    None,
                    keys,
                    aggs,
                    scan_key=None,
                    max_groups=conf.agg_max_groups,
                    cap_floor=conf.agg_capacity_floor,
                    parallel=_maybe_parallel(session, B.num_rows(batch)),
                )
                trace.record("agg", "device-grouped-batch")
                return got
            except D.GroupCapacityExceeded:
                trace.fallback("agg", "spill")
            except D.DeviceUnsupported:
                trace.fallback("agg", "unsupported")
    return host_aggregate(batch, keys, aggs)


class Executor:
    def __init__(self, session):
        self.session = session

    def _prime_staging_pad(self) -> None:
        """Materialize the session mesh before the first scan decode so the
        native fast path pads its buffers to the device count up front
        (session._note_mesh -> io.set_staging_pad) — otherwise the first
        query's chunks decode with pad=1 and lose the zero-copy device_put
        handoff. A mesh failure must never kill a host-path query."""
        if self.session.conf.io_native_enabled:
            try:
                self.session.mesh
            except Exception:
                pass

    def execute(
        self,
        plan: L.LogicalPlan,
        required_columns: Optional[List[str]] = None,
        prepruned: bool = False,
    ) -> B.Batch:
        from hyperspace_tpu.plan.expr import subquery_scope

        self._prime_staging_pad()

        # execution-time column pruning for EVERY plan (Catalyst runs
        # ColumnPruning unconditionally; ApplyHyperspace only prunes plans
        # it rewrites, and hyperspace-off queries never saw it at all —
        # TPC-H q7 carried 48-column join intermediates for ~10 referenced
        # columns). The approved-plan goldens pin the rule-relevant
        # optimized plan, like the reference's NORMALIZED approvals, so the
        # mechanical Project-over-scan layer stays out of them; the
        # dispatch trace still records what actually runs. Fallback keeps
        # the never-break-a-query contract. ``prepruned`` lets the serving
        # plan cache skip this walk for templates pruned once at compile.
        if not prepruned:
            try:
                from hyperspace_tpu.rules.utils import prune_columns

                plan = prune_columns(plan)
            except Exception:  # pruning must never kill a query
                # visible in recorded dispatch traces (and so in the goldens):
                # a silent fallback here once hid a RecursionError that cost
                # 3x on every view-sharing query
                trace.record("prune", "fallback-unpruned")

        # sub-plans referenced more than once (a CTE used N times holds ONE
        # plan object) execute once per collect; only those roots memoize.
        # NOTE: joins served by the device bucketed-SMJ path decode their
        # sides from index files directly (with their own byte-capped
        # caches), so this memo pays off on the host execution paths
        from hyperspace_tpu.rules.utils import shared_subplan_ids

        self._shared = shared_subplan_ids(plan)
        self._memo: Dict[Tuple[int, bool], B.Batch] = {}
        try:
            with subquery_scope():  # each subquery runs once per execute
                with_file_names = _plan_needs_file_names(plan)
                batch = self._exec(plan, with_file_names)
        finally:
            self._memo = {}
            self._shared = set()
        if required_columns is not None:
            batch = B.select(batch, required_columns)
        elif INPUT_FILE_NAME in batch:
            batch = {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
        return batch

    def execute_stream(self, plan: L.LogicalPlan):
        """Yield result batches incrementally (DataFrame.to_local_iterator).

        Streamed shapes: a (Project over) compatible bucketed Join yields
        per-bucket chunks via the streaming SMJ; a row-wise chain over one
        scan yields per-file-group chunks. Everything else yields the one
        materialized batch — streaming is an execution strategy, never an
        API restriction (Spark's toLocalIterator contract)."""
        from hyperspace_tpu.plan.expr import subquery_scope
        from hyperspace_tpu.rules.utils import prune_columns, shared_subplan_ids

        self._prime_staging_pad()
        try:
            plan = prune_columns(plan)
        except Exception:
            trace.record("prune", "fallback-unpruned")
        self._shared = shared_subplan_ids(plan)
        self._memo = {}
        try:
            with subquery_scope():
                if _plan_needs_file_names(plan):
                    batch = self._exec(plan, True)
                    yield {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
                    return
                node = plan
                if isinstance(node, L.Limit):
                    gen = self._stream_limit_node(node)
                    if gen is not None:
                        yield from gen
                        return
                proj = None
                if isinstance(node, L.Project):
                    proj, node = list(node.columns), node.child
                # a Filter directly above a Join fuses into the streaming
                # join paths (per-chunk mask for the SMJ, jitted post-join
                # program for the broadcast probe) instead of forcing the
                # materialized shape
                post_filter = None
                if isinstance(node, L.Filter) and isinstance(node.child, L.Join):
                    post_filter, node = node.condition, node.child
                if isinstance(node, L.Join) and self.session.conf.device_execution_enabled:
                    try:
                        from hyperspace_tpu.exec import device as D
                    except ImportError:
                        D = None
                    if D is not None and D.join_sides_compatible(node) is not None:
                        gen = D.stream_bucketed_join(self.session, node)
                        try:
                            first = next(gen)
                        except StopIteration:
                            return
                        except D.DeviceUnsupported:
                            gen = None
                        if gen is not None:
                            from hyperspace_tpu.plan.expr import as_bool_mask

                            def shape(chunk):
                                if post_filter is not None:
                                    chunk = B.mask_rows(
                                        chunk, as_bool_mask(post_filter.eval(chunk))
                                    )
                                return B.select(chunk, proj) if proj else chunk

                            trace.record("join", "host-span-smj-stream")
                            yield shape(first)
                            for chunk in gen:
                                yield shape(chunk)
                            return
                    if D is not None:
                        from hyperspace_tpu.exec import join_stream as JS

                        if JS.broadcast_spec(self.session, node) is not None:
                            gen = JS.stream_broadcast_join(
                                self, node, post_filter=post_filter, project=proj
                            )
                            try:
                                first = next(gen)
                            except StopIteration:
                                return
                            except D.DeviceUnsupported:
                                gen = None
                            if gen is not None:
                                yield first
                                yield from gen
                                return
                chain, leaf = _chain_to_scan(plan)
                if leaf is not None:
                    files = _leaf_files(leaf)
                    groups = _chunk_files_by_bytes(
                        files, max(1, self.session.conf.stream_chunk_bytes)
                    )
                    if len(groups) > 1:
                        needed = _chain_needed_columns(chain) | set(plan.output_columns)
                        yield from self._stream_chunks(chain, leaf, groups, needed)
                        return
                batch = self._exec(plan, False)
                yield {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
        finally:
            self._memo = {}
            self._shared = set()

    def _stream_chunks(
        self, chain, leaf, groups, needed, leaf_only=False, stage_extra=None,
        dynamic_pushdown=None,
    ):
        """Yield one executed chain batch per file group, overlapping chunk
        k+1's decode + H2D staging with chunk k's execution via ScanPipeline
        (the tentpole's stage-1/2/3 split). Pushed-down Filter conditions are
        attached to each leaf clone for row-group pruning; the serial path
        (pipeline disabled, or a chain that needs file names) executes the
        same clones, so streamed results are identical either way.

        ``leaf_only=True`` yields ``(leaf_clone, chain_plan, leaf_batch)``
        instead of executed batches: the device grouped-aggregate stream
        consumes raw leaf chunks (the predicate fuses into its program) but
        must still be able to run the chain over the same prefetched batch
        when it falls back mid-stream. ``stage_extra`` names additional
        columns (group keys, aggregate inputs) the H2D staging hook uploads
        alongside the predicate columns.

        ``dynamic_pushdown`` is a zero-arg callable returning a *currently
        valid* extra pruning predicate (or None) — the top-k stream's running
        k-th-value threshold. It is evaluated inside each chunk's decode
        thunk, so prefetched chunks pick up whatever threshold the fold has
        reached by the time their decode starts (stale thresholds are merely
        conservative; pruning is row-group granularity and never row-exact).
        H2D staging is disabled with a dynamic predicate: the branded scan
        key changes per threshold, so staged columns would never be looked
        up again."""
        conf = self.session.conf
        pushed = _chain_pushdown_condition(chain) if conf.rowgroup_pruning_enabled else None
        leaves, subs = [], []
        for g in groups:
            lf = _leaf_subset(leaf, g, needed)
            if pushed is not None and isinstance(lf, (L.FileScan, L.IndexScan)):
                lf.pushdown_predicate = pushed
            leaves.append(lf)
            subs.append(_rebuild_chain(chain, lf))
        wfns = [_plan_needs_file_names(s) for s in subs]

        def apply_dynamic(i):
            # refresh the chunk leaf's pruning predicate at decode time (the
            # top-k threshold tightens as earlier chunks fold)
            if dynamic_pushdown is None or not isinstance(
                leaves[i], (L.FileScan, L.IndexScan)
            ):
                return
            dp = dynamic_pushdown()
            if dp is None:
                return
            from hyperspace_tpu.plan.expr import BinaryOp

            leaves[i].pushdown_predicate = (
                dp if pushed is None else BinaryOp("AND", pushed, dp)
            )

        if not conf.pipeline_enabled or len(groups) < 2 or any(wfns):
            # leaf-batch prefetch can't also carry file-name columns; such
            # chains (rare: InputFileName in a filter) stay serial
            for i, (sub, wfn) in enumerate(zip(subs, wfns)):
                apply_dynamic(i)
                if leaf_only:
                    yield leaves[i], sub, self._exec(leaves[i], False)
                else:
                    yield self._exec(sub, wfn)
            return

        try:
            from hyperspace_tpu.exec import device as D
        except ImportError:
            D = None
        from hyperspace_tpu.exec.pipeline import ScanPipeline

        # H2D staging (stage 2) applies when the chunk will take the device
        # filter path: Filter directly over the scan leaf
        dev_cond = None
        if (
            D is not None
            and conf.device_execution_enabled
            and chain
            and isinstance(chain[-1], L.Filter)
            and isinstance(leaves[0], (L.FileScan, L.IndexScan))
        ):
            dev_cond = chain[-1].condition
        staging = (
            D is not None
            and (dev_cond is not None or stage_extra)
            and dynamic_pushdown is None
        )

        def stage(i, batch):
            if B.num_rows(batch) < conf.device_exec_min_rows:
                return
            key = _pruned_scan_key(_scan_identity(leaves[i]), pushed)
            # stage onto the mesh the consumer will execute over, so the
            # sharded path's device-cache lookups (keyed by mesh fingerprint)
            # hit the columns placed here
            D.stage_filter_columns(
                self.session, batch, dev_cond, key, extra_columns=stage_extra,
                parallel=_maybe_parallel(self.session, B.num_rows(batch)),
            )

        def weigh(batch):
            return sum(int(getattr(a, "nbytes", 0)) for a in batch.values())

        def decode(i):
            apply_dynamic(i)
            return self._exec(leaves[i], False)

        pipe = ScanPipeline(
            [(lambda i=i: decode(i)) for i in range(len(leaves))],
            depth=max(1, conf.pipeline_depth),
            max_buffered_bytes=conf.pipeline_max_buffered_bytes,
            weigh=weigh,
            stage=stage if staging else None,
        )
        try:
            for i, leaf_batch in enumerate(pipe):
                if leaf_only:
                    yield leaves[i], subs[i], leaf_batch
                    continue
                prev = getattr(self, "_leaf_override", None)
                self._leaf_override = (leaves[i], leaf_batch)
                try:
                    with spans.span("execute", cat="pipeline", chunk=i):
                        out = self._exec(subs[i], False)
                finally:
                    self._leaf_override = prev
                yield out
        finally:
            pipe.close()

    def _exec(self, plan: L.LogicalPlan, with_file_names: bool) -> B.Batch:
        # hits hand out shallow copies so callers may add derived keys
        # without cross-talk (arrays themselves are never mutated)
        if id(plan) in getattr(self, "_shared", ()):
            key = (id(plan), with_file_names)
            hit = self._memo.get(key)
            if hit is not None:
                return dict(hit)
            batch = self._exec_inner(plan, with_file_names)
            self._memo[key] = batch
            return dict(batch)
        return self._exec_inner(plan, with_file_names)

    def _exec_inner(self, plan: L.LogicalPlan, with_file_names: bool) -> B.Batch:
        # per-operator span: node-type name + result rows/bytes. One
        # contextvar read on the disabled path (spans.span returns the shared
        # null CM), so this sits on the recursion unconditionally.
        cm = spans.span(type(plan).__name__, cat="exec")
        if cm is spans._NULL_CM:
            return self._exec_node(plan, with_file_names)
        with cm as sp:
            batch = self._exec_node(plan, with_file_names)
            try:
                sp.set(
                    rows=B.num_rows(batch),
                    bytes=int(sum(getattr(a, "nbytes", 0) for a in batch.values())),
                )
            except Exception:
                pass
            return batch

    def _exec_node(self, plan: L.LogicalPlan, with_file_names: bool) -> B.Batch:
        # pipelined streaming hands the current chunk's prefetched leaf batch
        # to the consumer's chain execution through this override (identity
        # match: each chunk's leaf clone is unique to that chunk)
        ov = getattr(self, "_leaf_override", None)
        if ov is not None and plan is ov[0]:
            return dict(ov[1])

        if isinstance(plan, L.Scan):
            return self._exec_scan(plan, with_file_names)

        if isinstance(plan, L.FileScan):
            bucket_cache = getattr(self.session, "bucket_cache", None)
            if (
                bucket_cache is not None
                and not with_file_names
                and plan.files
                and plan.file_format == "parquet"
                and not plan.partition_values
                and not plan.format_options
            ):
                trace.record("scan", "bucket-cache-filescan")
                return bucket_cache.read(list(plan.files), list(plan.columns))
            return _read_files(
                list(plan.files),
                plan.file_format,
                list(plan.columns),
                with_file_names,
                partition_values=plan.partition_values,
                partition_dtypes=plan.partition_dtypes,
                format_options=plan.format_options,
                predicate=getattr(plan, "pushdown_predicate", None),
            )

        if isinstance(plan, L.IndexScan):
            if plan.pruned_buckets is not None:
                trace.record("scan", f"index-bucket-pruned({len(plan.pruned_buckets)} buckets)")
            else:
                trace.record("scan", "index")
            fcols = plan.file_columns if plan.file_columns is not None else list(plan.columns)
            bucket_cache = getattr(self.session, "bucket_cache", None)
            if bucket_cache is not None and not with_file_names and plan.files:
                batch = bucket_cache.read(list(plan.files), list(fcols))
            else:
                batch = _read_files(
                    list(plan.files),
                    "parquet",
                    list(fcols),
                    with_file_names,
                    predicate=getattr(plan, "pushdown_predicate", None),
                )
            if plan.file_columns is not None:
                # nested index columns are stored under their flat
                # __hs_nested. name; present them under the output name
                renamed: B.Batch = {}
                for out, fc in zip(plan.columns, fcols):
                    renamed[out] = batch[fc]
                if INPUT_FILE_NAME in batch:
                    renamed[INPUT_FILE_NAME] = batch[INPUT_FILE_NAME]
                return renamed
            return batch

        if isinstance(plan, L.Filter):
            rg_ok = self.session.conf.rowgroup_pruning_enabled
            pushed = None
            if isinstance(plan.child, L.Scan):
                # partition pruning: conjuncts over partition columns decide
                # per-file from path-derived values which files to read at all
                # (Spark's PartitioningAwareFileIndex.listFiles role)
                files = _prune_partitions(plan.child, plan.condition)
                if rg_ok:
                    pushed = plan.condition
                child = self._exec_scan(
                    plan.child, with_file_names, files=files, predicate=pushed
                )
            else:
                existing = getattr(plan.child, "pushdown_predicate", None)
                if existing is not None:
                    # a streamed leaf subset arrives with its pushdown already
                    # attached (_stream_chunks); just execute it
                    pushed = existing
                    child = self._exec(plan.child, with_file_names)
                elif (
                    rg_ok
                    and isinstance(plan.child, (L.FileScan, L.IndexScan))
                    and id(plan.child) not in self._shared
                ):
                    # push the predicate down for row-group pruning on a CLONE:
                    # the original node may be referenced by plan caches or
                    # shared subtrees, which must keep full-read semantics
                    import copy

                    clone = copy.copy(plan.child)
                    clone.pushdown_predicate = plan.condition
                    pushed = plan.condition
                    child = self._exec(clone, with_file_names)
                else:
                    child = self._exec(plan.child, with_file_names)
            mask = self._filter_mask(plan, child, pruned_by=pushed)
            return B.mask_rows(child, mask)

        if isinstance(plan, L.Project):
            # projection pushdown into a directly-scanned source: decode ONLY
            # the projected columns (the column-pruned plan shape is
            # Project-over-Scan; reading all 16 lineitem columns to keep 7
            # doubled TPC-H q1's scan cost). Shared scans are pruned to one
            # shared Project, so the _exec memo above still deduplicates.
            if (
                isinstance(plan.child, L.Scan)
                and id(plan.child) not in self._shared
                and set(plan.columns) <= set(plan.child.output_columns)
            ):
                got = self._exec_scan(
                    plan.child, with_file_names, columns=list(plan.columns)
                )
                if with_file_names and INPUT_FILE_NAME in got:
                    return got
                return B.select(got, list(plan.columns))
            child = self._exec(plan.child, with_file_names)
            cols = list(plan.columns)
            if with_file_names and INPUT_FILE_NAME in child:
                cols = cols + [INPUT_FILE_NAME]
            return B.select(child, cols)

        if isinstance(plan, L.Compute):
            from hyperspace_tpu.plan.expr import EMPTY_SCALAR, NullableBool

            child = self._exec(plan.child, with_file_names)
            out = dict(child)
            n = B.num_rows(child)
            for name, expr in plan.exprs:
                v = expr.eval(child)
                if v is EMPTY_SCALAR:  # NULL scalar subquery -> NULL column
                    v = np.full(n, np.nan)
                elif isinstance(v, NullableBool):
                    # a three-valued boolean projected as a SELECT item keeps
                    # its NULLs (Spark yields NULL, not false — so IS NULL on
                    # the alias stays correct)
                    from hyperspace_tpu.plan.expr import _to_value_array

                    v = _to_value_array(v)
                v = np.asarray(v)
                if v.ndim == 0:
                    v = np.broadcast_to(v, (n,)).copy()
                out[name] = v
            return out

        if isinstance(plan, L.Join):
            return self._exec_join(plan, with_file_names)

        if isinstance(plan, L.Aggregate):
            return self._exec_aggregate(plan, with_file_names)

        if isinstance(plan, L.Sort):
            if not with_file_names:
                got = self._try_sorted_run_merge(plan)
                if got is not None:
                    return got
            child = self._exec(plan.child, with_file_names)
            from hyperspace_tpu.plan.expr import get_column

            order = np.arange(B.num_rows(child))
            # least-significant key first: stable argsorts compose into the
            # lexicographic order over all keys. Keys sort by rank (np.unique
            # codes): negation-safe for every dtype, and missing values
            # (NaN/None) rank last in BOTH directions like pandas.
            for name, asc in reversed(plan.keys):
                arr = get_column(child, name)
                if arr is None:
                    raise KeyError(f"Sort key {name!r} not found")
                keyvals = _key_codes(arr[order], asc)
                order = order[np.argsort(keyvals, kind="stable")]
            return {k: v[order] for k, v in child.items()}

        if isinstance(plan, L.Limit):
            if isinstance(plan.child, L.Sort) and not with_file_names:
                # ORDER BY ... LIMIT k: index-order merge first (no sort at
                # all), then the streaming device top-k; both are
                # byte-identical to host-sort-then-slice
                got = self._try_sorted_run_merge(plan.child, limit=plan.n)
                if got is None:
                    got = self._try_streaming_topk(plan.child, plan.n)
                if got is not None:
                    return got
            child = self._exec(plan.child, with_file_names)
            return {k: v[: plan.n] for k, v in child.items()}

        if isinstance(plan, L.Window):
            child = self._exec(plan.child, with_file_names)
            out = dict(child)
            caches = ({}, {})  # partition ngroups / order codes, shared by specs
            for spec in plan.specs:
                out[spec[0]] = _window_column(child, spec, caches)
            return out

        if isinstance(plan, L.Rename):
            child = self._exec(plan.child, with_file_names)
            return {plan.mapping.get(k, k): v for k, v in child.items()}

        if isinstance(plan, (L.Union, L.BucketUnion)):
            return B.concat([self._exec(c, with_file_names) for c in plan.children()])

        if isinstance(plan, L.SetOp):
            left = self._exec(plan.left, with_file_names)
            right = self._exec(plan.right, with_file_names)
            lcols = plan.left.output_columns
            rcols = plan.right.output_columns
            n_l = B.num_rows(left)
            # code rows over the CONCATENATION of both sides so equal values
            # of different dtypes (int64 vs float64 from a CAST or nullable
            # column) share a code; NULLs (NaN/NaT/None) compare equal via
            # the shared _key_codes missing handling
            per_key = []
            for lc, rc in zip(lcols, rcols):
                a, b = left[lc], right[rc]
                try:
                    both = np.concatenate([a, b])
                except (TypeError, ValueError):
                    both = np.concatenate([a.astype(object), b.astype(object)])
                per_key.append(_key_codes(both, True))
            comp = _composite_codes(per_key) if per_key else np.zeros(0, dtype=np.int64)
            l_codes, r_codes = comp[:n_l], comp[n_l:]
            rset = np.zeros(int(comp.max()) + 1 if comp.size else 1, dtype=bool)
            rset[r_codes] = True
            hit = rset[l_codes]
            first = np.zeros(n_l, dtype=bool)
            if n_l:
                _, first_idx = np.unique(l_codes, return_index=True)
                first[first_idx] = True  # distinct semantics
            keep = first & (hit if plan.kind == "intersect" else ~hit)
            take = np.nonzero(keep)[0]
            return {c: left[c][take] for c in lcols}

        if isinstance(plan, L.Repartition):
            # Host path: in-memory data has no physical bucketing; pass through.
            return self._exec(plan.child, with_file_names)

        raise NotImplementedError(f"Cannot execute {type(plan).__name__}")

    def _exec_scan(
        self,
        plan: L.Scan,
        with_file_names: bool,
        files: Optional[List[str]] = None,
        columns: Optional[List[str]] = None,
        predicate=None,
    ) -> B.Batch:
        rel = plan.relation
        if files is None:
            files = [fi.name for fi in rel.all_file_infos()]
        if not files:
            # empty after pruning: typed empty columns from the schema
            from hyperspace_tpu.sources import schema as schema_codec

            batch: B.Batch = {
                f.name: np.empty(0, dtype=schema_codec.arrow_to_numpy_dtype(f.type))
                for f in rel.schema
                if columns is None or f.name in columns
            }
            if with_file_names:
                batch[INPUT_FILE_NAME] = np.empty(0, dtype=object)
            return batch
        part_cols = list(getattr(rel, "partition_columns", []) or [])
        pv = pd = None
        if part_cols:
            pv = {f: rel.partition_values_for(f) for f in files}
            pd_ = getattr(rel, "partition_dtypes", None)
            pd = dict(pd_) if pd_ else None
        return _read_files(
            files,
            rel.physical_format,
            columns,
            with_file_names,
            pv,
            pd,
            format_options=getattr(rel, "options", None) or None,
            predicate=predicate,
        )

    @staticmethod
    def _lineage_not_in(condition) -> Optional[Tuple[str, list]]:
        """Match the hybrid-scan delete filter ``NOT (col IN int-literals)``
        (rules/utils._hybrid_scan_plan); returns (column, ids) or None."""
        from hyperspace_tpu.plan.expr import Col, In, Lit, Not

        if not (isinstance(condition, Not) and isinstance(condition.child, In)):
            return None
        inner = condition.child
        if not isinstance(inner.child, Col):
            return None
        ids = []
        for lit in inner.values:
            if not (isinstance(lit, Lit) and isinstance(lit.value, (int, np.integer))):
                return None
            ids.append(int(lit.value))
        return inner.child.name, ids

    def _filter_mask(self, plan: L.Filter, child: B.Batch, pruned_by=None) -> np.ndarray:
        """Predicate evaluation: device path over index/file scans when the
        session mesh is available, host numpy otherwise. ``pruned_by`` is the
        predicate whose row-group pruning produced ``child``, if any."""
        if self.session.conf.device_execution_enabled and isinstance(
            plan.child, (L.IndexScan, L.FileScan)
        ):
            # hybrid-scan lineage delete filter: fused device anti-semi-join
            # instead of the general predicate path (which has no IN support)
            # or the host NumPy set-op
            lineage = self._lineage_not_in(plan.condition)
            if lineage is not None and self.session.conf.lifecycle_device_lineage_enabled:
                if B.num_rows(child) >= self.session.conf.lifecycle_device_lineage_min_rows:
                    from hyperspace_tpu.exec import device as D
                    from hyperspace_tpu.exec.lineage import lineage_delete_mask

                    col, ids = lineage
                    px = _maybe_parallel(self.session, B.num_rows(child))
                    try:
                        mask = lineage_delete_mask(
                            self.session,
                            child,
                            col,
                            ids,
                            scan_key=_pruned_scan_key(_scan_identity(plan.child), pruned_by),
                            parallel=px,
                        )
                        trace.record("filter", "device-lineage")
                        return mask
                    except D.DeviceUnsupported:
                        trace.record("filter", "host-fallback")
                        trace.fallback("lineage", "unsupported")
                        return as_bool_mask(plan.condition.eval(child))
                trace.fallback("lineage", "min-rows")
                trace.record("filter", "host")
                return as_bool_mask(plan.condition.eval(child))
            if B.num_rows(child) >= self.session.conf.device_exec_min_rows:
                from hyperspace_tpu.exec import device as D

                px = _maybe_parallel(self.session, B.num_rows(child))
                try:
                    mask = D.device_filter_mask(
                        self.session,
                        child,
                        plan.condition,
                        scan_key=_pruned_scan_key(_scan_identity(plan.child), pruned_by),
                        parallel=px,
                    )
                    trace.record("filter", "device-sharded" if px is not None else "device")
                    return mask
                except D.DeviceUnsupported:
                    trace.record("filter", "host-fallback")
                    trace.fallback("filter", "unsupported")
                    return as_bool_mask(plan.condition.eval(child))
            trace.fallback("filter", "min-rows")
        trace.record("filter", "host")
        return as_bool_mask(plan.condition.eval(child))

    def _exec_aggregate(self, plan: L.Aggregate, with_file_names: bool) -> B.Batch:
        # fused device path for global aggregates over an (optionally
        # filtered) index/file scan: predicate + reductions run in one jitted
        # program over HBM-resident columns; only scalars transfer back
        child = None
        if not with_file_names and self.session.conf.device_execution_enabled:
            # fused aggregate over a bucketed join: spans give each pair's
            # multiplicity, so no join output is ever materialized (global
            # aggregates, or grouped by the join keys)
            join_node = plan.child
            computes = []
            while isinstance(join_node, (L.Project, L.Compute)):
                if isinstance(join_node, L.Compute):
                    # computed aggregate inputs / group keys (q3's
                    # sum(l_extendedprice * (1 - l_discount))): single-side
                    # expressions evaluate per bucket inside the fusion
                    computes.extend(join_node.exprs)
                join_node = join_node.child
            if isinstance(join_node, L.Join):
                from hyperspace_tpu.exec import device as D

                try:
                    got = D.aggregate_over_bucketed_join(
                        self.session, plan, join_node, computes=computes
                    )
                    trace.record("agg", "fused-bucketed-join")
                    return got
                except D.DeviceUnsupported:
                    trace.fallback("agg", "join-unsupported")
        # streaming check BEFORE the device-scan gate: _try_device_aggregate
        # materializes the whole scan to size its decision, which is exactly
        # what the out-of-core path exists to avoid
        if not with_file_names:
            got = self._try_fused_join_aggregate(plan)
            if got is not None:
                return got
            got = self._try_streaming_aggregate(plan)
            if got is not None:
                trace.record("agg", "streamed-partial")
                return got
        if not with_file_names and self.session.conf.device_execution_enabled:
            got, scan_batch, filter_node, pruned = self._try_device_aggregate(plan)
            if got is not None:
                trace.record(
                    "agg", "device-grouped-scan" if plan.keys else "device-fused-scan"
                )
                return got
            if scan_batch is not None:
                # the device gate already materialized the scan — reuse it
                # instead of re-reading parquet on the host fallback
                if filter_node is not None:
                    mask = self._filter_mask(filter_node, scan_batch, pruned_by=pruned)
                    child = B.mask_rows(scan_batch, mask)
                else:
                    child = scan_batch

        if child is None:
            child = self._exec(plan.child, with_file_names)
        return host_aggregate(child, list(plan.keys), list(plan.aggs))

    # -- streamed Limit shapes (execute_stream) -------------------------------

    def _stream_limit_node(self, plan: L.Limit):
        """Streamed execution of a root Limit: ORDER BY...LIMIT dispatches to
        the sorted-run merge / device top-k (one result batch), a bare Limit
        early-terminates the scan pipeline. Returns a generator, or None to
        fall back to the materialized path."""
        if isinstance(plan.child, L.Sort):
            got = self._try_sorted_run_merge(plan.child, limit=plan.n)
            if got is None:
                got = self._try_streaming_topk(plan.child, plan.n)
            if got is None:
                return None

            def one():
                yield got

            return one()
        return self._stream_limit(plan)

    def _stream_limit(self, plan: L.Limit):
        """Early-terminating bare Limit: stop pulling source chunks once n
        rows are collected. Closing the chunk generator propagates into
        ScanPipeline.close(), which cancels every queued decode (the
        mid-stream-close discipline of the streaming joins)."""
        if plan.n <= 0:
            return None
        conf = self.session.conf
        chain, leaf = _chain_to_scan(plan.child)
        if leaf is None:
            return None
        files = _leaf_files(leaf)
        groups = _chunk_files_by_bytes(files, max(1, conf.stream_chunk_bytes))
        if len(groups) < 2:
            return None
        needed = _chain_needed_columns(chain) | set(plan.output_columns)

        def gen():
            remaining = int(plan.n)
            chunks = self._stream_chunks(chain, leaf, groups, needed)
            try:
                for batch in chunks:
                    batch = {c: v for c, v in batch.items() if c != INPUT_FILE_NAME}
                    rows = B.num_rows(batch)
                    if rows >= remaining:
                        trace.record("limit", "early-stop-stream")
                        yield {c: np.asarray(v)[:remaining] for c, v in batch.items()}
                        return
                    if rows:
                        remaining -= rows
                        yield batch
            finally:
                # deterministic cancel of queued decodes, even when our own
                # consumer abandons mid-iteration
                chunks.close()

        return gen()

    # -- streaming device top-k (ORDER BY ... LIMIT k) ------------------------

    def _try_streaming_topk(self, sort_plan: L.Sort, k: int) -> Optional[B.Batch]:
        """ORDER BY ... LIMIT k over a multi-chunk scan chain as a streaming
        device top-k fold (exec/topk.TopKStream): no full materialization,
        one compile per (key count, capacity, shape bucket), byte-identical
        to host-sort-then-slice. Returns None (caller materializes) when the
        shape or configuration doesn't stream."""
        conf = self.session.conf
        if not (conf.topk_enabled and conf.device_execution_enabled):
            return None
        if not sort_plan.keys or k <= 0 or k > conf.topk_max_k:
            return None
        chain, leaf = _chain_to_scan(sort_plan.child)
        if leaf is None:
            return None
        files = _leaf_files(leaf)
        if len(files) < 2:
            return None
        groups = _chunk_files_by_bytes(files, max(1, conf.stream_chunk_bytes))
        if len(groups) < 2:
            return None
        try:
            return self._streaming_topk(sort_plan, k, chain, leaf, groups)
        except Exception:
            # the streamed path must never break a query the materialized
            # path can answer; visible in dispatch traces
            trace.record("topk", "stream-fallback")
            return None

    def _streaming_topk(self, sort_plan, k, chain, leaf, groups) -> Optional[B.Batch]:
        from hyperspace_tpu.exec import device as D
        from hyperspace_tpu.exec.topk import TopKStream

        conf = self.session.conf
        needed = _chain_needed_columns(chain) | set(sort_plan.output_columns)
        needed |= {c for c, _ in sort_plan.keys}
        stream = TopKStream(
            self.session, sort_plan.keys, k, parallel=_maybe_parallel(self.session)
        )
        # the running k-th-value threshold prunes row groups of chunks not
        # yet decoded; only sound when pruning is on and the chain cannot
        # rebind the primary key column
        dynamic = None
        if (
            conf.topk_threshold_pushdown
            and conf.rowgroup_pruning_enabled
            and all(isinstance(nd, (L.Filter, L.Project)) for nd in chain)
        ):
            dynamic = stream.threshold_condition
        host_parts: Optional[List[B.Batch]] = None
        host_rid = 0
        for batch in self._stream_chunks(
            chain, leaf, groups, needed, dynamic_pushdown=dynamic
        ):
            batch = {c: v for c, v in batch.items() if c != INPUT_FILE_NAME}
            if host_parts is None:
                try:
                    stream.update(batch)
                    continue
                except D.DeviceUnsupported as e:
                    # mid-stream fallback: the pool is a superset of the
                    # top-k of every folded row, so (pool + this and later
                    # chunks) re-sorted on host stays byte-identical
                    trace.fallback("topk", str(e) or type(e).__name__)
                    host_parts = (
                        [stream.pool_rows_with_rid(_TOPK_RID)]
                        if stream.has_data
                        else []
                    )
                    host_rid = stream.rows_seen
            n = B.num_rows(batch)
            part = dict(batch)
            part[_TOPK_RID] = host_rid + np.arange(n, dtype=np.int64)
            host_rid += n
            host_parts.append(part)
        if host_parts is None:
            if not stream.has_data:
                return None  # every chunk came back empty — materialize
            trace.record(
                "topk",
                "device-topk-stream-sharded"
                if stream.parallel is not None
                else "device-topk-stream",
            )
            return stream.finalize()
        parts = [p for p in host_parts if B.num_rows(p)]
        if not parts:
            return None
        from hyperspace_tpu.plan.expr import get_column

        merged = B.concat(parts)
        # stable composite sort with the global row id as the base order —
        # exactly the host Sort's tie semantics
        order = np.argsort(np.asarray(merged[_TOPK_RID]), kind="stable")
        for name, asc in reversed(sort_plan.keys):
            arr = get_column(merged, name)
            if arr is None:
                raise KeyError(f"Sort key {name!r} not found")
            codes = _key_codes(np.asarray(arr)[order], asc)
            order = order[np.argsort(codes, kind="stable")]
        take = order[:k]
        trace.record("topk", "host-candidate-fallback")
        return {c: np.asarray(v)[take] for c, v in merged.items() if c != _TOPK_RID}

    # -- sort elimination: streamed merge of sorted index runs ----------------

    def _try_sorted_run_merge(self, sort_plan: L.Sort, limit=None) -> Optional[B.Batch]:
        """Replace a Sort whose order the covering index already provides
        (within-bucket sort order, plan/ordering.sort_run_eligibility) with a
        k-way merge of per-file runs. Why-not reasons land in dispatch traces
        and the QueryProfile report when the rewrite cannot fire."""
        from hyperspace_tpu.plan import ordering as ORD

        leaf, chain, reason = ORD.sort_run_eligibility(sort_plan)
        if leaf is None:
            # record once per query: the bare-Sort call (the Limit wrapper
            # retries through the Sort branch anyway)
            if reason is not None and limit is None:
                trace.record("sort", f"merge-why-not: {reason}")
            return None
        try:
            return self._merge_sorted_runs(sort_plan, chain, leaf, limit)
        except Exception:
            trace.record("sort", "merge-fallback")
            return None

    def _merge_sorted_runs(self, sort_plan, chain, leaf, limit) -> Optional[B.Batch]:
        import heapq

        from hyperspace_tpu.plan.expr import get_column

        files = _leaf_files(leaf)
        if len(files) < 2:
            return None  # a single run needs no merge; host path is fine
        needed = _chain_needed_columns(chain) | set(sort_plan.output_columns)
        needed |= {c for c, _ in sort_plan.keys}
        runs = []
        for f in files:
            sub = _rebuild_chain(chain, _leaf_subset(leaf, [f], needed))
            runs.append(self._exec(sub, False))
        lens = [B.num_rows(r) for r in runs]
        total = B.concat(runs)
        n = B.num_rows(total)
        bounds = np.cumsum([0] + lens)
        # rank codes over the concatenation: one consistent code space for
        # all runs, same NULLS LAST / DESC semantics as the host Sort
        codes = []
        for name, asc in sort_plan.keys:
            arr = get_column(total, name)
            if arr is None:
                raise KeyError(f"Sort key {name!r} not found")
            codes.append(_key_codes(np.asarray(arr), asc))

        def run_monotone(s: int, e: int) -> bool:
            if e - s < 2:
                return True
            lt = np.zeros(e - s - 1, dtype=bool)
            eq = np.ones(e - s - 1, dtype=bool)
            for c in codes:
                seg = c[s:e]
                lt |= eq & (seg[1:] < seg[:-1])
                eq &= seg[1:] == seg[:-1]
            return not lt.any()

        run_orders = []
        repaired = 0
        for i in range(len(runs)):
            s, e = int(bounds[i]), int(bounds[i + 1])
            if run_monotone(s, e):
                run_orders.append(np.arange(s, e, dtype=np.int64))
            else:
                # physical order disagrees with the requested order (NULL
                # placement, float total-order rotation, stale layout):
                # stable-repair the run; the merge stays byte-identical
                repaired += 1
                sl = np.lexsort(tuple(c[s:e] for c in reversed(codes)))
                run_orders.append(s + sl.astype(np.int64))
        # k-way heap merge; ties across runs resolve by global position ==
        # the host stable sort's tie order (within a run the repair is
        # stable, so sequential emission preserves it too)
        take_n = n if limit is None else min(int(limit), n)
        heap = []
        for ro in run_orders:
            if ro.size:
                i0 = int(ro[0])
                heapq.heappush(heap, (tuple(c[i0] for c in codes), i0, ro, 1))
        out_idx = np.empty(take_n, dtype=np.int64)
        taken = 0
        while heap and taken < take_n:
            _, idx, ro, nxt = heapq.heappop(heap)
            out_idx[taken] = idx
            taken += 1
            if nxt < ro.size:
                i0 = int(ro[nxt])
                heapq.heappush(heap, (tuple(c[i0] for c in codes), i0, ro, nxt + 1))
        trace.record(
            "sort",
            "index-order-merge"
            + ("-limit" if limit is not None else "")
            + (f"-repaired:{repaired}" if repaired else ""),
        )
        return {c: np.asarray(v)[out_idx] for c, v in total.items()}

    def _try_fused_join_aggregate(self, plan: L.Aggregate) -> Optional[B.Batch]:
        """Whole-plan fused q3 shape: Aggregate over (Filter over) an inner
        broadcast Join compiles to ONE donated XLA program per chunk
        (exec/stage_ir.stream_join_aggregate) instead of the per-family
        probe/verify/postjoin/fold/merge dispatch chain. Returns None (caller
        falls through to the per-family streaming and materialized paths)
        unless ``hyperspace.exec.fusion.enabled`` is set and the shape fuses."""
        conf = self.session.conf
        try:
            from hyperspace_tpu.exec import device as D
            from hyperspace_tpu.exec import join_stream as JS
            from hyperspace_tpu.exec import stage_ir
        except ImportError:
            return None
        if not (
            conf.device_execution_enabled
            and conf.agg_device_grouped_enabled
            and stage_ir.fusion_wanted(conf)
        ):
            return None
        if not plan.keys:
            return None
        if any(fn not in _STREAMABLE_AGGS or fn.endswith("_distinct")
               for _, fn, _ in plan.aggs):
            return None
        node = plan.child
        post_filter = None
        if isinstance(node, L.Filter) and isinstance(node.child, L.Join):
            post_filter, node = node.condition, node.child
        if not isinstance(node, L.Join):
            return None
        spec = JS.broadcast_spec(self.session, node)
        if spec is None:
            return None
        try:
            return stage_ir.stream_join_aggregate(
                self, node, spec, post_filter, list(plan.keys), list(plan.aggs)
            )
        except D.DeviceUnsupported:
            trace.fallback("fusion", "join-agg-unsupported")
            return None
        except Exception:
            # same discipline as the per-family streamed aggregate: the fused
            # path must never break a query the materialized path can answer
            trace.record("agg", "stream-fallback")
            return None

    def _try_streaming_aggregate(self, plan: L.Aggregate) -> Optional[B.Batch]:
        """Out-of-core aggregate: when the child is a scan chain over more
        source bytes than conf ``exec.stream.aggMinBytes``, execute it in
        file chunks and merge decomposable partial states — Spark's
        partial/final aggregation split, which is what lets the reference
        aggregate over tables far larger than executor memory. Returns None
        (caller materializes) when the shape, size, or aggregate set doesn't
        stream."""
        conf = self.session.conf
        min_bytes = conf.stream_agg_min_bytes
        if not min_bytes or min_bytes <= 0:
            return None
        if any(fn not in _STREAMABLE_AGGS for _, fn, _ in plan.aggs):
            return None
        chain, leaf = _chain_to_scan(plan.child)
        if leaf is None:
            return None
        files = _leaf_files(leaf)
        if len(files) < 2:
            return None
        import os

        try:
            total_bytes = sum(os.stat(f).st_size for f in files)
        except OSError:
            return None
        if total_bytes < min_bytes:
            return None
        groups = _chunk_files_by_bytes(files, max(1, conf.stream_chunk_bytes))
        if len(groups) < 2:
            return None
        needed = _chain_needed_columns(chain, plan.aggs, plan.keys)
        try:
            return self._streaming_aggregate(plan, chain, leaf, groups, needed)
        except Exception:
            # the streamed path must never break a query the materialized
            # path can answer; visible in dispatch traces
            trace.record("agg", "stream-fallback")
            return None

    def _streaming_aggregate(self, plan, chain, leaf, groups, needed) -> B.Batch:
        import pandas as pd

        grouped = bool(plan.keys)
        # distinct-form aggregates accumulate (group keys +) unique values;
        # everything else carries closed-form partial states
        plain = [(i, n, fn, c) for i, (n, fn, c) in enumerate(plan.aggs)
                 if not fn.endswith("_distinct")]
        distinct = [(i, n, fn, c) for i, (n, fn, c) in enumerate(plan.aggs)
                    if fn.endswith("_distinct")]

        partial_frames: List = []          # grouped plain partials
        distinct_frames = {i: [] for i, *_ in distinct}  # per-agg pair frames
        g_state: Dict[int, Any] = {}       # global plain partials

        def fold_chunk(batch):
            batch = {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
            n = B.num_rows(batch)

            def series(col):
                from hyperspace_tpu.plan.expr import get_column

                got = batch.get(col)
                if got is None:
                    got = get_column(batch, col)
                if got is None:
                    raise KeyError(f"Aggregate input column {col!r} not found")
                return got

            if grouped:
                frame_cols = {k: series(k) for k in plan.keys}
                for _i, _n, _fn, c in plain:
                    if c is not None and c not in frame_cols:
                        frame_cols[c] = series(c)
                df = pd.DataFrame(frame_cols)
                gb = df.groupby(list(plan.keys), dropna=False, sort=False)
                pieces = {}
                for i, name, fn, c in plain:
                    p = f"__p{i}"
                    if fn == "count":
                        pieces[p] = gb.size() if c is None else gb[c].count()
                    elif fn == "sum":
                        pieces[p] = gb[c].sum(min_count=1)
                    elif fn == "min":
                        pieces[p] = gb[c].min()
                    elif fn == "max":
                        pieces[p] = gb[c].max()
                    elif fn == "avg":
                        pieces[p + "_s"] = gb[c].sum(min_count=1)
                        pieces[p + "_c"] = gb[c].count()
                    elif fn == "stddev_samp":
                        pieces[p + "_n"] = gb[c].count()
                        pieces[p + "_s"] = gb[c].sum(min_count=1)
                        # float64 BEFORE squaring: int64 values near 2^32
                        # would wrap the sum-of-squares negative
                        pieces[p + "_ss"] = gb[c].apply(
                            lambda s: float((s.dropna().astype(np.float64) ** 2).sum())
                        )
                if pieces:
                    partial_frames.append(pd.DataFrame(pieces).reset_index())
                elif distinct:
                    # keys-only partial so groups with only-distinct aggs
                    # still materialize every group
                    partial_frames.append(
                        pd.DataFrame({k: frame_cols[k] for k in plan.keys})
                        .drop_duplicates()
                    )
                for i, name, fn, c in distinct:
                    pair = pd.DataFrame(
                        {**{k: series(k) for k in plan.keys}, "__v": series(c)}
                    ).drop_duplicates()
                    distinct_frames[i].append(pair)
            else:
                for i, name, fn, c in plain:
                    s = pd.Series(series(c)) if c is not None else None
                    st = g_state.get(i)
                    if fn == "count":
                        v = n if c is None else int(s.count())
                        g_state[i] = (st or 0) + v
                    elif fn in ("sum", "min", "max"):
                        part = getattr(s, {"sum": "sum", "min": "min", "max": "max"}[fn])(
                            **({"min_count": 1} if fn == "sum" else {})
                        )
                        g_state.setdefault(i, []).append(part)
                    elif fn == "avg":
                        sc = g_state.setdefault(i, [0.0, 0])
                        cnt = int(s.count())
                        if cnt:
                            sc[0] += float(s.sum())
                            sc[1] += cnt
                    elif fn == "stddev_samp":
                        sc = g_state.setdefault(i, [0, 0.0, 0.0])
                        d = s.dropna().astype(np.float64)
                        sc[0] += int(d.shape[0])
                        sc[1] += float(d.sum())
                        sc[2] += float((d**2).sum())
                for i, name, fn, c in distinct:
                    u = pd.Series(series(c)).dropna().drop_duplicates()
                    distinct_frames[i].append(u.to_frame("__v"))

        # device grouped streaming: fuse the chain's predicate into the
        # grouped segment-reduction program over each RAW leaf chunk and keep
        # the running partial-aggregate table on device, merged chunk-to-chunk
        # — the scan never materializes on host. Any mid-stream fallback
        # (cardinality spill, dtype drift) converts the device partial into
        # ONE host partial frame and continues with the pandas fold below.
        conf = self.session.conf
        stream = None
        fuse_cond = None
        stage_extra = None
        if (
            grouped
            and not distinct
            and conf.device_execution_enabled
            and conf.agg_device_grouped_enabled
            # the per-chunk leaf CLONES are always FileScan/IndexScan
            # (_leaf_subset converts a relation Scan), so any chain of
            # Filters/Projects fuses; Compute/Rename rebind the namespace
            # the fused predicate and keys are expressed in
            and all(isinstance(nd, (L.Filter, L.Project)) for nd in chain)
        ):
            try:
                from hyperspace_tpu.exec import device as D
            except ImportError:
                D = None
            if D is not None:
                fuse_cond = _chain_pushdown_condition(chain)
                stage_extra = sorted(
                    set(plan.keys) | {c for _, _, _, c in plain if c is not None}
                )
                stream = D.GroupedAggStream(
                    self.session,
                    list(plan.keys),
                    list(plan.aggs),
                    max_groups=conf.agg_max_groups,
                    cap_floor=conf.agg_capacity_floor,
                    # capacity hint shared across repeated runs of the same
                    # query shape over the same file set (skips the first
                    # chunk's right-sizing re-run once cardinality is known)
                    hint_key=("stream",) + tuple(_leaf_files(leaf)),
                    # per-stream mode decision (chunk sizes aren't known yet):
                    # minRows gates the one-shot ops, not stream chunks
                    parallel=_maybe_parallel(self.session),
                )

        # chunks arrive through the prefetch pipeline: chunk k+1 decodes (and
        # stages) while this loop folds chunk k's partials
        if stream is None:
            for batch in self._stream_chunks(chain, leaf, groups, needed):
                fold_chunk(batch)
        else:
            device_ok = True
            for lf, sub, leaf_batch in self._stream_chunks(
                chain, leaf, groups, needed, leaf_only=True, stage_extra=stage_extra
            ):
                if device_ok:
                    nb = B.num_rows(leaf_batch)
                    if nb and nb < conf.device_exec_min_rows:
                        trace.fallback("agg", "min-rows")
                        device_ok = False
                    else:
                        key = _pruned_scan_key(
                            _scan_identity(lf), getattr(lf, "pushdown_predicate", None)
                        )
                        try:
                            stream.update(leaf_batch, fuse_cond, scan_key=key)
                            continue
                        except D.GroupCapacityExceeded as e:
                            trace.fallback("agg", "spill")
                            device_ok = False
                            if stream.has_data:
                                partial_frames.append(stream.to_partial_frame(plain))
                            if getattr(e, "folded", False):
                                continue  # chunk already merged into the partial
                        except D.DeviceUnsupported:
                            trace.fallback("agg", "unsupported")
                            device_ok = False
                            if stream.has_data:
                                partial_frames.append(stream.to_partial_frame(plain))
                # host fold of this (and every later) chunk, executing the
                # chain over the SAME prefetched leaf batch
                prev = getattr(self, "_leaf_override", None)
                self._leaf_override = (lf, leaf_batch)
                try:
                    fold_chunk(self._exec(sub, False))
                finally:
                    self._leaf_override = prev
            if device_ok and stream.has_data:
                trace.record(
                    "agg",
                    "device-grouped-stream-sharded"
                    if getattr(stream, "_parallel", None) is not None
                    else "device-grouped-stream",
                )
                return stream.finalize()

        if grouped:
            merged = pd.concat(partial_frames, ignore_index=True)
            gb = merged.groupby(list(plan.keys), dropna=False, sort=False)
            final = {}
            for i, name, fn, c in plain:
                p = f"__p{i}"
                if fn == "count":
                    final[name] = gb[p].sum().astype(np.int64)
                elif fn == "sum":
                    final[name] = gb[p].sum(min_count=1)
                elif fn == "min":
                    final[name] = gb[p].min()
                elif fn == "max":
                    final[name] = gb[p].max()
                elif fn == "avg":
                    s_, c_ = gb[p + "_s"].sum(min_count=1), gb[p + "_c"].sum()
                    final[name] = s_ / c_.where(c_ > 0)
                elif fn == "stddev_samp":
                    n_ = gb[p + "_n"].sum()
                    s_ = gb[p + "_s"].sum(min_count=1)
                    ss_ = gb[p + "_ss"].sum()
                    var = (ss_ - (s_**2) / n_.where(n_ > 0)) / (n_ - 1).where(n_ > 1)
                    final[name] = np.sqrt(var.clip(lower=0))
            result = pd.DataFrame(final).reset_index() if final else (
                merged[list(plan.keys)].drop_duplicates().reset_index(drop=True)
            )
            for i, name, fn, c in distinct:
                pairs = pd.concat(distinct_frames[i], ignore_index=True).drop_duplicates()
                pairs = pairs[pairs["__v"].notna()]
                pgb = pairs.groupby(list(plan.keys), dropna=False, sort=False)["__v"]
                if fn == "count_distinct":
                    dser = pgb.nunique(dropna=True)
                elif fn == "sum_distinct":
                    dser = pgb.sum(min_count=1)
                else:  # avg_distinct
                    dser = pgb.mean()
                dser.name = name
                result = result.merge(dser.reset_index(), on=list(plan.keys), how="left")
                if fn == "count_distinct":
                    result[name] = result[name].fillna(0).astype(np.int64)
            out: B.Batch = {}
            for k in plan.keys:
                out[k] = result[k].to_numpy()
            for name, _, _ in plan.aggs:
                out[name] = result[name].to_numpy()
            return out

        out = {}
        for i, name, fn, c in plain:
            st = g_state.get(i)
            if fn == "count":
                out[name] = np.asarray([st or 0])
            elif fn in ("sum", "min", "max"):
                s = pd.Series(st or [])
                v = getattr(s, {"sum": "sum", "min": "min", "max": "max"}[fn])(
                    **({"min_count": 1} if fn == "sum" else {})
                )
                out[name] = np.asarray([v])
            elif fn == "avg":
                s_, c_ = st or (0.0, 0)
                out[name] = np.asarray([s_ / c_ if c_ else np.nan])
            elif fn == "stddev_samp":
                n_, s_, ss_ = st or (0, 0.0, 0.0)
                if n_ > 1:
                    var = max(0.0, (ss_ - s_ * s_ / n_) / (n_ - 1))
                    out[name] = np.asarray([np.sqrt(var)])
                else:
                    out[name] = np.asarray([np.nan])
        for i, name, fn, c in distinct:
            u = pd.concat(distinct_frames[i], ignore_index=True)["__v"].drop_duplicates()
            u = u[u.notna()]
            if fn == "count_distinct":
                out[name] = np.asarray([int(u.shape[0])])
            elif fn == "sum_distinct":
                out[name] = np.asarray([u.sum(min_count=1) if len(u) else np.nan])
            else:
                out[name] = np.asarray([u.mean() if len(u) else np.nan])
        return {name: out[name] for name, _, _ in plan.aggs}

    def _try_device_aggregate(self, plan: L.Aggregate):
        """Returns (result, scan_batch, filter_node, pruned_by): result=None
        means the caller runs the host path — reusing scan_batch (the
        materialized scan, pre-filter) when it was already read for the gate.
        ``pruned_by`` is the scan's attached row-group-pruning predicate; the
        caller must thread it into any further device-cache use of
        scan_batch, or a pruned batch gets branded with an unpruned key."""
        conf = self.session.conf
        node = plan.child
        filter_node = None
        if isinstance(node, L.Filter):
            filter_node = node
            node = node.child
        if not isinstance(node, (L.IndexScan, L.FileScan)):
            return None, None, None, None
        if plan.keys and not conf.agg_device_grouped_enabled:
            return None, None, None, None
        try:
            from hyperspace_tpu.exec import device as D
        except ImportError:
            return None, None, None, None
        pruned = getattr(node, "pushdown_predicate", None)
        batch = self._exec(node, with_file_names=False)
        if B.num_rows(batch) < conf.device_exec_min_rows:
            trace.fallback("agg", "min-rows")
            return None, batch, filter_node, pruned
        condition = filter_node.condition if filter_node is not None else None
        scan_key = _pruned_scan_key(_scan_identity(node), pruned)
        try:
            if plan.keys:
                got = D.device_grouped_aggregate(
                    self.session,
                    batch,
                    condition,
                    list(plan.keys),
                    list(plan.aggs),
                    scan_key=scan_key,
                    max_groups=conf.agg_max_groups,
                    cap_floor=conf.agg_capacity_floor,
                    parallel=_maybe_parallel(self.session, B.num_rows(batch)),
                )
            else:
                got = D.device_filtered_aggregate(
                    self.session, batch, condition, plan.aggs, scan_key=scan_key
                )
            return got, batch, filter_node, pruned
        except D.GroupCapacityExceeded:
            trace.fallback("agg", "spill")
            return None, batch, filter_node, pruned
        except D.DeviceUnsupported:
            trace.fallback("agg", "unsupported")
            return None, batch, filter_node, pruned

    def _exec_join(self, plan: L.Join, with_file_names: bool) -> B.Batch:
        """Generic (non-bucketed) equi-join fallback via a pandas hash merge
        over slim key frames; see the slim-merge note below."""
        import pandas as pd

        if not with_file_names and self.session.conf.device_execution_enabled:
            # deviceExecution=False is the kill switch back to the pandas
            # merge below — it routes around the whole bucketed-SMJ stack
            try:
                from hyperspace_tpu.exec import device as D
            except ImportError:
                D = None
            if D is not None:
                try:
                    return D.dispatch_bucketed_join(self.session, plan)
                except D.DeviceUnsupported:
                    pass  # next tier: broadcast hash join
                try:
                    from hyperspace_tpu.exec import join_stream as JS

                    return JS.dispatch_broadcast_join(self, plan)
                except D.DeviceUnsupported:
                    trace.fallback("join", "unsupported")
        trace.record("join", "generic-merge")

        pairs = extract_equi_join_keys(plan.condition)
        if pairs is None:
            raise NotImplementedError("Only conjunctive equi-joins are supported")
        left = self._exec(plan.left, with_file_names)
        right = self._exec(plan.right, with_file_names)
        left = {k: v for k, v in left.items() if k != INPUT_FILE_NAME}
        right = {k: v for k, v in right.items() if k != INPUT_FILE_NAME}

        def materialize_key(batch: B.Batch, name: str) -> bool:
            """Ensure ``name`` is a column of ``batch``; a dotted nested key
            is extracted from its root struct column on demand, and casing
            resolves like the analyzer's (Spark-default case-insensitive)."""
            if name in batch:
                return True
            from hyperspace_tpu.plan.expr import get_column

            got = get_column(batch, name)
            if got is not None:
                batch[name] = got
                return True
            lowered = {k.lower(): k for k in batch}
            actual = lowered.get(name.lower())
            if actual is not None:
                batch[name] = batch[actual]
                return True
            return False

        # validate key sides (columns may arrive swapped from the user)
        lkeys, rkeys = [], []
        for a, b in pairs:
            if materialize_key(left, a) and materialize_key(right, b):
                lkeys.append(a)
                rkeys.append(b)
            elif materialize_key(left, b) and materialize_key(right, a):
                lkeys.append(b)
                rkeys.append(a)
            else:
                raise ValueError(f"Join keys ({a}, {b}) not found in the two sides")
        left_cols = list(left)
        right_cols = list(right)

        # rename duplicated right-side columns up front so every output column
        # resolves to one unambiguous source; naming must match the plan's
        # (join_output_names). Only the KEY columns enter pandas: payload
        # columns would round-trip through pandas' (Arrow-backed) column
        # construction and back — measured at ~65% of TPC-H q7's join time
        # for string payloads — so the merge works on slim key+row-id frames
        # and every payload column is gathered from the original numpy
        # arrays by matched row id afterwards.
        _, rename = L.join_output_names(left_cols, right_cols)
        right_named = {rename.get(k, k): v for k, v in right.items()}
        rkeys_renamed = [rename.get(k, k) for k in rkeys]
        ldf = pd.DataFrame(
            {**{k: left[k] for k in lkeys}, "__lrow": np.arange(B.num_rows(left))}
        )
        rdf = pd.DataFrame(
            {
                **{k: right_named[k] for k in rkeys_renamed},
                "__rrow": np.arange(B.num_rows(right)),
            }
        )
        if plan.residual is None:
            spill = self.session.conf.join_spill_min_rows
            if spill and spill > 0 and max(len(ldf), len(rdf)) > spill:
                merged = self._partitioned_merge(
                    ldf, rdf, lkeys, rkeys_renamed, plan.how, spill
                )
            else:
                merged = ldf.merge(rdf, left_on=lkeys, right_on=rkeys_renamed, how=plan.how)
        else:
            merged = self._residual_join(
                plan, ldf, rdf, lkeys, rkeys_renamed, left, right_named
            )
        lspec = _gather_spec(merged["__lrow"].to_numpy())
        rspec = _gather_spec(merged["__rrow"].to_numpy())
        out: B.Batch = {}
        for name in plan.output_columns:
            if name in merged.columns:  # key columns, incl. renamed right keys
                out[name] = merged[name].to_numpy()
            elif name in left:
                out[name] = _gather_with_missing(left[name], lspec)
            elif name in right_named:
                out[name] = _gather_with_missing(right_named[name], rspec)
            else:
                raise KeyError(f"Join output column {name!r} missing")
        # USING-style joins coalesce the key across sides (Spark's
        # df.join(other, on="k") semantics): a right/outer join's unmatched
        # rows show the RIGHT side's key under the left name, not NULL
        if plan.how in ("right", "outer") and plan.using_pairs:
            for lk, rk in plan.using_pairs:
                rkr = rename.get(rk, rk)
                if lk in out and rkr in merged.columns:
                    lv = out[lk]
                    mask = pd.isna(lv)
                    if mask.any():
                        out[lk] = np.where(mask, merged[rkr].to_numpy(), lv)
        return out

    @staticmethod
    def _partitioned_merge(ldf, rdf, lkeys, rkeys, how: str, spill_rows: int):
        """Grace-style partitioned hash merge: both slim key frames split by
        a shared key hash and each partition merges independently, bounding
        the merge intermediate (hash table + indexers) to ~1/P of the
        unpartitioned spike. Correct for every join type because hash
        partitions are disjoint by key: each row joins (or null-extends)
        entirely within its partition — the same argument Spark's shuffled
        hash join rests on. Equal values hash equally across the two sides'
        dtypes (keys coerce to a common type before hashing), and NaN keys
        hash deterministically, so pandas' NaN-matches-NaN merge semantics
        are preserved partition-locally."""
        import pandas as pd

        from hyperspace_tpu.ops.encode import hash_input_uint32
        from hyperspace_tpu.ops.hashing import bucket_ids_np

        n_parts = max(2, -(-max(len(ldf), len(rdf)) // spill_rows))

        # partitioning is only sound when equal-under-pandas keys hash
        # equally on both sides: coerce numeric pairs to a common dtype and
        # normalize -0.0 to +0.0 (pandas merges them equal; their IEEE bit
        # patterns hash apart); any key pair outside that guarantee (object
        # vs numeric, mismatched datetime units) falls back to the single
        # merge rather than silently dropping matches
        def keyed(df, keys, other_df, other_keys):
            planes = []
            for k, ok in zip(keys, other_keys):
                a = df[k].to_numpy()
                b = other_df[ok].to_numpy()
                if a.dtype != b.dtype:
                    if a.dtype.kind in "iuf" and b.dtype.kind in "iuf":
                        a = a.astype(np.result_type(a.dtype, b.dtype), copy=False)
                    else:
                        return None
                if a.dtype.kind == "f":
                    a = a + 0.0  # -0.0 -> +0.0; NaN unchanged
                planes.append(hash_input_uint32(a))
            return bucket_ids_np(planes, n_parts)

        lids = keyed(ldf, lkeys, rdf, rkeys)
        rids = keyed(rdf, rkeys, ldf, lkeys)
        if lids is None or rids is None:
            return ldf.merge(rdf, left_on=lkeys, right_on=rkeys, how=how)
        trace.record("join", f"generic-merge-partitioned({n_parts})")
        parts = []
        for p in range(n_parts):
            lp = ldf[lids == p]
            rp = rdf[rids == p]
            if len(lp) == 0 and len(rp) == 0:
                continue
            if how == "inner" and (len(lp) == 0 or len(rp) == 0):
                continue
            if how == "left" and len(lp) == 0:
                continue
            if how == "right" and len(rp) == 0:
                continue
            parts.append(lp.merge(rp, left_on=lkeys, right_on=rkeys, how=how))
        if not parts:
            return ldf.iloc[:0].merge(rdf.iloc[:0], left_on=lkeys, right_on=rkeys, how=how)
        return pd.concat(parts, ignore_index=True, sort=False)

    @staticmethod
    def _residual_join(plan: L.Join, ldf, rdf, lkeys, rkeys, left, right_named):
        """Join with a non-equi ON residual: equi-match pairs, keep only
        pairs satisfying the residual, then null-extend the unmatched side
        rows for outer joins — ON-clause semantics, which a post-join filter
        cannot express for left/right/full joins (a failing pair must
        null-extend, not disappear). Residual references use post-join
        (renamed) column names; NULL residual results drop the pair
        (three-valued, like any SQL predicate). ``ldf``/``rdf`` are the slim
        key+row-id frames; residual inputs gather from the original arrays."""
        import pandas as pd

        from hyperspace_tpu.plan.expr import as_bool_mask

        pairs = ldf.merge(rdf, left_on=lkeys, right_on=rkeys, how="inner")
        if len(pairs):
            # only the referenced columns feed the predicate (the planner
            # resolved them to exact post-join names)
            refs = plan.residual.references()
            li = pairs["__lrow"].to_numpy()
            ri = pairs["__rrow"].to_numpy()
            batch = {}
            for c in refs:
                if c in pairs.columns:
                    batch[c] = pairs[c].to_numpy()
                elif c in left:
                    batch[c] = left[c][li]
                elif c in right_named:
                    batch[c] = right_named[c][ri]
            keep = as_bool_mask(plan.residual.eval(batch))
            # a constant residual (ON ... AND 1 = 0) evaluates 0-d: broadcast
            keep = np.broadcast_to(np.asarray(keep, dtype=bool), (len(pairs),))
            surviving = pairs[keep]
        else:
            surviving = pairs
        parts = [surviving]
        if plan.how in ("left", "outer"):
            lost = ldf[~np.isin(np.arange(len(ldf)), surviving["__lrow"].to_numpy())]
            parts.append(lost)  # right columns null-extend via concat
        if plan.how in ("right", "outer"):
            lost_r = rdf[~np.isin(np.arange(len(rdf)), surviving["__rrow"].to_numpy())]
            parts.append(lost_r)  # left columns null-extend
        return pd.concat(parts, ignore_index=True, sort=False) if len(parts) > 1 else surviving
