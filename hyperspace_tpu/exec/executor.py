"""Host-path physical executor.

Executes a (possibly index-rewritten) logical plan over pyarrow + numpy. This
is the correctness baseline and the non-indexed fallback; index-accelerated
scans and joins are dispatched to the TPU device path (exec/device.py) when a
session mesh is available.

The reference delegates all of this to Spark's physical planner/executors;
here the framework owns it (SURVEY.md §7 design stance).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow.dataset as pads

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import INPUT_FILE_NAME, Expr, InputFileName, extract_equi_join_keys


def _scan_identity(scan):
    """Stable identity of a scan's file set for device-side caching: any
    rewrite of a file (new index version, compaction) changes mtime/size and
    naturally invalidates. Returns None (= don't cache) when any file can't
    be stat'ed — a path-only key could serve stale device columns after an
    in-place rewrite."""
    import os

    parts = []
    for f in scan.files:
        try:
            st = os.stat(f)
        except OSError:
            return None
        parts.append((f, st.st_mtime_ns, st.st_size))
    return tuple(parts)


def _plan_needs_file_names(plan: L.LogicalPlan) -> bool:
    def expr_has(e: Expr) -> bool:
        if isinstance(e, InputFileName):
            return True
        return any(expr_has(c) for c in e.children())

    if isinstance(plan, L.Filter) and expr_has(plan.condition):
        return True
    return any(_plan_needs_file_names(c) for c in plan.children())


def _read_files(files: List[str], file_format: str, columns: Optional[List[str]], with_file_names: bool) -> B.Batch:
    from hyperspace_tpu.exec.io import read_parquet_batch

    if with_file_names:
        batches = []
        for f in files:
            if file_format == "parquet":
                b = read_parquet_batch([f], columns)
            else:
                b = B.table_to_batch(pads.dataset([f], format=file_format).to_table(columns=columns))
            b[INPUT_FILE_NAME] = np.full(B.num_rows(b), f, dtype=object)
            batches.append(b)
        return B.concat(batches)
    if file_format == "parquet":
        return read_parquet_batch(list(files), columns)
    t = pads.dataset(files, format=file_format).to_table(columns=columns)
    return B.table_to_batch(t)


class Executor:
    def __init__(self, session):
        self.session = session

    def execute(self, plan: L.LogicalPlan, required_columns: Optional[List[str]] = None) -> B.Batch:
        with_file_names = _plan_needs_file_names(plan)
        batch = self._exec(plan, with_file_names)
        if required_columns is not None:
            batch = B.select(batch, required_columns)
        elif INPUT_FILE_NAME in batch:
            batch = {k: v for k, v in batch.items() if k != INPUT_FILE_NAME}
        return batch

    def _exec(self, plan: L.LogicalPlan, with_file_names: bool) -> B.Batch:
        if isinstance(plan, L.Scan):
            rel = plan.relation
            files = [fi.name for fi in rel.all_file_infos()]
            return _read_files(files, rel.physical_format, None, with_file_names)

        if isinstance(plan, L.FileScan):
            return _read_files(list(plan.files), plan.file_format, list(plan.columns), with_file_names)

        if isinstance(plan, L.IndexScan):
            return _read_files(list(plan.files), "parquet", list(plan.columns), with_file_names)

        if isinstance(plan, L.Filter):
            child = self._exec(plan.child, with_file_names)
            mask = self._filter_mask(plan, child)
            return B.mask_rows(child, mask)

        if isinstance(plan, L.Project):
            child = self._exec(plan.child, with_file_names)
            cols = list(plan.columns)
            if with_file_names and INPUT_FILE_NAME in child:
                cols = cols + [INPUT_FILE_NAME]
            return B.select(child, cols)

        if isinstance(plan, L.Join):
            return self._exec_join(plan, with_file_names)

        if isinstance(plan, (L.Union, L.BucketUnion)):
            return B.concat([self._exec(c, with_file_names) for c in plan.children()])

        if isinstance(plan, L.Repartition):
            # Host path: in-memory data has no physical bucketing; pass through.
            return self._exec(plan.child, with_file_names)

        raise NotImplementedError(f"Cannot execute {type(plan).__name__}")

    def _filter_mask(self, plan: L.Filter, child: B.Batch) -> np.ndarray:
        """Predicate evaluation: device path over index/file scans when the
        session mesh is available, host numpy otherwise."""
        if (
            self.session.conf.device_execution_enabled
            and isinstance(plan.child, (L.IndexScan, L.FileScan))
            and B.num_rows(child) >= self.session.conf.device_exec_min_rows
        ):
            from hyperspace_tpu.exec import device as D

            try:
                return D.device_filter_mask(
                    self.session, child, plan.condition, scan_key=_scan_identity(plan.child)
                )
            except D.DeviceUnsupported:
                pass
        return np.asarray(plan.condition.eval(child), dtype=bool)

    def _exec_join(self, plan: L.Join, with_file_names: bool) -> B.Batch:
        import pandas as pd

        if not with_file_names and self.session.conf.device_execution_enabled:
            # deviceExecution=False is the kill switch back to the pandas
            # merge below — it routes around the whole bucketed-SMJ stack
            try:
                from hyperspace_tpu.exec import device as D
            except ImportError:
                D = None
            if D is not None:
                try:
                    return D.dispatch_bucketed_join(self.session, plan)
                except D.DeviceUnsupported:
                    pass

        pairs = extract_equi_join_keys(plan.condition)
        if pairs is None:
            raise NotImplementedError("Only conjunctive equi-joins are supported")
        left = self._exec(plan.left, with_file_names)
        right = self._exec(plan.right, with_file_names)
        left = {k: v for k, v in left.items() if k != INPUT_FILE_NAME}
        right = {k: v for k, v in right.items() if k != INPUT_FILE_NAME}

        left_cols = list(left)
        right_cols = list(right)
        # validate key sides (columns may arrive swapped from the user)
        lkeys, rkeys = [], []
        for a, b in pairs:
            if a in left_cols and b in right_cols:
                lkeys.append(a)
                rkeys.append(b)
            elif b in left_cols and a in right_cols:
                lkeys.append(b)
                rkeys.append(a)
            else:
                raise ValueError(f"Join keys ({a}, {b}) not found in the two sides")

        # rename duplicated right-side columns up front so every output column
        # (including unmatched-row nulls on outer joins) comes straight out of
        # the merge result
        rename = {c: f"{c}#r" for c in right_cols if c in left_cols}
        ldf = pd.DataFrame(left)
        rdf = pd.DataFrame(right).rename(columns=rename)
        rkeys_renamed = [rename.get(k, k) for k in rkeys]
        merged = ldf.merge(rdf, left_on=lkeys, right_on=rkeys_renamed, how=plan.how)
        out: B.Batch = {}
        for name in plan.output_columns:
            if name not in merged.columns:
                raise KeyError(f"Join output column {name!r} missing")
            out[name] = merged[name].to_numpy()
        return out
