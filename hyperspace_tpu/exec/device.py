"""TPU device execution path.

The two accelerated physical patterns (the ones the optimizer rewrites plans
into — SURVEY.md §3.2):

  1. ``Filter`` over an ``IndexScan``/``FileScan`` — the predicate tree is
     compiled to a jitted jnp program evaluated over encoded device columns,
     sharded row-wise over the session mesh (replaces Spark's
     per-bucket-parquet-scan + codegen'd filter; ref:
     HS/index/covering/FilterIndexRule.scala:144-194).
  2. Bucketed equi-``Join`` of two compatible ``IndexScan``s — both sides are
     pre-bucketed and pre-sorted on the join keys, so the join runs per-bucket
     with **no collectives**: a shard_map over the bucket axis where each
     device merge-joins its co-located buckets via two vmapped searchsorted
     passes (replaces Spark's exchange-free sort-merge join; ref:
     HS/index/covering/JoinIndexRule.scala:604-618).

Strings are dictionary-encoded host-side (exec/batch.py docstring); predicate
literals are translated into code-space via the sorted dictionary, so <, <=,
=, >=, > on strings all lower to integer compares on device.

Anything the device path cannot express raises ``DeviceUnsupported`` and the
host executor (exec/executor.py) runs the plan instead — mirroring how
``ApplyHyperspace`` never fails a query (ref: HS/index/rules/ApplyHyperspace.scala:59-63).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax

# int64 keys/sentinels require x64 even in query-only processes that never
# import the build-path modules (ops/sort.py sets it for builds)
jax.config.update("jax_enable_x64", True)

import numpy as np

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    BinaryOp,
    Col,
    Expr,
    In,
    InputFileName,
    IsNull,
    Lit,
    Not,
    extract_equi_join_keys,
)


class DeviceUnsupported(Exception):
    """Raised when an expression/plan shape cannot run on the device path."""


# --------------------------------------------------------------------------
# column encoding
# --------------------------------------------------------------------------


class ColumnCodec:
    """How one host column was encoded for the device.

    kind:
      - "numeric":  device array is the column itself (int64/float64/bool)
      - "datetime": device array is the int64 epoch view; ``unit`` remembers
                    the datetime64 unit for literal conversion
      - "string":   device array is int32 codes into ``uniques`` (sorted);
                    code -1 encodes null
    """

    def __init__(self, kind: str, uniques: Optional[np.ndarray] = None, unit: Optional[str] = None):
        self.kind = kind
        self.uniques = uniques
        self.unit = unit


def encode_column(arr: np.ndarray) -> Tuple[np.ndarray, ColumnCodec]:
    kind = arr.dtype.kind
    if kind in ("i", "u", "b"):
        return arr.astype(np.int64), ColumnCodec("numeric")
    if kind == "f":
        return arr.astype(np.float64), ColumnCodec("numeric")
    if kind == "M":
        unit = np.datetime_data(arr.dtype)[0]
        return arr.view("int64").astype(np.int64), ColumnCodec("datetime", unit=unit)
    if kind in ("U", "S", "O"):
        from hyperspace_tpu.ops.encode import factorize_strings

        codes, uniques, _ = factorize_strings(arr)
        return codes.astype(np.int32), ColumnCodec("string", uniques=uniques)
    raise DeviceUnsupported(f"unsupported column dtype {arr.dtype}")


def _literal_bounds(codec: ColumnCodec, value) -> Tuple[int, int]:
    """(lo, hi) code bounds of a literal in a string dictionary:
    col == lit ⇔ lo <= code < hi;  col < lit ⇔ code < lo;  col <= lit ⇔ code < hi."""
    lo = int(np.searchsorted(codec.uniques, str(value), side="left"))
    hi = int(np.searchsorted(codec.uniques, str(value), side="right"))
    return lo, hi


def _literal_numeric(codec: ColumnCodec, value):
    if codec.kind == "datetime":
        return int(np.datetime64(value, codec.unit).view("int64"))
    if isinstance(value, (bool, np.bool_)):
        return int(value)
    return value


# --------------------------------------------------------------------------
# predicate compiler: Expr tree -> jnp program over encoded columns
# --------------------------------------------------------------------------

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}


def compile_predicate(expr: Expr, codecs: Dict[str, ColumnCodec]):
    """Compile ``expr`` into ``f(cols: dict[str, jnp.ndarray]) -> bool mask``.

    Raises DeviceUnsupported for shapes outside the device language (string
    arithmetic, input_file_name(), col-vs-col string compares, ...).
    """
    import jax.numpy as jnp

    def is_string_col(e: Expr) -> bool:
        return isinstance(e, Col) and codecs[e.name].kind == "string"

    def build_num(e: Expr):
        """Numeric-valued subexpression -> device fn."""
        if isinstance(e, Col):
            codec = codecs[e.name]
            if codec.kind == "string":
                raise DeviceUnsupported("string column used in numeric context")
            name = e.name
            return lambda cols: cols[name]
        if isinstance(e, Lit):
            v = e.value
            if isinstance(v, str):
                raise DeviceUnsupported("string literal in numeric context")
            if isinstance(v, np.datetime64):
                v = int(v.view("int64"))
            return lambda cols, v=v: v
        if isinstance(e, BinaryOp) and e.op in ("+", "-", "*", "/", "%"):
            lf, rf = build_num(e.left), build_num(e.right)
            op = e.op
            def f(cols):
                l, r = lf(cols), rf(cols)
                if op == "+":
                    return l + r
                if op == "-":
                    return l - r
                if op == "*":
                    return l * r
                if op == "/":
                    return l / r
                return l % r
            return f
        raise DeviceUnsupported(f"unsupported numeric expr {type(e).__name__}")

    def string_compare(col: Col, op: str, lit_value) -> "callable":
        codec = codecs[col.name]
        if codec.kind != "string" or not isinstance(lit_value, str):
            # mixed-type compares have host-defined semantics; don't guess
            raise DeviceUnsupported("string compare requires string column and string literal")
        lo, hi = _literal_bounds(codec, lit_value)
        name = col.name
        if op == "=":
            return lambda cols: (cols[name] >= lo) & (cols[name] < hi)
        if op == "!=":
            # null codes (-1) satisfy != like the host's elementwise None != "x"
            return lambda cols: (cols[name] < lo) | (cols[name] >= hi)
        if op == "<":
            return lambda cols: (cols[name] < lo) & (cols[name] >= 0)
        if op == "<=":
            return lambda cols: (cols[name] < hi) & (cols[name] >= 0)
        if op == ">":
            return lambda cols: cols[name] >= hi
        if op == ">=":
            return lambda cols: cols[name] >= lo
        raise DeviceUnsupported(f"unsupported string compare {op}")

    def build_bool(e: Expr):
        if isinstance(e, BinaryOp) and e.op in ("AND", "OR"):
            lf, rf = build_bool(e.left), build_bool(e.right)
            if e.op == "AND":
                return lambda cols: lf(cols) & rf(cols)
            return lambda cols: lf(cols) | rf(cols)
        if isinstance(e, Not):
            cf = build_bool(e.child)
            return lambda cols: ~cf(cols)
        if isinstance(e, IsNull):
            c = e.child
            if isinstance(c, Col):
                codec = codecs[c.name]
                name = c.name
                if codec.kind == "string":
                    return lambda cols: cols[name] < 0
                if codec.kind == "numeric":
                    return lambda cols: jnp.isnan(cols[name]) if cols[name].dtype == jnp.float64 else jnp.zeros(cols[name].shape, bool)
                return lambda cols: jnp.zeros(cols[name].shape, bool)
            raise DeviceUnsupported("IS NULL on non-column")
        if isinstance(e, In):
            child = e.child
            if not isinstance(child, Col):
                raise DeviceUnsupported("IN on non-column")
            values = [v.value for v in e.values]
            if not values:
                raise DeviceUnsupported("empty IN list")
            if is_string_col(child):
                if not all(isinstance(v, str) for v in values):
                    raise DeviceUnsupported("mixed-type IN on string column")
            elif any(isinstance(v, str) for v in values):
                raise DeviceUnsupported("string IN value on non-string column")
            terms = []
            for val in values:
                if is_string_col(child):
                    terms.append(string_compare(child, "=", val))
                else:
                    cf = build_num(child)
                    num = _literal_numeric(codecs[child.name], val)
                    terms.append(lambda cols, cf=cf, num=num: cf(cols) == num)
            def f(cols):
                m = terms[0](cols)
                for t in terms[1:]:
                    m = m | t(cols)
                return m
            return f
        if isinstance(e, BinaryOp) and e.op in ("=", "!=", "<", "<=", ">", ">="):
            left, right, op = e.left, e.right, e.op
            # normalize: Col OP Lit
            if isinstance(right, Col) and isinstance(left, Lit):
                left, right, op = right, left, _FLIP[op]
            if isinstance(left, Col) and isinstance(right, Lit):
                codec = codecs[left.name]
                if codec.kind == "string" or isinstance(right.value, str):
                    if codec.kind != "string":
                        raise DeviceUnsupported("string literal vs non-string column")
                    return string_compare(left, op, right.value)
                lf = build_num(left)
                val = _literal_numeric(codec, right.value)
                return _compare(lf, lambda cols, val=val: val, op)
            # general numeric compare (col-vs-col, arithmetic)
            return _compare(build_num(left), build_num(right), op)
        if isinstance(e, InputFileName):
            raise DeviceUnsupported("input_file_name() is host-only")
        raise DeviceUnsupported(f"unsupported boolean expr {type(e).__name__}")

    def _compare(lf, rf, op: str):
        if op == "=":
            return lambda cols: lf(cols) == rf(cols)
        if op == "!=":
            return lambda cols: lf(cols) != rf(cols)
        if op == "<":
            return lambda cols: lf(cols) < rf(cols)
        if op == "<=":
            return lambda cols: lf(cols) <= rf(cols)
        if op == ">":
            return lambda cols: lf(cols) > rf(cols)
        return lambda cols: lf(cols) >= rf(cols)

    return build_bool(expr)


# --------------------------------------------------------------------------
# device filter
# --------------------------------------------------------------------------


def _pad_to_multiple(arr: np.ndarray, m: int, fill) -> np.ndarray:
    n = arr.shape[0]
    rem = (-n) % m
    if rem == 0:
        return arr
    pad = np.full((rem,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


def device_filter_mask(session, batch: B.Batch, condition: Expr) -> np.ndarray:
    """Evaluate ``condition`` on device over the referenced columns of
    ``batch``; returns the host bool mask. Raises DeviceUnsupported when the
    predicate is outside the device language."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    refs = sorted(condition.references())
    for r in refs:
        if r not in batch:
            raise DeviceUnsupported(f"referenced column {r!r} missing from batch")
    n = B.num_rows(batch)
    if n == 0:
        return np.zeros(0, dtype=bool)

    encoded: Dict[str, np.ndarray] = {}
    codecs: Dict[str, ColumnCodec] = {}
    for r in refs:
        encoded[r], codecs[r] = encode_column(batch[r])
    fn = compile_predicate(condition, codecs)

    mesh = session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    dev_cols = {
        k: jax.device_put(_pad_to_multiple(v, n_dev, 0 if v.dtype != np.float64 else np.nan), sharding)
        for k, v in encoded.items()
    }

    mask = jax.jit(fn)(dev_cols)
    return np.asarray(mask)[:n]


# --------------------------------------------------------------------------
# bucketed shuffle-free merge join
# --------------------------------------------------------------------------


def _strip_projects(plan: L.LogicalPlan) -> Tuple[L.LogicalPlan, Optional[List[str]]]:
    cols = None
    while isinstance(plan, L.Project):
        cols = list(plan.columns) if cols is None else cols
        plan = plan.child
    return plan, cols


def join_sides_compatible(plan: L.Join) -> Optional[Tuple[L.IndexScan, L.IndexScan, List[str], List[str]]]:
    """If both join children are (projected) IndexScans bucketed on exactly the
    join keys with equal bucket counts, return (left_scan, right_scan, lkeys,
    rkeys); else None (ref: JoinIndexRanker's equal-bucket preference,
    HS/index/covering/JoinIndexRanker.scala:52-92)."""
    pairs = extract_equi_join_keys(plan.condition)
    if not pairs:
        return None
    lchild, _ = _strip_projects(plan.left)
    rchild, _ = _strip_projects(plan.right)
    if not isinstance(lchild, L.IndexScan) or not isinstance(rchild, L.IndexScan):
        return None
    lspec, rspec = lchild.bucket_spec, rchild.bucket_spec
    if lspec is None or rspec is None or lspec.num_buckets != rspec.num_buckets:
        return None
    lcols = set(lchild.columns)
    rcols = set(rchild.columns)
    lkeys, rkeys = [], []
    for a, b in pairs:
        if a in lcols and b in rcols:
            lkeys.append(a)
            rkeys.append(b)
        elif b in lcols and a in rcols:
            lkeys.append(b)
            rkeys.append(a)
        else:
            return None
    if list(lspec.bucket_columns) != lkeys or list(rspec.bucket_columns) != rkeys:
        return None
    return lchild, rchild, lkeys, rkeys


def _read_buckets(scan: L.IndexScan, columns: List[str], sort_key: Optional[str] = None) -> Dict[int, B.Batch]:
    """Read an IndexScan's files grouped per bucket id (file name carries the
    bucket; ref layout: part-<bucket>.parquet, indexes/covering.py).

    Only ``columns`` are decoded. When ``sort_key`` is given, each bucket is
    re-sorted on it if needed: a bucket holding several files (incremental
    refresh merges delta files into existing buckets, UpdateMode.Merge —
    ref: actions/RefreshIncrementalAction.scala:115-128) is only piecewise
    sorted after concatenation."""
    from hyperspace_tpu.indexes.covering import bucket_of_file

    per_bucket: Dict[int, List[str]] = {}
    for f in scan.files:
        b = bucket_of_file(f)
        if b is None:
            raise DeviceUnsupported(f"index file {f!r} has no bucket id")
        per_bucket.setdefault(b, []).append(f)
    from hyperspace_tpu.exec.io import read_parquet_batch

    out: Dict[int, B.Batch] = {}
    for b, files in per_bucket.items():
        batch = read_parquet_batch(files, columns)
        if sort_key is not None and len(files) > 1:
            k = batch[sort_key]
            if k.size > 1 and np.any(k[1:] < k[:-1]):
                batch = B.take(batch, np.argsort(k, kind="stable"))
        out[b] = batch
    return out


def device_bucketed_join(session, plan: L.Join) -> B.Batch:
    """Execute a compatible bucketed equi-join on device.

    Per-bucket sorted runs of both sides are padded to rectangles, sharded over
    the mesh's bucket axis, and each device computes, for every left row, the
    [lo, hi) span of matching right rows via two vmapped ``searchsorted``
    passes — no collective is emitted (the reference's no-exchange SMJ,
    HS/index/covering/JoinIndexRule.scala:604-618). Pair expansion and column
    gathering happen host-side (variable-size output).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from hyperspace_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    compat = join_sides_compatible(plan)
    if compat is None:
        raise DeviceUnsupported("join sides are not compatible bucketed index scans")
    lscan, rscan, lkeys, rkeys = compat
    if len(lkeys) != 1:
        raise DeviceUnsupported("device join supports single-key equi-joins (multi-key -> host)")
    lkey, rkey = lkeys[0], rkeys[0]
    if plan.how != "inner":
        raise DeviceUnsupported("device join handles inner joins (outer -> host)")

    # key dtype check from parquet metadata BEFORE any data is decoded — an
    # unsupported key must not cost a full read on both sides
    import pyarrow as pa
    import pyarrow.parquet as pq

    for scan, key in ((lscan, lkey), (rscan, rkey)):
        if not scan.files:
            raise DeviceUnsupported("empty index scan")
        field = pq.read_schema(scan.files[0]).field(key)
        if not (pa.types.is_integer(field.type) or pa.types.is_temporal(field.type) or pa.types.is_boolean(field.type)):
            raise DeviceUnsupported(f"device join requires integer/datetime keys; got {field.type}")

    # decode only the columns the join output (plus keys) needs
    needed = set(plan.output_columns) | {n[:-2] for n in plan.output_columns if n.endswith("#r")}
    lcols_needed = [c for c in lscan.columns if c in needed or c == lkey]
    rcols_needed = [c for c in rscan.columns if c in needed or c == rkey]
    lbuckets = _read_buckets(lscan, lcols_needed, sort_key=lkey)
    rbuckets = _read_buckets(rscan, rcols_needed, sort_key=rkey)
    nb = lscan.bucket_spec.num_buckets

    # Encode keys; only identity-ordered encodings are cross-side comparable.
    def key_of(batch: B.Batch, key: str) -> np.ndarray:
        arr = batch[key]
        if arr.dtype.kind in ("i", "u", "b"):
            return arr.astype(np.int64)
        if arr.dtype.kind == "M":
            return arr.view("int64").astype(np.int64)
        raise DeviceUnsupported(f"device join requires integer/datetime keys; got {arr.dtype}")

    SENTINEL = np.int64(2**62)
    mesh = session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    nb_padded = nb + ((-nb) % n_dev)

    def stack_side(buckets: Dict[int, B.Batch], key: str):
        lens = [B.num_rows(buckets[b]) if b in buckets else 0 for b in range(nb_padded)]
        width = max(max(lens), 1)
        keys_mat = np.full((nb_padded, width), SENTINEL, dtype=np.int64)
        for b in range(nb_padded):
            if lens[b]:
                keys_mat[b, : lens[b]] = key_of(buckets[b], key)
        return keys_mat, np.asarray(lens, dtype=np.int64)

    lmat, llens = stack_side(lbuckets, lkey)
    rmat, rlens = stack_side(rbuckets, rkey)

    sharding = NamedSharding(mesh, P(axis))

    @jax.jit
    def spans(lm, rm):
        @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis)))
        def per_shard(lm_, rm_):
            lo = jax.vmap(lambda lk, rk: jnp.searchsorted(rk, lk, side="left"))(lm_, rm_)
            hi = jax.vmap(lambda lk, rk: jnp.searchsorted(rk, lk, side="right"))(lm_, rm_)
            return lo, hi
        return per_shard(lm, rm)

    lo, hi = spans(jax.device_put(lmat, sharding), jax.device_put(rmat, sharding))
    lo = np.asarray(lo)
    hi = np.asarray(hi)

    # host-side pair expansion (variable-size output) + column gather
    out_batches: List[B.Batch] = []
    out_cols = plan.output_columns
    lout = list(lcols_needed)
    rout = list(rcols_needed)
    for b in range(nb):
        ll = int(llens[b])
        if ll == 0 or int(rlens[b]) == 0:
            continue
        counts = (hi[b, :ll] - lo[b, :ll]).astype(np.int64)
        total = int(counts.sum())
        if total == 0:
            continue
        lidx = np.repeat(np.arange(ll), counts)
        # right indices: for row i, lo[i] .. hi[i]-1
        offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
        ridx = np.arange(total) - np.repeat(offsets, counts) + np.repeat(lo[b, :ll], counts)
        lb, rb = lbuckets[b], rbuckets[b]
        out: B.Batch = {}
        for name in out_cols:
            if name in lout:
                out[name] = lb[name][lidx]
            elif name.endswith("#r") and name[:-2] in rout:
                out[name] = rb[name[:-2]][ridx]
            elif name in rout:
                out[name] = rb[name][ridx]
            else:
                raise DeviceUnsupported(f"join output column {name!r} not found on either side")
        out_batches.append(out)
    if not out_batches:
        # preserve real column dtypes in the empty result
        def empty_like(name: str) -> np.ndarray:
            if name in lout:
                src, col = lbuckets, name
            else:
                src, col = rbuckets, name[:-2] if name.endswith("#r") else name
            for b in src.values():
                if col in b:
                    return np.empty(0, dtype=b[col].dtype)
            raise DeviceUnsupported(f"cannot determine dtype of empty join column {name!r}")

        return {name: empty_like(name) for name in out_cols}
    return B.concat(out_batches)
