"""TPU device execution path.

The two accelerated physical patterns (the ones the optimizer rewrites plans
into — SURVEY.md §3.2):

  1. ``Filter`` over an ``IndexScan``/``FileScan`` — the predicate tree is
     compiled to a jitted jnp program evaluated over encoded device columns,
     sharded row-wise over the session mesh (replaces Spark's
     per-bucket-parquet-scan + codegen'd filter; ref:
     HS/index/covering/FilterIndexRule.scala:144-194).
  2. Bucketed equi-``Join`` of two compatible ``IndexScan``s — both sides are
     pre-bucketed and pre-sorted on the join keys, so the join runs per-bucket
     with **no collectives**: a shard_map over the bucket axis where each
     device merge-joins its co-located buckets via two vmapped searchsorted
     passes (replaces Spark's exchange-free sort-merge join; ref:
     HS/index/covering/JoinIndexRule.scala:604-618).

Strings are dictionary-encoded host-side (exec/batch.py docstring); predicate
literals are translated into code-space via the sorted dictionary, so <, <=,
=, >=, > on strings all lower to integer compares on device.

Anything the device path cannot express raises ``DeviceUnsupported`` and the
host executor (exec/executor.py) runs the plan instead — mirroring how
``ApplyHyperspace`` never fails a query (ref: HS/index/rules/ApplyHyperspace.scala:59-63).
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax

from hyperspace_tpu.utils.x64 import ensure_x64

import numpy as np

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.plan import logical as L
from hyperspace_tpu.plan.expr import (
    BinaryOp,
    Col,
    Expr,
    In,
    InputFileName,
    IsNull,
    Lit,
    Not,
    extract_equi_join_keys,
)


class DeviceUnsupported(Exception):
    """Raised when an expression/plan shape cannot run on the device path."""


class GroupCapacityExceeded(DeviceUnsupported):
    """Observed group cardinality exceeds conf ``hyperspace.exec.agg.maxGroups``
    — the caller spills to the host hash-combine path (the accumulated device
    partial stays valid; see ``GroupedAggStream.to_partial_frame``)."""


# --------------------------------------------------------------------------
# column encoding
# --------------------------------------------------------------------------


class ColumnCodec:
    """How one host column was encoded for the device.

    kind:
      - "numeric":  device array is the column itself (int64/float64/bool)
      - "datetime": device array is the int64 epoch view; ``unit`` remembers
                    the datetime64 unit for literal conversion
      - "string":   device array is int32 codes into ``uniques`` (sorted);
                    code -1 encodes null
    """

    def __init__(self, kind: str, uniques: Optional[np.ndarray] = None, unit: Optional[str] = None):
        self.kind = kind
        self.uniques = uniques
        self.unit = unit


def encode_column(arr: np.ndarray) -> Tuple[np.ndarray, ColumnCodec]:
    # already-device-dtype columns pass through uncopied (asarray/view, not
    # astype): the native decode fast path hands us prefix views of padded
    # buffers, and a copy here would break the zero-copy staging handoff
    kind = arr.dtype.kind
    if kind in ("i", "u", "b"):
        return np.asarray(arr, dtype=np.int64), ColumnCodec("numeric")
    if kind == "f":
        return np.asarray(arr, dtype=np.float64), ColumnCodec("numeric")
    if kind == "M":
        unit = np.datetime_data(arr.dtype)[0]
        return arr.view("int64"), ColumnCodec("datetime", unit=unit)
    if kind in ("U", "S", "O"):
        from hyperspace_tpu.ops.encode import factorize_strings

        codes, uniques, _ = factorize_strings(arr)
        return codes.astype(np.int32), ColumnCodec("string", uniques=uniques)
    raise DeviceUnsupported(f"unsupported column dtype {arr.dtype}")


def _literal_bounds(codec: ColumnCodec, value) -> Tuple[int, int]:
    """(lo, hi) code bounds of a literal in a string dictionary:
    col == lit ⇔ lo <= code < hi;  col < lit ⇔ code < lo;  col <= lit ⇔ code < hi."""
    lo = int(np.searchsorted(codec.uniques, str(value), side="left"))
    hi = int(np.searchsorted(codec.uniques, str(value), side="right"))
    return lo, hi


def _literal_numeric(codec: ColumnCodec, value):
    if codec.kind == "datetime":
        return int(np.datetime64(value, codec.unit).view("int64"))
    if isinstance(value, (bool, np.bool_)):
        return int(value)
    return value


# --------------------------------------------------------------------------
# predicate compiler: Expr tree -> jnp program over encoded columns
#
# Literal values (and string-dictionary code bounds, which change per batch)
# are *runtime arguments* of the compiled program, not trace-time constants,
# so two queries that differ only in their constants (or dictionaries) hit
# the same XLA executable. The jitted program is cached per predicate
# *skeleton* (structure + column kinds, no literal values).
# --------------------------------------------------------------------------

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}


class _LitSlots:
    """Collects literal values during compilation; each gets a slot index in
    the ``lits`` tuple passed to the compiled program at call time."""

    def __init__(self):
        self.values: List = []

    def add(self, value) -> int:
        self.values.append(value)
        return len(self.values) - 1


def predicate_skeleton(expr: Expr, codecs: Dict[str, ColumnCodec]) -> str:
    """Canonical structure of ``expr`` with literal *values* erased — the
    cache key for the jitted program (literals are runtime args)."""

    def lit_tag(v) -> str:
        if isinstance(v, str):
            return "s"
        if isinstance(v, (bool, np.bool_)):
            return "b"
        if isinstance(v, (int, np.integer)):
            return "i"
        if isinstance(v, np.datetime64):
            return "d"
        return "f"

    def rec(e: Expr) -> str:
        if isinstance(e, Col):
            return f"c:{e.name}:{codecs[e.name].kind if e.name in codecs else '?'}"
        if isinstance(e, Lit):
            return f"l:{lit_tag(e.value)}"
        if isinstance(e, BinaryOp):
            return f"({rec(e.left)}{e.op}{rec(e.right)})"
        if isinstance(e, Not):
            return f"!({rec(e.child)})"
        if isinstance(e, IsNull):
            return f"isnull({rec(e.child)})"
        if isinstance(e, In):
            return f"in({rec(e.child)},[{','.join(rec(v) for v in e.values)}])"
        if isinstance(e, InputFileName):
            return "input_file_name()"
        return f"{type(e).__name__}({','.join(rec(c) for c in e.children())})"

    return rec(expr)


def compile_predicate(expr: Expr, codecs: Dict[str, ColumnCodec]):
    """Compile ``expr`` into ``(f, lit_values)`` where
    ``f(cols: dict[str, jnp.ndarray], lits: tuple) -> bool mask`` and
    ``lit_values`` is the concrete argument tuple for this query.

    Raises DeviceUnsupported for shapes outside the device language (string
    arithmetic, input_file_name(), col-vs-col string compares, ...).
    """
    import jax.numpy as jnp

    slots = _LitSlots()

    def is_string_col(e: Expr) -> bool:
        return isinstance(e, Col) and codecs[e.name].kind == "string"

    def _const_subtree(e: Expr) -> bool:
        if isinstance(e, Lit):
            return True
        if isinstance(e, BinaryOp) and e.op in ("+", "-", "*", "/", "%"):
            return _const_subtree(e.left) and _const_subtree(e.right)
        return False

    def _fold_const(e: Expr) -> Expr:
        """Fold literal-only arithmetic on host: calendar-unit intervals
        (date '1994-01-01' + interval '1' year => timedelta64[M]) have no
        JAX dtype, but their folded result is a plain datetime scalar."""
        if isinstance(e, Lit) or not _const_subtree(e):
            return e
        v = e.eval({})
        arr = np.asarray(v)
        return Lit(arr.reshape(-1)[0] if arr.ndim else arr[()])

    def _has_datetime(e: Expr) -> bool:
        if isinstance(e, Col):
            return codecs[e.name].kind == "datetime"
        if isinstance(e, Lit):
            return isinstance(e.value, (np.datetime64, np.timedelta64))
        return any(_has_datetime(c) for c in e.children())

    def build_num(e: Expr):
        """Numeric-valued subexpression -> device fn."""
        e = _fold_const(e)
        if isinstance(e, Col):
            codec = codecs[e.name]
            if codec.kind == "string":
                raise DeviceUnsupported("string column used in numeric context")
            name = e.name
            return lambda cols, lits: cols[name]
        if isinstance(e, Lit):
            v = e.value
            if isinstance(v, str):
                raise DeviceUnsupported("string literal in numeric context")
            if isinstance(v, np.datetime64):
                v = int(v.view("int64"))
            i = slots.add(_as_lit_scalar(v))
            return lambda cols, lits: lits[i]
        if isinstance(e, BinaryOp) and e.op in ("+", "-", "*", "/", "%"):
            lf, rf = build_num(e.left), build_num(e.right)
            op = e.op
            def f(cols, lits):
                l, r = lf(cols, lits), rf(cols, lits)
                if op == "+":
                    return l + r
                if op == "-":
                    return l - r
                if op == "*":
                    return l * r
                if op == "/":
                    return l / r
                return l % r
            return f
        raise DeviceUnsupported(f"unsupported numeric expr {type(e).__name__}")

    # Boolean subtrees compile to (value, unknown) Kleene pairs so NULL stays
    # three-valued on device exactly as on host (expr.NullableBool): a NULL
    # operand makes the comparison unknown — in particular NULL != x and
    # NOT(NULL = x) must not come out true. The top level keeps definite-TRUE
    # rows only (value & ~unknown).
    def string_compare(col: Col, op: str, lit_value):
        codec = codecs[col.name]
        if codec.kind != "string" or not isinstance(lit_value, str):
            # mixed-type compares have host-defined semantics; don't guess
            raise DeviceUnsupported("string compare requires string column and string literal")
        lo_v, hi_v = _literal_bounds(codec, lit_value)
        lo = slots.add(np.int32(lo_v))
        hi = slots.add(np.int32(hi_v))
        name = col.name
        unknown = lambda cols, lits: cols[name] < 0  # null code is -1
        if op == "=":
            return (lambda cols, lits: (cols[name] >= lits[lo]) & (cols[name] < lits[hi]), unknown)
        if op == "!=":
            return (lambda cols, lits: (cols[name] < lits[lo]) | (cols[name] >= lits[hi]), unknown)
        if op == "<":
            return (lambda cols, lits: cols[name] < lits[lo], unknown)
        if op == "<=":
            return (lambda cols, lits: cols[name] < lits[hi], unknown)
        if op == ">":
            return (lambda cols, lits: cols[name] >= lits[hi], unknown)
        if op == ">=":
            return (lambda cols, lits: cols[name] >= lits[lo], unknown)
        raise DeviceUnsupported(f"unsupported string compare {op}")

    def _num_unknown(x):
        return jnp.isnan(x) if jnp.issubdtype(x.dtype, jnp.floating) else jnp.zeros(jnp.shape(x), bool)

    _NAT = np.iinfo(np.int64).min  # NaT under the int64 epoch view

    def num_unknown_expr(e: Expr):
        """Missing-value mask of a numeric-valued subexpression: NaN for
        float columns, NaT (INT64_MIN epoch view) for datetime columns,
        propagated through arithmetic."""
        if isinstance(e, Col):
            codec = codecs[e.name]
            name = e.name
            if codec.kind == "datetime":
                return lambda cols, lits: cols[name] == _NAT
            return lambda cols, lits: _num_unknown(cols[name])
        if isinstance(e, BinaryOp) and e.op in ("+", "-", "*", "/", "%"):
            lu, ru = num_unknown_expr(e.left), num_unknown_expr(e.right)
            return lambda cols, lits: lu(cols, lits) | ru(cols, lits)
        return lambda cols, lits: jnp.zeros((), bool)

    def _compare(lf, rf, op: str, lu=None, ru=None):
        def value(cols, lits):
            l, r = lf(cols, lits), rf(cols, lits)
            if op == "=":
                return l == r
            if op == "!=":
                return l != r
            if op == "<":
                return l < r
            if op == "<=":
                return l <= r
            if op == ">":
                return l > r
            return l >= r

        def unknown(cols, lits):
            u = _num_unknown(lf(cols, lits)) | _num_unknown(rf(cols, lits))
            if lu is not None:
                u = u | lu(cols, lits)
            if ru is not None:
                u = u | ru(cols, lits)
            return u

        return value, unknown

    def build_bool(e: Expr):
        if isinstance(e, BinaryOp) and e.op in ("AND", "OR"):
            (lv, lu), (rv, ru) = build_bool(e.left), build_bool(e.right)
            if e.op == "AND":
                # unknown unless either side is definitely false
                return (
                    lambda cols, lits: lv(cols, lits) & rv(cols, lits),
                    lambda cols, lits: (lu(cols, lits) | ru(cols, lits))
                    & ~(~lv(cols, lits) & ~lu(cols, lits))
                    & ~(~rv(cols, lits) & ~ru(cols, lits)),
                )
            return (
                lambda cols, lits: (lv(cols, lits) & ~lu(cols, lits))
                | (rv(cols, lits) & ~ru(cols, lits)),
                lambda cols, lits: (lu(cols, lits) | ru(cols, lits))
                & ~(lv(cols, lits) & ~lu(cols, lits))
                & ~(rv(cols, lits) & ~ru(cols, lits)),
            )
        if isinstance(e, Not):
            cv, cu = build_bool(e.child)
            return (lambda cols, lits: ~cv(cols, lits), cu)
        if isinstance(e, IsNull):
            c = e.child
            if isinstance(c, Col):
                codec = codecs[c.name]
                name = c.name
                no_unknown = lambda cols, lits: jnp.zeros(cols[name].shape, bool)
                if codec.kind == "string":
                    return (lambda cols, lits: cols[name] < 0, no_unknown)
                if codec.kind == "numeric":
                    return (
                        lambda cols, lits: jnp.isnan(cols[name])
                        if cols[name].dtype == jnp.float64
                        else jnp.zeros(cols[name].shape, bool),
                        no_unknown,
                    )
                if codec.kind == "datetime":  # NaT under the int64 epoch view
                    nat = np.iinfo(np.int64).min
                    return (lambda cols, lits: cols[name] == nat, no_unknown)
                return (lambda cols, lits: jnp.zeros(cols[name].shape, bool), no_unknown)
            raise DeviceUnsupported("IS NULL on non-column")
        if isinstance(e, In):
            child = e.child
            if not isinstance(child, Col):
                raise DeviceUnsupported("IN on non-column")
            values = [v.value for v in e.values]
            if not values:
                raise DeviceUnsupported("empty IN list")
            if any(v is None or (isinstance(v, float) and v != v) for v in values):
                # NULL in the list makes non-matches unknown (host
                # _in_semantics); keep that shape host-side
                raise DeviceUnsupported("NULL literal in IN list")
            if is_string_col(child):
                if not all(isinstance(v, str) for v in values):
                    raise DeviceUnsupported("mixed-type IN on string column")
            elif any(isinstance(v, str) for v in values):
                raise DeviceUnsupported("string IN value on non-string column")
            terms = []
            for val in values:
                if is_string_col(child):
                    terms.append(string_compare(child, "=", val))
                else:
                    cf = build_num(child)
                    num = _literal_numeric(codecs[child.name], val)
                    i = slots.add(_as_lit_scalar(num))
                    terms.append(
                        _compare(cf, lambda cols, lits, i=i: lits[i], "=", lu=num_unknown_expr(child))
                    )

            def value(cols, lits):
                m = terms[0][0](cols, lits)
                for tv, _ in terms[1:]:
                    m = m | tv(cols, lits)
                return m

            return value, terms[0][1]  # all terms share the child's null mask
        if isinstance(e, BinaryOp) and e.op in ("=", "!=", "<", "<=", ">", ">="):
            left, right, op = e.left, e.right, e.op
            # fold literal-only sides FIRST so a folded datetime constant
            # takes the Col-vs-Lit path below, where _literal_numeric
            # converts it to the column codec's epoch unit
            left, right = _fold_const(left), _fold_const(right)
            # normalize: Col OP Lit
            if isinstance(right, Col) and isinstance(left, Lit):
                left, right, op = right, left, _FLIP[op]
            if isinstance(left, Col) and isinstance(right, Lit):
                codec = codecs[left.name]
                if codec.kind == "string" or isinstance(right.value, str):
                    if codec.kind != "string":
                        raise DeviceUnsupported("string literal vs non-string column")
                    return string_compare(left, op, right.value)
                lf = build_num(left)
                val = _literal_numeric(codec, right.value)
                i = slots.add(_as_lit_scalar(val))
                return _compare(lf, lambda cols, lits: lits[i], op, lu=num_unknown_expr(left))
            # general numeric compare (col-vs-col, arithmetic): datetime
            # operands have per-column epoch units the generic path cannot
            # reconcile — reject rather than compare mismatched units
            for side in (left, right):
                if _has_datetime(side):
                    raise DeviceUnsupported("datetime arithmetic compare on device")
            return _compare(
                build_num(left), build_num(right), op,
                lu=num_unknown_expr(left), ru=num_unknown_expr(right),
            )
        if isinstance(e, InputFileName):
            raise DeviceUnsupported("input_file_name() is host-only")
        raise DeviceUnsupported(f"unsupported boolean expr {type(e).__name__}")

    vf, uf = build_bool(expr)

    def fn(cols, lits):
        return vf(cols, lits) & ~uf(cols, lits)

    return fn, tuple(slots.values)


def _as_lit_scalar(v):
    """Fix the dtype a literal is passed with (jit traces lits as 0-d arrays;
    a stable dtype per slot keeps the executable cache warm)."""
    if isinstance(v, (np.timedelta64, np.datetime64)):
        # calendar units have no JAX dtype; raise here (not deep inside jit
        # tracing, where the ValueError would escape the fallback machinery)
        raise DeviceUnsupported(f"literal dtype {type(v).__name__} not device-representable")
    if isinstance(v, np.generic):
        return v
    if isinstance(v, bool):
        return np.int64(v)
    if isinstance(v, int):
        return np.int64(v)
    if isinstance(v, (str, bytes)):
        raise DeviceUnsupported("string literal in numeric slot")
    return np.float64(v)


# --------------------------------------------------------------------------
# device filter
# --------------------------------------------------------------------------


def _pad_to_multiple(arr: np.ndarray, m: int, fill) -> np.ndarray:
    n = arr.shape[0]
    rem = (-n) % m
    if rem == 0:
        return arr
    pad = np.full((rem,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


# --------------------------------------------------------------------------
# geometric shape buckets: every jitted program here specializes per input
# SHAPE, so ad-hoc padding (next multiple of n_dev) turns a streamed scan's
# slightly-varying chunk sizes into one fresh XLA compile per chunk. Rounding
# shapes up to powers of sqrt(2) over a floor caps the distinct shapes any
# stream can produce at 2-3 (chunking targets equal byte sizes), at <= 41%
# memory overhead. Shared by the filter, aggregate, and bucketed-SMJ
# rectangle paths; hs_xla_compiles_total measures the effect.
# --------------------------------------------------------------------------

_BUCKET_FLOOR = 4096
_SQRT2 = 1.4142135623730951


def bucket_rows(n: int, floor: int = _BUCKET_FLOOR) -> int:
    """Smallest geometric shape bucket (powers of sqrt(2) over ``floor``)
    holding ``n`` rows."""
    b = floor
    while b < n:
        b = int(b * _SQRT2) + 1
    return b


def _pad_to_bucket(arr: np.ndarray, m: int, fill) -> np.ndarray:
    """Pad axis 0 to the shape bucket for len(arr), rounded up to a multiple
    of ``m`` (the device count) so sharding stays even.

    Zero-copy handoff: when ``arr`` is a prefix view of a buffer that is
    *already* exactly this padded shape — the native decode fast path
    (exec/io.py) allocates its per-column buffers that way — and the buffer's
    tail holds ``fill``, the base buffer is adopted as-is; ``device_put``
    then ships the very memory the C decoder wrote."""
    n = arr.shape[0]
    target = bucket_rows(n)
    target += (-target) % m
    if target == n:
        return arr
    base = arr.base
    if (
        arr.ndim == 1
        and isinstance(base, np.ndarray)
        and base.ndim == 1
        and base.shape[0] == target
        and base.dtype.itemsize == arr.dtype.itemsize
        and arr.__array_interface__["data"][0] == base.__array_interface__["data"][0]
    ):
        adopted = base if base.dtype == arr.dtype else base.view(arr.dtype)
        # the fast path pre-fills the tail, but a coincidentally-shaped slice
        # of someone else's buffer must not leak its tail garbage: verify
        tail = adopted[n:]
        ok = bool(np.isnan(tail).all()) if fill != fill else bool((tail == fill).all())
        if ok:
            return adopted
    pad = np.full((target - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


# own (program skeleton, input-shape signature) ledger: jax.jit compiles
# exactly once per such pair, so first-seen here == one XLA compilation.
# Survives clear_device_cache() because the jit caches do too.
import threading as _threading
import time as _ptime

_COMPILE_SEEN: set = set()
_COMPILE_SEEN_LOCK = _threading.Lock()


def _note_compile(skeleton: str, sig) -> bool:
    """Record one (skeleton, signature) pair; True when first seen — i.e.
    the next invocation of the jitted program pays the XLA compile."""
    key = (skeleton, sig)
    with _COMPILE_SEEN_LOCK:
        if key in _COMPILE_SEEN:
            return False
        _COMPILE_SEEN.add(key)
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_xla_compiles_total",
        "Distinct (device program skeleton, input shape) XLA compilations",
    ).inc()
    return True


def _observe_program(family: str, first_seen: bool, t0: float) -> None:
    """Per-program-family device timing at the program-cache call sites
    (ROADMAP item 2's fusion baseline): a wall-clock histogram around the
    jitted call, a cumulative compile-seconds counter on first-seen
    signatures, and a span annotation on the active trace.

    Timing caveat (documented in observability.md): JAX dispatch is async —
    on a cached signature the interval covers dispatch plus whatever host
    sync the call site performs, NOT necessarily full device execution. On
    a first-seen signature it is dominated by XLA compilation, which is the
    cost these hooks exist to attribute.
    """
    wall = max(0.0, _ptime.perf_counter() - t0)
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.histogram(
        "hs_device_program_seconds",
        "wall seconds around device program invocations, by program family",
        program=family,
    ).observe(wall)
    if first_seen:
        REGISTRY.counter(
            "hs_device_compile_seconds_total",
            "cumulative wall seconds of first-seen (compiling) device "
            "program invocations, by program family",
            program=family,
        ).inc(wall)
    from hyperspace_tpu.obs import spans as _obs_spans

    sp = _obs_spans.current_span()
    if sp is not None:
        sp.event(
            "device-program",
            f"{family}: {wall * 1e3:.2f} ms" + (" (compile)" if first_seen else ""),
        )


# skeleton -> jitted predicate program; the jit object is reused across
# queries so only genuinely new predicate *structures* pay an XLA compile
from collections import OrderedDict as _OrderedDict

_PREDICATE_CACHE: "_OrderedDict[str, callable]" = _OrderedDict()
_PREDICATE_CACHE_MAX = 256

# (scan identity, column, n_dev) -> (sharded device array, codec, n_rows).
# Index bucket files are immutable (versioned v__=N dirs), so predicate
# columns stay resident in HBM across queries — the survey's "index
# column-chunks resident in HBM" stance (SURVEY.md §3.2); only the first
# query on an index version pays the host->device transfer.
from hyperspace_tpu.utils.lru import BytesLRU

_device_cache = BytesLRU(int(os.environ.get("HS_DEVICE_CACHE_BYTES", 1 << 31)))


def _device_cache_get(key):
    return _device_cache.get(key)


def _device_cache_put(key, value, nbytes: int) -> None:
    # overwrite semantics matter: a stale same-key entry (e.g. rows changed)
    # must be replaced, not pinned
    _device_cache.put(key, value, nbytes)


def clear_device_cache() -> None:
    _device_cache.clear()
    # the join rank cache short-circuits per-bucket key decodes, so it must
    # clear too or decode-count dispatch traces depend on run history
    _RANK_CACHE.clear()
    _REBUCKET_CACHE.clear()
    _CAP_HINT_MEMO.clear()


def purge_device_cache_files(paths) -> int:
    """Drop every resident device column whose scan covers any of ``paths``
    (data-version commit invalidation); returns entries removed.

    Cache keys are ``(scan_key, col, mesh_fp)`` where scan_key is a tuple of
    ``(path, mtime_ns, size)`` file triples (optionally suffixed with a
    row-group-pruning marker), so a purge scans those leading triples.
    """
    wanted = set(paths)
    if not wanted:
        return 0
    removed = 0
    for key in _device_cache.keys():
        scan_key = key[0]
        if not isinstance(scan_key, tuple):
            continue
        hit = any(
            isinstance(part, tuple) and part and part[0] in wanted
            for part in scan_key
        )
        if hit and _device_cache.discard(key):
            removed += 1
    return removed


def _cached_predicate_jit(skeleton: str, fn):
    import jax

    jitted = _PREDICATE_CACHE.get(skeleton)
    if jitted is None:
        while len(_PREDICATE_CACHE) >= _PREDICATE_CACHE_MAX:
            _PREDICATE_CACHE.popitem(last=False)
        jitted = jax.jit(fn)
        _PREDICATE_CACHE[skeleton] = jitted
    else:
        _PREDICATE_CACHE.move_to_end(skeleton)
    return jitted


def _mesh_fp(mesh) -> str:
    from hyperspace_tpu.parallel.mesh import mesh_fingerprint

    return mesh_fingerprint(mesh)


def _program_key(skeleton: str, mesh, sharded: bool = False) -> str:
    """Program-cache key: (program skeleton, mesh fingerprint, execution
    mode). The shape bucket is the jit cache's own shape signature, so the
    full identity is (skeleton, shape bucket, mesh fingerprint) — one cache
    serves the single-device (GSPMD jit) and sharded (shard_map) paths
    without executables ever aliasing across meshes or modes."""
    mode = "shmap" if sharded else "spmd"
    return f"{skeleton}@{_mesh_fp(mesh)}/{mode}"


# --- declared HLO contracts (hyperspace_tpu/check/hlo_lint.py) -------------
# Each device-program family states its collective budget next to the code
# that builds it (and inherits the forbidden-op rules: no host callbacks, no
# f32->f64 array upcasts, no bounded-dynamic shapes). With
# hyperspace.check.hlo.enabled on, maybe_verify() checks every newly
# compiled executable at program-cache-fill time.
from hyperspace_tpu.check import hlo_lint as _hlo_lint

_ANY = (0, None)
_hlo_lint.register_contract(
    "fused-filter",
    collectives={},
    description="fused predicate mask: elementwise over resident shards, shuffle-free",
)
_hlo_lint.register_contract(
    "fused-agg",
    collectives={"all-reduce": _ANY},
    description="fused filter+aggregate: scalar reductions may all-reduce, never move rows",
)
_hlo_lint.register_contract(
    "grouped-agg-chunk",
    collectives={"all-gather": _ANY, "all-reduce": _ANY},
    description="GSPMD grouped-aggregate chunk: the partitioner may gather fixed-size partials, never rows",
)
_hlo_lint.register_contract(
    "sharded-grouped",
    collectives={"all-gather": (1, None), "all-reduce": _ANY},
    description="shard_map grouped chunk: all-gathers per-shard partial TABLES (>=1), never rows",
)
_hlo_lint.register_contract(
    "grouped-merge",
    collectives={},
    description="pairwise partial-aggregate merge: device-local, collective-free",
)
_hlo_lint.register_contract(
    "bucketed-smj-span",
    collectives={},
    description="bucketed sort-merge join span search: the shuffle-freedom claim itself",
)
_hlo_lint.register_contract(
    "fused-stage-agg",
    collectives={"all-gather": _ANY, "all-reduce": _ANY},
    description="whole-stage filter+group+state-merge with donated fold state: one executable per chunk",
    single_fusion=True,
)
_hlo_lint.register_contract(
    "fused-stage-agg-sharded",
    collectives={"all-gather": (1, None), "all-reduce": _ANY},
    description="shard_map whole-stage grouped fold: gathers per-shard partial TABLES (>=1), one executable",
    single_fusion=True,
)
_hlo_lint.register_contract(
    "dict-expand",
    collectives={},
    description="on-device dictionary expansion: codes gather a replicated remap table, shuffle-free",
    single_fusion=True,
)

# whole-plan fusion helpers (stage compiler, dispatch counter, HBM gauge);
# stage_ir imports device only lazily inside functions, so this is acyclic
from hyperspace_tpu.exec import stage_ir as _stage_ir


def _dry_codecs(batch: B.Batch, refs) -> Dict[str, ColumnCodec]:
    """Dtype-kind-only codecs for the pre-transfer support check (string
    bounds resolve to 0; values are discarded)."""
    out: Dict[str, ColumnCodec] = {}
    for r in refs:
        kind = batch[r].dtype.kind
        if kind in ("U", "S", "O"):
            out[r] = ColumnCodec("string", uniques=np.empty(0, dtype=str))
        elif kind == "M":
            out[r] = ColumnCodec("datetime", unit=np.datetime_data(batch[r].dtype)[0])
        elif kind in ("i", "u", "b", "f"):
            out[r] = ColumnCodec("numeric")
        else:
            raise DeviceUnsupported(f"unsupported column dtype {batch[r].dtype}")
    return out


def _dict_expand_fn(codes, remap):
    import jax.numpy as jnp

    return jnp.where(codes >= 0, remap[jnp.maximum(codes, 0)], jnp.int32(-1))


def _put_encoded(session, mesh, sharding, n_dev, arr):
    """Encode + bucket-pad + ``device_put`` one column; returns
    (device array, codec, staged bytes).

    Dict-backed string columns (B.DictBackedArray, produced by the native
    decode fast path) skip host factorization entirely: the int32 codes ship
    as-is — bytes×rows becomes 4×rows over PCIe — plus a small replicated
    code→sorted-rank remap table, and the fused collective-free "dict-expand"
    gather rewrites codes into sorted-dictionary space on device. The result
    (array + ColumnCodec) is identical to the factorize_strings path, so
    _literal_bounds' searchsorted contract holds."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    codes = getattr(arr, "hs_dict_codes", None)
    uniques = getattr(arr, "hs_dict_uniques", None)
    if codes is not None and uniques is not None and codes.shape[0] == arr.shape[0]:
        order = np.argsort(uniques)
        su = uniques[order]
        k = int(order.shape[0])
        rank = np.empty(k, dtype=np.int32)
        rank[order] = np.arange(k, dtype=np.int32)
        cap = 1
        while cap < max(k, 1):
            cap *= 2  # power-of-two remap shapes cap distinct XLA signatures
        remap = np.zeros(cap, dtype=np.int32)
        remap[:k] = rank
        padded = _pad_to_bucket(codes, n_dev, 0)
        dev_codes = jax.device_put(padded, sharding)
        dev_remap = jax.device_put(remap, NamedSharding(mesh, P()))
        key = _program_key("dict-expand", mesh)
        jitted = _cached_predicate_jit(key, _dict_expand_fn)
        first = _note_compile(key, (padded.shape, remap.shape))
        _hlo_lint.maybe_verify(session.conf, "dict-expand", key, jitted, (dev_codes, dev_remap))
        t0 = _ptime.perf_counter()
        dev = jitted(dev_codes, dev_remap)
        _stage_ir.count_dispatch("dict-expand")
        _observe_program("dict-expand", first, t0)
        return dev, ColumnCodec("string", uniques=su), int(padded.nbytes + remap.nbytes)
    enc, codec = encode_column(arr)
    padded = _pad_to_bucket(enc, n_dev, 0 if enc.dtype != np.float64 else np.nan)
    dev = jax.device_put(padded, sharding)
    return dev, codec, int(padded.nbytes)


def device_filter_mask(session, batch: B.Batch, condition: Expr, scan_key=None, parallel=None) -> np.ndarray:
    """Evaluate ``condition`` on device over the referenced columns of
    ``batch``; returns the host bool mask. Raises DeviceUnsupported when the
    predicate is outside the device language.

    ``scan_key`` identifies an immutable file set (IndexScan bucket files);
    when given, encoded predicate columns are kept resident on device across
    queries. ``parallel`` (a ``ShardedExecutor``) switches compilation from
    GSPMD jit to an explicit shard_map over the executor's mesh; the device
    cache is shared between the two modes (same fingerprint, same layout)."""
    ensure_x64()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    refs = sorted(condition.references())
    for r in refs:
        if r not in batch:
            raise DeviceUnsupported(f"referenced column {r!r} missing from batch")
    n = B.num_rows(batch)
    if n == 0:
        return np.zeros(0, dtype=bool)

    mesh = parallel.mesh if parallel is not None else session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    fp = _mesh_fp(mesh)  # device-cache key part shared by both modes

    dev_cols: Dict[str, "jax.Array"] = {}
    codecs: Dict[str, ColumnCodec] = {}
    missing: List[str] = []
    for r in refs:
        ckey = (scan_key, r, fp) if scan_key is not None else None
        cached = _device_cache_get(ckey) if ckey is not None else None
        if cached is not None and cached[2] == n:
            dev_cols[r], codecs[r] = cached[0], cached[1]
        else:
            missing.append(r)

    if missing:
        # reject unsupported predicates BEFORE encoding/transferring the
        # missing columns — an unsupported shape must not cost HBM space or
        # a wasted upload
        compile_predicate(condition, _dry_codecs(batch, refs))

        for r in missing:
            dev, codec, nbytes = _put_encoded(session, mesh, sharding, n_dev, batch[r])
            dev_cols[r] = dev
            codecs[r] = codec
            if scan_key is not None:
                _device_cache_put((scan_key, r, fp), (dev, codec, n), nbytes)

    fn, lit_values = compile_predicate(condition, codecs)
    skeleton = predicate_skeleton(condition, codecs)
    if parallel is not None:
        from hyperspace_tpu.parallel import collectives as _collectives

        fn = _collectives.sharded_elementwise(mesh, axis, fn)
        parallel.note_op("filter")
    key = _program_key(skeleton, mesh, sharded=parallel is not None)
    jitted = _cached_predicate_jit(key, fn)
    first = _note_compile(key, tuple(dev_cols[r].shape for r in sorted(dev_cols)))
    _hlo_lint.maybe_verify(session.conf, "fused-filter", key, jitted, (dev_cols, lit_values))
    t0 = _ptime.perf_counter()
    mask = jitted(dev_cols, lit_values)
    _stage_ir.count_dispatch("fused-filter")
    out = np.asarray(mask)[:n]
    _observe_program("fused-filter", first, t0)
    return out


def stage_filter_columns(session, batch: B.Batch, condition: Optional[Expr], scan_key, extra_columns=None, parallel=None) -> None:
    """H2D staging hook for the scan pipeline (stage 2 of 3): encode,
    bucket-pad and ``device_put`` ``condition``'s columns into the device
    cache on the prefetch thread, so the consumer's ``device_filter_mask``
    on this chunk is a pure cache hit and the transfer overlaps chunk k's
    compute. ``extra_columns`` (group keys / aggregate inputs for the fused
    grouped-aggregate path) stage alongside the predicate columns. Silently
    a no-op when the predicate is outside the device language or
    ``scan_key`` is None (nothing would be cached)."""
    if scan_key is None or (condition is None and not extra_columns):
        return
    n = B.num_rows(batch)
    if n == 0:
        return
    refs = sorted(condition.references()) if condition is not None else []
    if any(r not in batch for r in refs):
        return
    cols = list(dict.fromkeys(refs + [c for c in (extra_columns or []) if c in batch]))
    from hyperspace_tpu.obs import spans as obs_spans

    try:
        ensure_x64()
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if condition is not None:
            compile_predicate(condition, _dry_codecs(batch, refs))
        mesh = parallel.mesh if parallel is not None else session.mesh
        n_dev = mesh.devices.size
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        fp = _mesh_fp(mesh)
        from hyperspace_tpu.reliability.faults import FAULTS

        if FAULTS.active:
            FAULTS.check("device.transfer")
        with obs_spans.span("h2d-stage", cat="pipeline", rows=n):
            for r in cols:
                ckey = (scan_key, r, fp)
                cached = _device_cache_get(ckey)
                if cached is not None and cached[2] == n:
                    continue
                dev, codec, nbytes = _put_encoded(session, mesh, sharding, n_dev, batch[r])
                _device_cache_put(ckey, (dev, codec, n), nbytes)
    except DeviceUnsupported:
        return  # the consumer's host fallback will handle this chunk


# --------------------------------------------------------------------------
# fused filter + global aggregate (only scalars leave the device)
# --------------------------------------------------------------------------

_AGG_FNS = ("count", "sum", "min", "max", "avg")


def device_filtered_aggregate(
    session,
    batch: B.Batch,
    condition: Optional[Expr],
    aggs: List[Tuple[str, str, Optional[str]]],
    scan_key=None,
) -> Optional[Dict[str, np.ndarray]]:
    """Global aggregates over (optionally filtered) device-resident columns
    in ONE fused program: predicate mask, validity mask for padding, and the
    reductions all execute on device; only per-aggregate scalars transfer
    back. ``aggs`` as in plan.Aggregate ((out name, fn, input col)).

    Raises DeviceUnsupported outside the device language (string aggregate
    inputs, unsupported predicate shapes, ...)."""
    ensure_x64()
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = B.num_rows(batch)
    if n == 0:
        return None  # empty-input semantics (NaN mins etc.) stay host-side

    agg_inputs = sorted({c for _, fn, c in aggs if c is not None})
    for _, fn, c in aggs:
        if fn not in _AGG_FNS:
            raise DeviceUnsupported(f"unsupported aggregate fn {fn!r}")
        # datetimes stay host-side: float64 reduction would lose ns precision
        if c is not None and batch[c].dtype.kind not in ("i", "u", "f", "b"):
            raise DeviceUnsupported(f"aggregate over non-numeric column {c!r}")
    refs = sorted(condition.references()) if condition is not None else []
    if not refs and not agg_inputs:
        # nothing to put on device (count(*) with no predicate): the program
        # would see an empty column dict and derive total=0 — host handles it
        raise DeviceUnsupported("no device-resident columns involved")
    for r in refs + agg_inputs:
        if r not in batch:
            raise DeviceUnsupported(f"column {r!r} missing from batch")

    mesh = session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    fp = _mesh_fp(mesh)

    # dry-check the predicate before any upload
    if condition is not None:
        compile_predicate(condition, _dry_codecs(batch, refs))

    dev_cols: Dict[str, "jax.Array"] = {}
    codecs: Dict[str, ColumnCodec] = {}
    for r in sorted(set(refs) | set(agg_inputs)):
        ckey = (scan_key, r, fp) if scan_key is not None else None
        cached = _device_cache_get(ckey) if ckey is not None else None
        if cached is not None and cached[2] == n:
            dev_cols[r], codecs[r] = cached[0], cached[1]
            continue
        arr, codec = encode_column(batch[r])
        if codec.kind == "string":
            raise DeviceUnsupported("string aggregate/predicate columns stay host-side here")
        padded = _pad_to_bucket(arr, n_dev, 0 if arr.dtype != np.float64 else np.nan)
        dev = jax.device_put(padded, sharding)
        dev_cols[r] = dev
        codecs[r] = codec
        if ckey is not None:
            _device_cache_put(ckey, (dev, codec, n), int(padded.nbytes))

    if condition is not None:
        pred_fn, lit_values = compile_predicate(condition, codecs)
        skeleton = "agg:" + predicate_skeleton(condition, codecs)
    else:
        pred_fn, lit_values = None, ()
        skeleton = "agg:<none>"
    agg_spec = tuple((fn, c) for _, fn, c in aggs)
    skeleton += "|" + repr(agg_spec)

    def program(cols, lits, n_valid):
        total = next(iter(cols.values())).shape[0]
        valid = jnp.arange(total) < n_valid
        mask = valid if pred_fn is None else (pred_fn(cols, lits) & valid)
        cnt = mask.sum()
        outs = []
        valids = []  # per-aggregate non-null match count (NaN-skipping)
        for fn, c in agg_spec:
            if fn == "count":
                if c is None or not jnp.issubdtype(cols[c].dtype, jnp.floating):
                    outs.append(cnt.astype(jnp.int64))
                else:
                    # count(col) skips nulls (NaN), like the host path
                    outs.append((mask & ~jnp.isnan(cols[c])).sum().astype(jnp.int64))
                valids.append(cnt)
                continue
            x = cols[c]
            is_int = jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_
            # pandas semantics: NaNs are skipped, not propagated
            m = mask if is_int else (mask & ~jnp.isnan(x))
            valids.append(m.sum())
            if fn == "sum":
                # integer sums stay int64 (host-path parity; exact)
                z = x.astype(jnp.int64) if is_int else x.astype(jnp.float64)
                outs.append(jnp.where(m, z, z.dtype.type(0)).sum())
            elif fn == "avg":
                xf = x.astype(jnp.float64)
                outs.append(jnp.where(m, xf, 0.0).sum() / jnp.maximum(m.sum(), 1))
            elif fn == "min":
                if is_int:
                    outs.append(jnp.where(m, x.astype(jnp.int64), jnp.iinfo(jnp.int64).max).min())
                else:
                    outs.append(jnp.where(m, x.astype(jnp.float64), jnp.inf).min())
            else:  # max
                if is_int:
                    outs.append(jnp.where(m, x.astype(jnp.int64), jnp.iinfo(jnp.int64).min).max())
                else:
                    outs.append(jnp.where(m, x.astype(jnp.float64), -jnp.inf).max())
        return tuple(outs), tuple(valids)

    key = _program_key(skeleton, mesh)
    jitted = _cached_predicate_jit(key, program)
    first = _note_compile(key, tuple(dev_cols[r].shape for r in sorted(dev_cols)))
    _hlo_lint.maybe_verify(session.conf, "fused-agg", key, jitted, (dev_cols, lit_values, np.int64(n)))
    t0 = _ptime.perf_counter()
    outs, valids = jitted(dev_cols, lit_values, np.int64(n))
    _stage_ir.count_dispatch("fused-agg")
    outs = [np.asarray(o) for o in outs]
    valids = [int(v) for v in valids]
    _observe_program("fused-agg", first, t0)

    result: Dict[str, np.ndarray] = {}
    for (name, fn, c), val, n_valid in zip(aggs, outs, valids):
        if fn == "count":
            result[name] = np.asarray([int(val)])
        elif fn in ("sum", "min", "max", "avg") and n_valid == 0:
            # no non-null matches: SQL yields NULL (sum included — SUM over
            # zero rows is NULL, not 0)
            result[name] = np.asarray([np.nan])
        else:
            src = batch[c]
            if fn in ("sum", "min", "max") and src.dtype.kind in ("i", "u", "b"):
                result[name] = np.asarray([int(val)])
            else:
                result[name] = np.asarray([float(val)])
    return result


# --------------------------------------------------------------------------
# fused filter + grouped aggregate: sort-based segment reduction
#
# One jitted program per (predicate skeleton, key/slot spec, shape bucket,
# capacity bucket): predicate mask, lexicographic rank-compression of the
# encoded group keys, and jax.ops.segment_sum/min/max reductions all run on
# device; only the per-group partial table (<= capacity rows) ever leaves.
# Streamed chunks each produce such a partial, merged chunk-to-chunk ON
# DEVICE by the same segment-reduction applied to the concatenated partials
# (avg/stddev decompose into sum/count/sumsq, so every state is mergeable).
# `num_segments` capacities grow geometrically (powers of sqrt(2) over a
# conf floor) so arbitrary group cardinalities land on a handful of cached
# executables; cardinalities beyond conf maxGroups spill to the host
# hash-combine path via DeviceUnsupported.
# --------------------------------------------------------------------------

_GROUPED_AGG_FNS = ("count", "sum", "min", "max", "avg", "stddev_samp")

_FS_SENTINEL = np.int64(np.iinfo(np.int64).max)


def group_capacity(n: int, floor: int) -> int:
    """Smallest geometric capacity bucket (powers of sqrt(2) over ``floor``)
    holding ``n`` groups — same geometry as the row-shape buckets, applied to
    ``num_segments`` so cardinality sweeps reuse executables."""
    return bucket_rows(max(1, int(n)), floor=max(1, int(floor)))


def topk_capacity(k: int, floor: int = 64) -> int:
    """Candidate-buffer capacity for a LIMIT ``k``: the same geometric
    buckets over a small floor, so nearby limits (10, 12, 100...) land on a
    handful of compiled top-k executables instead of one per distinct k."""
    return bucket_rows(max(1, int(k)), floor=max(1, int(floor)))


def _grouped_slots(aggs, is_int: Dict[str, bool]):
    """Decompose ``aggs`` into deduplicated mergeable state slots.

    Returns (slots, refs): ``slots`` is a list of (kind, col, int-valued)
    with kind in cntm/cnt/sum/sumsq/min/max (cntm = matched-row count for
    count(*)); ``refs[i]`` maps aggregate i to its slot indices."""
    slots: List[Tuple[str, Optional[str], bool]] = []
    index: Dict[Tuple[str, Optional[str], bool], int] = {}

    def slot(kind, col, isint):
        key = (kind, col, isint)
        got = index.get(key)
        if got is None:
            got = index[key] = len(slots)
            slots.append(key)
        return got

    refs: List[List[int]] = []
    for _, fn, c in aggs:
        if fn not in _GROUPED_AGG_FNS:
            raise DeviceUnsupported(f"unsupported grouped aggregate fn {fn!r}")
        if fn == "count" and c is None:
            refs.append([slot("cntm", None, True)])
            continue
        if c is None:
            raise DeviceUnsupported(f"aggregate {fn!r} without an input column")
        ii = bool(is_int[c])
        if fn == "count":
            refs.append([slot("cnt", c, ii)])
        elif fn == "sum":
            refs.append([slot("sum", c, ii), slot("cnt", c, ii)])
        elif fn == "min":
            refs.append([slot("min", c, ii), slot("cnt", c, ii)])
        elif fn == "max":
            refs.append([slot("max", c, ii), slot("cnt", c, ii)])
        elif fn == "avg":
            # float64 sum even for int inputs (the host streaming partial
            # does the same); exactness holds below 2^53
            refs.append([slot("sum", c, False), slot("cnt", c, ii)])
        else:  # stddev_samp
            refs.append([slot("cnt", c, ii), slot("sum", c, False), slot("sumsq", c, False)])
    return slots, refs


def _key_code(k, tag):
    """int64 grouping code of an encoded key column: equality of codes ==
    group identity. Floats canonicalize (-0.0 -> +0.0, NaN -> one canonical
    NaN, so NaN keys form ONE group like pandas dropna=False) then bitcast."""
    import jax.numpy as jnp

    if tag == "f":
        kf = k.astype(jnp.float64)
        kf = jnp.where(jnp.isnan(kf), jnp.float64(np.nan), kf + 0.0)
        return jax.lax.bitcast_convert_type(kf, jnp.int64)
    return k.astype(jnp.int64)


def _segment_ids(codes, mask, cap):
    """Sort rows so equal key tuples are adjacent (masked rows last), then
    rank-compress into segment ids. Returns (order, sorted-mask, n_groups,
    scatter ids) — scatter ids send masked rows to ``cap``, which
    segment_sum/min/max silently drop (out-of-range scatter)."""
    import jax.numpy as jnp

    total = mask.shape[0]
    inv = (~mask).astype(jnp.int32)
    order = jnp.lexsort(tuple(reversed(codes)) + (inv,))
    ms = mask[order]
    ch = jnp.zeros((total - 1,), dtype=bool)
    for c in codes:
        cs = c[order]
        ch = ch | (cs[1:] != cs[:-1])
    ch = ch | (ms[1:] != ms[:-1])
    seg = jnp.concatenate([jnp.zeros((1,), jnp.int64), jnp.cumsum(ch.astype(jnp.int64))])
    n_groups = jnp.max(jnp.where(ms, seg, -1)) + 1
    segs = jnp.where(ms, seg, cap)
    return order, ms, n_groups, segs


def _segment_reduce_slots(cols_sorted, ms, segs, cap, slot_specs):
    """Per-slot segment reductions over the sorted rows. ``cols_sorted`` maps
    input column -> (sorted values, int-valued)."""
    import jax.numpy as jnp
    from jax import ops as jops

    out = []
    for kind, col, isint in slot_specs:
        if kind == "cntm":
            out.append(jops.segment_sum(ms.astype(jnp.int64), segs, num_segments=cap, indices_are_sorted=True))
            continue
        x = cols_sorted[col]
        nn = ms if isint else (ms & ~jnp.isnan(x))
        if kind == "cnt":
            out.append(jops.segment_sum(nn.astype(jnp.int64), segs, num_segments=cap, indices_are_sorted=True))
        elif kind == "sum":
            z = x.astype(jnp.int64) if isint else x.astype(jnp.float64)
            out.append(jops.segment_sum(jnp.where(nn, z, z.dtype.type(0)), segs, num_segments=cap, indices_are_sorted=True))
        elif kind == "sumsq":
            xf = x.astype(jnp.float64)
            out.append(jops.segment_sum(jnp.where(nn, xf * xf, 0.0), segs, num_segments=cap, indices_are_sorted=True))
        elif kind == "min":
            if isint:
                z = jnp.where(nn, x.astype(jnp.int64), jnp.iinfo(jnp.int64).max)
            else:
                z = jnp.where(nn, x.astype(jnp.float64), jnp.inf)
            out.append(jops.segment_min(z, segs, num_segments=cap, indices_are_sorted=True))
        else:  # max
            if isint:
                z = jnp.where(nn, x.astype(jnp.int64), jnp.iinfo(jnp.int64).min)
            else:
                z = jnp.where(nn, x.astype(jnp.float64), -jnp.inf)
            out.append(jops.segment_max(z, segs, num_segments=cap, indices_are_sorted=True))
    return tuple(out)


def _grouped_chunk_program(pred_fn, key_specs, slot_specs, cap):
    """Build the fused filter -> group-by -> segment-reduce device program.

    Returns n_groups, per-group first-seen global row index, per-group key
    representatives (gathered from the first-occurrence row, so -0.0/NaN
    payloads follow appearance order like pandas), and the state slots."""
    import jax.numpy as jnp
    from jax import ops as jops

    def program(cols, lits, n_valid, row_base):
        total = next(iter(cols.values())).shape[0]
        valid = jnp.arange(total) < n_valid
        mask = valid if pred_fn is None else (pred_fn(cols, lits) & valid)
        codes = [_key_code(cols[name], tag) for name, tag in key_specs]
        order, ms, n_groups, segs = _segment_ids(codes, mask, cap)
        # first original row index per group == appearance order == the
        # representative row the key values gather from
        rep = jops.segment_min(
            jnp.where(ms, order.astype(jnp.int64), jnp.int64(total)),
            segs, num_segments=cap, indices_are_sorted=True,
        )
        repc = jnp.clip(rep, 0, total - 1)
        fs = jnp.where(rep < total, rep + row_base, _FS_SENTINEL)
        key_out = tuple(cols[name][repc] for name, _ in key_specs)
        cols_sorted = {c: cols[c][order] for _, c, _ in slot_specs if c is not None}
        slot_out = _segment_reduce_slots(cols_sorted, ms, segs, cap, slot_specs)
        return n_groups, fs, key_out, slot_out

    return program


def _merge_concat_parts(key_specs, slot_specs, cap_out, kcat, slots_cat, fs_cat, mask):
    """Merge CONCATENATED partial-aggregate parts on device — the core shared
    by the pairwise chunk merge (``_grouped_merge_program``) and the sharded
    all-gather merge (parallel/collectives.py): re-rank-compress the keys and
    segment-reduce the states with each slot's merge op (cnt/sum/sumsq add,
    min/max fold).

    Contract: parts must be concatenated in ascending global-row-range order,
    so a group's minimum concat position is a row from the part where it first
    appeared — the key representatives gathered from it match what a single
    sequential pass would have produced."""
    import jax.numpy as jnp
    from jax import ops as jops

    total = mask.shape[0]
    codes = [_key_code(k, tag) for k, (_, tag) in zip(kcat, key_specs)]
    order, ms, n_groups, segs = _segment_ids(codes, mask, cap_out)
    rep = jops.segment_min(
        jnp.where(ms, order.astype(jnp.int64), jnp.int64(total)),
        segs, num_segments=cap_out, indices_are_sorted=True,
    )
    repc = jnp.clip(rep, 0, total - 1)
    key_out = tuple(k[repc] for k in kcat)
    # values fed to the segment ops must follow the SORTED row order that
    # ``segs`` is defined over (the keys above gather by concat position
    # instead, so they stay unsorted)
    fs = jops.segment_min(
        jnp.where(ms, fs_cat[order], _FS_SENTINEL), segs,
        num_segments=cap_out, indices_are_sorted=True,
    )
    slot_out = []
    for (kind, _, _), v in zip(slot_specs, slots_cat):
        v = v[order]
        if kind in ("cntm", "cnt", "sum", "sumsq"):
            slot_out.append(jops.segment_sum(jnp.where(ms, v, v.dtype.type(0)), segs, num_segments=cap_out, indices_are_sorted=True))
        elif kind == "min":
            big = jnp.iinfo(jnp.int64).max if jnp.issubdtype(v.dtype, jnp.integer) else jnp.inf
            slot_out.append(jops.segment_min(jnp.where(ms, v, big), segs, num_segments=cap_out, indices_are_sorted=True))
        else:  # max
            low = jnp.iinfo(jnp.int64).min if jnp.issubdtype(v.dtype, jnp.integer) else -jnp.inf
            slot_out.append(jops.segment_max(jnp.where(ms, v, low), segs, num_segments=cap_out, indices_are_sorted=True))
    return n_groups, fs, key_out, tuple(slot_out)


def _grouped_merge_program(key_specs, slot_specs, cap_in, cap_out):
    """Merge two partial-aggregate tables (each padded to ``cap_in`` rows) on
    device. The running partial occupies the first concat half and its groups
    were first seen no later than the incoming chunk's (row bases ascend), so
    the concat satisfies ``_merge_concat_parts``'s ordering contract."""
    import jax.numpy as jnp

    def program(keys_a, keys_b, slots_a, slots_b, fs_a, fs_b, n_a, n_b):
        idx = jnp.arange(cap_in)
        mask = jnp.concatenate([idx < n_a, idx < n_b])
        kcat = tuple(jnp.concatenate([a, b]) for a, b in zip(keys_a, keys_b))
        slots_cat = tuple(jnp.concatenate([va, vb]) for va, vb in zip(slots_a, slots_b))
        fs_cat = jnp.concatenate([fs_a, fs_b])
        return _merge_concat_parts(key_specs, slot_specs, cap_out, kcat, slots_cat, fs_cat, mask)

    return program


def _dev_pad(arr, target, fill):
    """Pad a (small, per-group) device array up to ``target`` rows."""
    import jax.numpy as jnp

    n = arr.shape[0]
    if n == target:
        return arr
    return jnp.concatenate([arr, jnp.full((target - n,), fill, arr.dtype)])


def _fused_grouped_update_program(pred_fn, key_specs, slot_specs, cap):
    """Whole-stage grouped fold (``hyperspace.exec.fusion.enabled``): the
    chunk's filter+group+segment-reduce AND the merge into the running
    partial as ONE program, so a streamed chunk costs a single dispatch and
    the fold state can be donated (args 0-2) for in-place buffer reuse.

    Overflow contract: the rank-compressed group counts are exact even above
    ``cap``, so ``n_b > cap`` (chunk-local) or ``n_m > cap`` (merged) flags a
    lost-groups hazard; every state output then selects the ORIGINAL state
    via ``jnp.where`` — with donation the buffers were reused, but their
    VALUES round-trip unchanged, and the host redoes the chunk per-family.
    """
    import jax.numpy as jnp

    chunk = _grouped_chunk_program(pred_fn, key_specs, slot_specs, cap)

    def program(state_keys, state_slots, state_fs, state_n, cols, lits, n_valid, row_base):
        n_b, fs_b, key_b, slot_b = chunk(cols, lits, n_valid, row_base)
        idx = jnp.arange(cap)
        mask = jnp.concatenate([idx < state_n, idx < n_b])
        kcat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_keys, key_b))
        scat = tuple(jnp.concatenate([a, b]) for a, b in zip(state_slots, slot_b))
        fs_cat = jnp.concatenate([state_fs, fs_b])
        n_m, fs_m, key_m, slot_m = _merge_concat_parts(
            key_specs, slot_specs, cap, kcat, scat, fs_cat, mask
        )
        ok = (n_b <= cap) & (n_m <= cap)
        n_out = jnp.where(ok, n_m, state_n)
        fs_out = jnp.where(ok, fs_m, state_fs)
        keys_out = tuple(jnp.where(ok, m, s) for m, s in zip(key_m, state_keys))
        slots_out = tuple(jnp.where(ok, m, s) for m, s in zip(slot_m, state_slots))
        return n_b, n_m, n_out, fs_out, keys_out, slots_out

    return program


def _fused_state_dtypes(key_specs, slot_specs):
    """(key dtype per group key, slot dtype per state slot) of the fused fold
    state — must match the chunk/merge program outputs EXACTLY or the
    overflow ``jnp.where`` selects would promote and break donation
    aliasing."""
    import jax.numpy as jnp

    key_dts = tuple(jnp.float64 if tag == "f" else jnp.int64 for _, tag in key_specs)
    slot_dts = tuple(
        jnp.int64
        if (kind in ("cntm", "cnt") or (isint and kind in ("sum", "min", "max")))
        else jnp.float64
        for kind, _, isint in slot_specs
    )
    return key_dts, slot_dts


class GroupedAggStream:
    """Streaming grouped aggregation with device-resident partials.

    ``update(batch, condition)`` fuses the scan predicate with the grouped
    segment reduction over one chunk and merges the resulting partial table
    into the running device partial; ``finalize()`` pulls only the per-group
    table back and reconstructs exact host-path semantics (NULL sums,
    NaN-skipping counts, dtype-preserving min/max, appearance-ordered rows).

    String group keys are grouped per-chunk in their chunk-local dictionary
    codes, then the <= cardinality per-group codes are remapped into one
    growing global dictionary between chunk and merge — O(groups) host
    traffic, never O(rows).

    Raises DeviceUnsupported whenever the shape, a dtype, or the observed
    group cardinality (> ``max_groups``) leaves the device language; callers
    fall back (or spill) to the host hash-combine path.
    """

    def __init__(
        self, session, group_keys, aggs, *, max_groups: int, cap_floor: int, hint_key=None,
        parallel=None,
    ):
        if not group_keys:
            raise DeviceUnsupported("global aggregates take the fused-scalar path")
        self.session = session
        # a ShardedExecutor switches the chunk program from GSPMD jit to an
        # explicit shard_map whose per-shard partials merge on-device via
        # all-gather (parallel/collectives.py) instead of the host loop
        self._parallel = parallel
        self.group_keys = list(group_keys)
        self.aggs = [(name, fn, c) for name, fn, c in aggs]
        self.max_groups = int(max_groups)
        self.cap_floor = max(1, int(cap_floor))
        self._schema = None  # per-key (tag, dtype, unit) + per-input dtype
        self._slots = None
        self._refs = None
        self._partial = None  # dict(cap, n, fs, keys, slots) — device arrays
        self._row_base = 0
        # seed capacity from the last observed cardinality of the same query
        # shape over the same scan: a fresh stream otherwise starts at the
        # floor and pays a right-sizing re-run on EVERY repeated (warm) query
        self._hint_key = (
            (hint_key, tuple(self.group_keys), tuple((fn, c) for _, fn, c in self.aggs))
            if hint_key is not None
            else None
        )
        self._cap_hint = _CAP_HINT_MEMO.get(self._hint_key, 1)
        self._strmaps: Dict[str, Dict[str, int]] = {}
        self._struniq: Dict[str, List] = {}

    # -- schema ---------------------------------------------------------------

    def _key_tag(self, arr: np.ndarray) -> str:
        kind = arr.dtype.kind
        if kind in ("i", "u", "b"):
            return "i"
        if kind == "f":
            return "f"
        if kind == "M":
            return "d"
        if kind in ("U", "S", "O"):
            return "s"
        raise DeviceUnsupported(f"unsupported group-key dtype {arr.dtype}")

    def _check_schema(self, batch: B.Batch):
        keys_schema = []
        for k in self.group_keys:
            arr = batch[k]
            tag = self._key_tag(arr)
            unit = np.datetime_data(arr.dtype)[0] if tag == "d" else None
            keys_schema.append((tag, arr.dtype, unit))
        inputs = {}
        for _, fn, c in self.aggs:
            if c is None:
                continue
            kind = batch[c].dtype.kind
            if kind not in ("i", "u", "b", "f"):
                raise DeviceUnsupported(f"grouped aggregate over non-numeric column {c!r}")
            inputs[c] = batch[c].dtype
        if self._schema is None:
            self._schema = (keys_schema, inputs)
            self._slots, self._refs = _grouped_slots(
                self.aggs, {c: dt.kind in ("i", "u", "b") for c, dt in inputs.items()}
            )
        else:
            prev_keys, prev_inputs = self._schema
            if [s[:1] + (s[2],) for s in prev_keys] != [s[:1] + (s[2],) for s in keys_schema] or {
                c: dt.kind in ("i", "u", "b") for c, dt in prev_inputs.items()
            } != {c: dt.kind in ("i", "u", "b") for c, dt in inputs.items()}:
                raise DeviceUnsupported("chunk schema drift under grouped aggregate")

    # -- chunk update ---------------------------------------------------------

    @property
    def has_data(self) -> bool:
        return self._partial is not None

    def update(self, batch: B.Batch, condition: Optional[Expr] = None, scan_key=None) -> None:
        ensure_x64()
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = B.num_rows(batch)
        if n == 0:
            return
        refs = sorted(condition.references()) if condition is not None else []
        agg_inputs = sorted({c for _, _, c in self.aggs if c is not None})
        for col in refs + agg_inputs + self.group_keys:
            if col not in batch:
                raise DeviceUnsupported(f"column {col!r} missing from batch")
        self._check_schema(batch)
        keys_schema, input_dtypes = self._schema
        if condition is not None:
            compile_predicate(condition, _dry_codecs(batch, refs))

        mesh = self._parallel.mesh if self._parallel is not None else self.session.mesh
        n_dev = mesh.devices.size
        sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
        fp = _mesh_fp(mesh)
        dev_cols: Dict[str, "jax.Array"] = {}
        codecs: Dict[str, ColumnCodec] = {}
        for col in sorted(set(refs) | set(agg_inputs) | set(self.group_keys)):
            ckey = (scan_key, col, fp) if scan_key is not None else None
            cached = _device_cache_get(ckey) if ckey is not None else None
            if cached is not None and cached[2] == n:
                dev_cols[col], codecs[col] = cached[0], cached[1]
                continue
            if col in agg_inputs and batch[col].dtype.kind in ("U", "S", "O"):
                raise DeviceUnsupported("string aggregate inputs stay host-side")
            dev, codec, nbytes = _put_encoded(
                self.session, mesh, sharding, n_dev, batch[col]
            )
            dev_cols[col] = dev
            codecs[col] = codec
            if ckey is not None:
                _device_cache_put(ckey, (dev, codec, n), nbytes)
        for col in agg_inputs:
            if codecs[col].kind == "string":
                raise DeviceUnsupported("string aggregate inputs stay host-side")

        if condition is not None:
            pred_fn, lit_values = compile_predicate(condition, codecs)
            pred_sk = predicate_skeleton(condition, codecs)
        else:
            pred_fn, lit_values = None, ()
            pred_sk = "<none>"
        key_specs = tuple(
            (name, "f" if tag == "f" else "i")
            for name, (tag, _, _) in zip(self.group_keys, keys_schema)
        )
        base_sk = (
            f"{pred_sk}|k:{','.join(f'{n_}:{t}' for n_, t in key_specs)}"
            f"|s:{','.join(f'{k}:{c}:{int(i)}' for k, c, i in self._slots)}"
        )

        cap = group_capacity(max(self._cap_hint, 1), self.cap_floor)
        shapes = tuple(dev_cols[r].shape for r in sorted(dev_cols))
        sharded = self._parallel is not None
        if _stage_ir.fusion_wanted(self.session.conf) and not any(
            tag == "s" for tag, _, _ in keys_schema
        ):
            # whole-stage fold: chunk select + state merge in ONE dispatch,
            # fold state donated. String group keys stay per-family (their
            # chunk->global dictionary remap is a host step between the chunk
            # and merge programs that fusion removes).
            if self._update_fused(
                mesh, sharded, dev_cols, lit_values, pred_fn, key_specs,
                base_sk, n, shapes,
            ):
                return
            trace.fallback("fusion", "grouped-overflow")
        while True:
            if sharded:
                from hyperspace_tpu.parallel import collectives as _collectives

                program = _collectives.sharded_grouped_chunk_program(
                    mesh, mesh.axis_names[0], pred_fn, key_specs, self._slots, cap
                )
            else:
                program = _grouped_chunk_program(pred_fn, key_specs, self._slots, cap)
            key = _program_key(f"gagg[{cap}]:{base_sk}", mesh, sharded=sharded)
            jitted = _cached_predicate_jit(key, program)
            first = _note_compile(key, shapes)
            _hlo_lint.maybe_verify(
                self.session.conf,
                "sharded-grouped" if sharded else "grouped-agg-chunk",
                key, jitted,
                (dev_cols, lit_values, np.int64(n), np.int64(self._row_base)),
            )
            t0 = _ptime.perf_counter()
            if sharded:
                n_g_dev, fs, key_out, slot_out = self._parallel.timed_call(
                    "grouped-agg", jitted,
                    dev_cols, lit_values, np.int64(n), np.int64(self._row_base),
                )
            else:
                n_g_dev, fs, key_out, slot_out = jitted(
                    dev_cols, lit_values, np.int64(n), np.int64(self._row_base)
                )
            _stage_ir.count_dispatch("sharded-grouped" if sharded else "grouped-agg-chunk")
            n_g = int(n_g_dev)
            _observe_program(
                "sharded-grouped" if sharded else "grouped-agg-chunk", first, t0
            )
            if n_g > self.max_groups:
                exc = GroupCapacityExceeded(
                    f"group cardinality {n_g} exceeds maxGroups {self.max_groups}"
                )
                exc.folded = False  # this chunk is NOT in the running partial
                raise exc
            if n_g <= cap:
                break
            cap = group_capacity(n_g, self.cap_floor)  # one re-run, right-sized
        self._cap_hint = max(self._cap_hint, n_g)

        key_out = list(key_out)
        for i, (name, (tag, _, _)) in enumerate(zip(self.group_keys, keys_schema)):
            if tag == "s":
                key_out[i] = self._remap_string_key(name, key_out[i], codecs[name], n_g, cap)
        new = {"cap": cap, "n": n_g, "fs": fs, "keys": key_out, "slots": list(slot_out)}
        self._row_base += n
        if self._partial is None:
            self._partial = new
        else:
            self._merge(new)
        _stage_ir.note_peak_bytes()

    def _ensure_fused_state(self, key_specs, cap):
        """The running partial as (keys, slots, fs, n) device arrays padded
        to ``cap`` — zero-filled when the stream is fresh (``state_n == 0``
        masks them out of the fused merge)."""
        import jax.numpy as jnp

        key_dts, slot_dts = _fused_state_dtypes(key_specs, self._slots)
        p = self._partial
        if p is None:
            keys = tuple(jnp.zeros(cap, dtype=dt) for dt in key_dts)
            slots = tuple(jnp.zeros(cap, dtype=dt) for dt in slot_dts)
            fs = jnp.full(cap, _FS_SENTINEL, dtype=jnp.int64)
            return keys, slots, fs, 0
        if p["cap"] < cap:
            p["fs"] = _dev_pad(p["fs"], cap, _FS_SENTINEL)
            p["keys"] = [_dev_pad(k, cap, 0 if k.dtype != np.float64 else np.nan) for k in p["keys"]]
            p["slots"] = [_dev_pad(s, cap, 0) for s in p["slots"]]
            p["cap"] = cap
        return tuple(p["keys"]), tuple(p["slots"]), p["fs"], int(p["n"])

    def _update_fused(self, mesh, sharded, dev_cols, lit_values, pred_fn,
                      key_specs, base_sk, n, shapes) -> bool:
        """One-dispatch whole-stage fold of this chunk. Returns False on
        capacity overflow — the state values round-tripped unchanged through
        the (possibly donated) buffers and the caller redoes the chunk on the
        per-family path."""
        conf = self.session.conf
        cap = group_capacity(max(self._cap_hint, 1), self.cap_floor)
        if self._partial is not None:
            cap = max(cap, self._partial["cap"])
        state_keys, state_slots, state_fs, state_n = self._ensure_fused_state(
            key_specs, cap
        )
        # donation stays off under shard_map: XLA cannot reliably alias the
        # replicated fold state there, and an unhonored donation both warns
        # and silently loses the in-place win
        donate = _stage_ir.donation_wanted(conf) and not sharded
        if sharded:
            from hyperspace_tpu.parallel import collectives as _collectives

            program = _collectives.sharded_fused_grouped_program(
                mesh, mesh.axis_names[0], pred_fn, key_specs, self._slots, cap
            )
        else:
            program = _fused_grouped_update_program(
                pred_fn, key_specs, self._slots, cap
            )
        family = "fused-stage-agg-sharded" if sharded else "fused-stage-agg"
        key = _program_key(
            f"gaggfused[{cap}{'+d' if donate else ''}]:{base_sk}",
            mesh, sharded=sharded,
        )
        jitted = _stage_ir.compile_stage(
            key, program, donate_argnums=(0, 1, 2) if donate else ()
        )
        first = _note_compile(key, shapes + ((cap,),))
        args = (
            state_keys, state_slots, state_fs, np.int64(state_n),
            dev_cols, lit_values, np.int64(n), np.int64(self._row_base),
        )
        _hlo_lint.maybe_verify(conf, family, key, jitted, args)
        t0 = _ptime.perf_counter()
        if sharded:
            n_b_d, n_m_d, n_out_d, fs_out, keys_out, slots_out = (
                self._parallel.timed_call("grouped-agg", jitted, *args)
            )
        else:
            n_b_d, n_m_d, n_out_d, fs_out, keys_out, slots_out = jitted(*args)
        _stage_ir.count_dispatch(family)
        n_b, n_m = int(n_b_d), int(n_m_d)
        _observe_program(family, first, t0)
        # the donated state is consumed either way: rebind the partial to the
        # returned (aliased) buffers, which carry the original values on
        # overflow
        self._partial = {
            "cap": cap, "n": int(n_out_d), "fs": fs_out,
            "keys": list(keys_out), "slots": list(slots_out),
        }
        _stage_ir.note_peak_bytes()
        if n_b > cap or n_m > cap:
            self._cap_hint = max(self._cap_hint, n_b, n_m)
            if state_n == 0:
                self._partial = None  # nothing folded yet; keep the redo cheap
            return False
        self._row_base += n
        self._cap_hint = max(self._cap_hint, n_m)
        if n_m > self.max_groups:
            exc = GroupCapacityExceeded(
                f"group cardinality {n_m} exceeds maxGroups {self.max_groups}"
            )
            exc.folded = True  # the chunk IS in the stored partial
            raise exc
        return True

    def _remap_string_key(self, name, dev_codes, codec: ColumnCodec, n_g: int, cap: int):
        """Chunk-local dictionary codes -> global int64 codes (host remap of
        only the per-group representatives; -1 null stays -1)."""
        import jax

        local = np.asarray(dev_codes)[:n_g]
        mapping = self._strmaps.setdefault(name, {})
        uniq = self._struniq.setdefault(name, [])
        out = np.full(cap, -1, dtype=np.int64)
        for j, code in enumerate(local):
            if code < 0:
                continue
            val = codec.uniques[int(code)]
            got = mapping.get(val)
            if got is None:
                got = mapping[val] = len(uniq)
                uniq.append(val)
            out[j] = got
        return jax.device_put(out)

    def _merge(self, new) -> None:
        import jax
        import time as _time
        from hyperspace_tpu.obs import spans as obs_spans
        from hyperspace_tpu.obs.metrics import REGISTRY

        a, b = self._partial, new
        keys_schema, _ = self._schema
        key_specs = tuple(
            (name, "f" if tag == "f" else "i")
            for name, (tag, _, _) in zip(self.group_keys, keys_schema)
        )
        cap_in = max(a["cap"], b["cap"])
        for part in (a, b):
            if part["cap"] != cap_in:
                part["fs"] = _dev_pad(part["fs"], cap_in, _FS_SENTINEL)
                part["keys"] = [_dev_pad(k, cap_in, 0 if k.dtype != np.float64 else np.nan) for k in part["keys"]]
                part["slots"] = [_dev_pad(s, cap_in, 0) for s in part["slots"]]
        cap_out = group_capacity(a["n"] + b["n"], self.cap_floor)
        mesh = self._parallel.mesh if self._parallel is not None else self.session.mesh
        skeleton = (
            f"gaggmerge[{cap_in}->{cap_out}]:k:{','.join(t for _, t in key_specs)}"
            f"|s:{','.join(f'{k}:{int(i)}' for k, _, i in self._slots)}"
        )
        key = _program_key(skeleton, mesh)
        program = _grouped_merge_program(key_specs, self._slots, cap_in, cap_out)
        jitted = _cached_predicate_jit(key, program)
        first = _note_compile(key, (cap_in, cap_out))
        _hlo_lint.maybe_verify(
            self.session.conf, "grouped-merge", key, jitted,
            (tuple(a["keys"]), tuple(b["keys"]), tuple(a["slots"]), tuple(b["slots"]),
             a["fs"], b["fs"], np.int64(a["n"]), np.int64(b["n"])),
        )
        t0 = _time.perf_counter()
        with obs_spans.span("agg-merge", cat="groupagg", groups_in=a["n"] + b["n"]):
            n_g_dev, fs, key_out, slot_out = jitted(
                tuple(a["keys"]), tuple(b["keys"]),
                tuple(a["slots"]), tuple(b["slots"]),
                a["fs"], b["fs"], np.int64(a["n"]), np.int64(b["n"]),
            )
            _stage_ir.count_dispatch("grouped-merge")
            n_g = int(n_g_dev)
        _observe_program("grouped-merge", first, t0)
        REGISTRY.counter(
            "hs_agg_merge_seconds_total",
            "Cumulative device partial-aggregate merge time (seconds)",
        ).inc(_time.perf_counter() - t0)
        self._partial = {
            "cap": cap_out, "n": n_g, "fs": fs,
            "keys": list(key_out), "slots": list(slot_out),
        }
        self._cap_hint = max(self._cap_hint, n_g)
        if n_g > self.max_groups:
            # the merged partial is still VALID (capacity covered it) — keep
            # it so the caller can convert to a host partial before spilling
            exc = GroupCapacityExceeded(
                f"group cardinality {n_g} exceeds maxGroups {self.max_groups}"
            )
            exc.folded = True  # the triggering chunk IS in the stored partial
            raise exc

    # -- finalization ---------------------------------------------------------

    def _host_table(self):
        """Pull the per-group table to host, appearance-ordered: decoded key
        arrays + raw slot arrays."""
        p = self._partial
        if p is None:
            raise DeviceUnsupported("no device partial to finalize")
        n = p["n"]
        keys_schema, input_dtypes = self._schema
        fs = np.asarray(p["fs"])[:n]
        order = np.argsort(fs, kind="stable")
        key_cols = {}
        for name, (tag, dtype, unit), dev in zip(self.group_keys, keys_schema, p["keys"]):
            vals = np.asarray(dev)[:n][order]
            if tag == "s":
                uniq = self._struniq.get(name, [])
                out = np.full(n, np.nan, dtype=object)
                pos = vals >= 0
                if pos.any():
                    lut = np.asarray(uniq, dtype=object)
                    out[pos] = lut[vals[pos].astype(np.int64)]
                key_cols[name] = out
            elif tag == "d":
                key_cols[name] = vals.astype(np.int64).view(f"M8[{unit}]")
            elif tag == "f":
                key_cols[name] = vals.astype(dtype)
            else:
                key_cols[name] = vals.astype(dtype)
        slot_cols = [np.asarray(s)[:n][order] for s in p["slots"]]
        return n, key_cols, slot_cols

    def finalize(self) -> B.Batch:
        """Per-group final values with host-path semantics: count -> int64,
        int sum -> int64 (exact), float sum/min/max -> NULL (NaN) when every
        matched row was NULL, int min/max keep the input dtype, avg/stddev
        from the decomposed states. Rows in first-appearance order, exactly
        like pandas groupby(sort=False)."""
        from hyperspace_tpu.obs.metrics import REGISTRY

        if self._hint_key is not None:
            if len(_CAP_HINT_MEMO) >= 4096:  # bound pathological key churn
                _CAP_HINT_MEMO.clear()
            _CAP_HINT_MEMO[self._hint_key] = self._cap_hint
        n, key_cols, slot_cols = self._host_table()
        _, input_dtypes = self._schema
        out: B.Batch = dict(key_cols)
        for (name, fn, c), ref in zip(self.aggs, self._refs):
            if fn == "count":
                out[name] = slot_cols[ref[0]].astype(np.int64)
                continue
            dt = input_dtypes[c]
            is_int = dt.kind in ("i", "u", "b")
            if fn == "sum":
                s, cnt = slot_cols[ref[0]], slot_cols[ref[1]]
                if is_int:
                    out[name] = s.astype(np.int64)  # int inputs have no NULLs
                else:
                    out[name] = np.where(cnt > 0, s.astype(np.float64), np.nan)
            elif fn in ("min", "max"):
                v, cnt = slot_cols[ref[0]], slot_cols[ref[1]]
                if is_int:
                    out[name] = v.astype(dt if dt.kind != "u" else np.int64)
                else:
                    out[name] = np.where(cnt > 0, v.astype(np.float64), np.nan)
            elif fn == "avg":
                s, cnt = slot_cols[ref[0]], slot_cols[ref[1]]
                with np.errstate(invalid="ignore", divide="ignore"):
                    out[name] = np.where(cnt > 0, s / np.maximum(cnt, 1), np.nan)
            else:  # stddev_samp
                cnt, s, ss = (slot_cols[r] for r in ref)
                with np.errstate(invalid="ignore", divide="ignore"):
                    m = cnt > 1
                    var = np.where(
                        m,
                        (ss - (s * s) / np.maximum(cnt, 1)) / np.maximum(cnt - 1, 1),
                        np.nan,
                    )
                    out[name] = np.sqrt(np.clip(var, 0.0, None))
        REGISTRY.counter(
            "hs_agg_groups_total", "Groups produced by device grouped aggregation"
        ).inc(n)
        return out

    def to_partial_frame(self, plain):
        """The running device partial as ONE host partial frame in the
        streaming-aggregate merge format (``__p{i}`` columns per plan-agg
        index) — the spill path hands accumulated device state to the host
        hash-combine without recomputing any chunk."""
        import pandas as pd

        n, key_cols, slot_cols = self._host_table()
        _, input_dtypes = self._schema
        frame = dict(key_cols)
        by_name = {name: (fn, c) for name, fn, c in self.aggs}
        refs_by_name = {name: ref for (name, _, _), ref in zip(self.aggs, self._refs)}
        for i, name, fn, c in plain:
            ref = refs_by_name[name]
            p = f"__p{i}"
            if fn == "count":
                frame[p] = slot_cols[ref[0]].astype(np.int64)
            elif fn in ("sum", "min", "max"):
                v, cnt = slot_cols[ref[0]], slot_cols[ref[1]]
                dt = input_dtypes[c]
                if dt.kind in ("i", "u", "b"):
                    if fn == "sum":
                        frame[p] = v.astype(np.int64)
                    else:
                        frame[p] = v.astype(dt if dt.kind != "u" else np.int64)
                else:
                    frame[p] = np.where(cnt > 0, v.astype(np.float64), np.nan)
            elif fn == "avg":
                s, cnt = slot_cols[ref[0]], slot_cols[ref[1]]
                frame[p + "_s"] = np.where(cnt > 0, s.astype(np.float64), np.nan)
                frame[p + "_c"] = cnt.astype(np.int64)
            else:  # stddev_samp
                cnt, s, ss = (slot_cols[r] for r in ref)
                frame[p + "_n"] = cnt.astype(np.int64)
                frame[p + "_s"] = np.where(cnt > 0, s.astype(np.float64), np.nan)
                frame[p + "_ss"] = ss.astype(np.float64)
        return pd.DataFrame(frame)


_CAP_HINT_MEMO: Dict[tuple, int] = {}


def device_grouped_aggregate(
    session,
    batch: B.Batch,
    condition: Optional[Expr],
    group_keys,
    aggs,
    scan_key=None,
    *,
    max_groups: int,
    cap_floor: int,
    parallel=None,
) -> B.Batch:
    """One-shot fused filter -> grouped aggregate over a materialized scan
    batch (the non-streamed `_exec_aggregate` path). Raises DeviceUnsupported
    outside the device language or beyond ``max_groups`` cardinality."""
    if B.num_rows(batch) == 0:
        raise DeviceUnsupported("empty input stays host-side")
    stream = GroupedAggStream(
        session,
        group_keys,
        aggs,
        max_groups=max_groups,
        cap_floor=cap_floor,
        hint_key=scan_key,
        parallel=parallel,
    )
    stream.update(batch, condition, scan_key=scan_key)
    return stream.finalize()


# --------------------------------------------------------------------------
# bucketed shuffle-free merge join
# --------------------------------------------------------------------------


def _strip_projects(plan: L.LogicalPlan) -> Tuple[L.LogicalPlan, Optional[List[str]]]:
    cols = None
    while isinstance(plan, L.Project):
        cols = list(plan.columns) if cols is None else cols
        plan = plan.child
    return plan, cols


def _side_bucket_spec(node: L.LogicalPlan) -> Optional[L.BucketSpec]:
    """The bucket layout a join side arrives in, looking through the
    layout-preserving wrappers (Project/Filter). Covers plain IndexScans AND
    hybrid-scan sides (BucketUnion of index minus deletes + re-bucketed
    appends — ref: CoveringIndexRuleUtils.scala:146-288)."""
    spec = getattr(node, "bucket_spec", None)
    if spec is not None:
        return spec
    if isinstance(node, (L.Project, L.Filter)):
        return _side_bucket_spec(node.child)
    return None


def join_sides_compatible(plan: L.Join) -> Optional[Tuple[L.LogicalPlan, L.LogicalPlan, List[str], List[str]]]:
    """If both join children arrive bucketed on exactly the join keys with
    equal bucket counts — index scans or hybrid-scan BucketUnions — return
    (left_side, right_side, lkeys, rkeys); else None (ref: JoinIndexRanker's
    equal-bucket preference, HS/index/covering/JoinIndexRanker.scala:52-92)."""
    if plan.residual is not None:
        return None  # non-equi ON residuals run on the host join path
    pairs = extract_equi_join_keys(plan.condition)
    if not pairs:
        return None
    lspec = _side_bucket_spec(plan.left)
    rspec = _side_bucket_spec(plan.right)
    if lspec is None or rspec is None or lspec.num_buckets != rspec.num_buckets:
        return None
    lcols = set(plan.left.output_columns)
    rcols = set(plan.right.output_columns)
    lkeys, rkeys = [], []
    for a, b in pairs:
        if a in lcols and b in rcols:
            lkeys.append(a)
            rkeys.append(b)
        elif b in lcols and a in rcols:
            lkeys.append(b)
            rkeys.append(a)
        else:
            return None
    from hyperspace_tpu.plan.expr import strip_nested_prefix

    def norm(cols):
        return [strip_nested_prefix(c).lower() for c in cols]

    if norm(lspec.bucket_columns) != norm(lkeys) or norm(rspec.bucket_columns) != norm(rkeys):
        return None
    return plan.left, plan.right, lkeys, rkeys


def _read_buckets(scan: L.IndexScan, columns: List[str], sort_keys: Optional[List[str]] = None) -> Dict[int, B.Batch]:
    """Read an IndexScan's files grouped per bucket id (file name carries the
    bucket; ref layout: part-<bucket>.parquet, indexes/covering.py).

    Only ``columns`` are decoded. When ``sort_keys`` is given, each bucket is
    re-sorted on them if needed: a bucket holding several files (incremental
    refresh merges delta files into existing buckets, UpdateMode.Merge —
    ref: actions/RefreshIncrementalAction.scala:115-128) is only piecewise
    sorted after concatenation."""
    trace.record("scan", "index-bucketed")
    from hyperspace_tpu.indexes.covering import bucket_of_file

    per_bucket: Dict[int, List[str]] = {}
    for f in scan.files:
        b = bucket_of_file(f)
        if b is None:
            raise DeviceUnsupported(f"index file {f!r} has no bucket id")
        per_bucket.setdefault(b, []).append(f)
    from hyperspace_tpu.exec.io import read_parquet_batch

    # nested index columns live under flat __hs_nested. names in the files
    file_cols = [scan.file_column_of(c) for c in columns]
    rename = file_cols != list(columns)

    out: Dict[int, B.Batch] = {}
    for b, files in per_bucket.items():
        batch = read_parquet_batch(files, file_cols)
        if rename:
            batch = {o: batch[fc] for o, fc in zip(columns, file_cols)}
        if sort_keys and len(files) > 1:
            batch = _sort_bucket(batch, sort_keys)
        out[b] = batch
    return out


def _order_key_array(arr: np.ndarray) -> np.ndarray:
    """An order-preserving int64 view of ``arr``, null-safe: strings
    factorize to codes (null -> -1, before everything), datetimes view their
    epoch, floats use the IEEE total-order encoding — the exact encoding the
    index build sorts by (ops/encode.sort_key_int64), so sortedness checks
    and rank comparisons are sound for NaN too (a raw float comparison is
    NaN-blind and a raw object comparison TypeErrors on None)."""
    from hyperspace_tpu.ops.encode import sort_key_int64

    return sort_key_int64(arr)


def _sort_bucket(batch: B.Batch, sort_keys: List[str]) -> B.Batch:
    cols = [_order_key_array(batch[k]) for k in sort_keys]
    if not cols or cols[0].size <= 1:
        return batch
    if len(cols) == 1:
        k = cols[0]
        if np.any(k[1:] < k[:-1]):
            return B.take(batch, np.argsort(k, kind="stable"))
        return batch
    return B.take(batch, np.lexsort(cols[::-1]))  # first key primary


def _composite_ranks(
    l_arrs: List[np.ndarray], r_arrs: List[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Order-preserving dense int64 ranks of the composite key tuples, shared
    across both sides: equal tuples (across sides) get equal ranks, and rank
    order is the lexicographic tuple order. Lets multi-column and string join
    keys reuse the single-int64 span machinery (native merge walk /
    searchsorted) unchanged."""
    n = l_arrs[0].shape[0]
    # order-preserving int codes for strings: python-string comparisons
    # inside lexsort dominate otherwise
    cols = [_order_key_array(np.concatenate([la, ra])) for la, ra in zip(l_arrs, r_arrs)]
    order = np.lexsort(cols[::-1])
    change = np.zeros(order.shape[0], dtype=bool)
    for c in cols:
        cs = c[order]
        if cs.shape[0] > 1:
            change[1:] |= cs[1:] != cs[:-1]
    ranks_sorted = np.cumsum(change.astype(np.int64))
    ranks = np.empty(order.shape[0], dtype=np.int64)
    ranks[order] = ranks_sorted
    return ranks[:n], ranks[n:]


def _side_buckets(
    session, node: L.LogicalPlan, columns: List[str], sort_keys: List[str]
) -> Dict[int, B.Batch]:
    """Per-bucket batches of one join side, each sorted on ``sort_keys``.

    Handles the full hybrid-scan shape: IndexScan leaves, lineage NOT-IN
    Filters (evaluated per bucket — layout preserving), Repartition of
    appended files (host re-bucketing with the SAME hash as the index build,
    so rows land in their index bucket), and BucketUnion (per-bucket concat
    of sorted runs, re-sorted once)."""
    node, _proj = _strip_projects(node)
    if isinstance(node, L.IndexScan):
        return _read_buckets(node, columns, sort_keys=sort_keys)
    if isinstance(node, L.Filter):
        refs = [c for c in node.condition.references()]
        inner_cols = list(dict.fromkeys(list(columns) + refs))
        from hyperspace_tpu.plan.expr import as_bool_mask, contains_input_file_name

        if contains_input_file_name(node.condition):
            raise DeviceUnsupported("input_file_name() predicate on a join side")
        buckets = _side_buckets(session, node.child, inner_cols, sort_keys)
        out: Dict[int, B.Batch] = {}
        for b, batch in buckets.items():
            mask = as_bool_mask(node.condition.eval(batch))
            kept = B.mask_rows(batch, mask)  # order-preserving: stays sorted
            out[b] = {c: kept[c] for c in columns}
        return out
    if isinstance(node, L.Repartition):
        from hyperspace_tpu.exec.executor import Executor
        from hyperspace_tpu.ops.encode import hash_input_uint32
        from hyperspace_tpu.ops.hashing import bucket_ids_np

        spec = node.bucket_spec
        # hybrid scan re-buckets the SAME appended files on every query
        # against the index (ref: CoveringIndexRuleUtils.scala:357-417 —
        # on-the-fly re-bucketing is supposed to be the cheap path); cache
        # the per-bucket result on the appended files' identity so repeat
        # executions skip the decode + hash + sort entirely. A new append
        # changes the file list/mtimes and naturally misses.
        cache_key = None
        files = []
        for p in L.collect(node.child, lambda x: isinstance(x, (L.FileScan, L.Scan))):
            files.extend(_side_files(p) if not isinstance(p, L.Scan)
                         else [fi.name for fi in p.relation.all_file_infos()])
        if files:
            try:
                ident = tuple(
                    (f, os.stat(f).st_mtime_ns, os.stat(f).st_size) for f in files
                )
                cache_key = (
                    "rebucket", ident, spec.num_buckets,
                    tuple(spec.bucket_columns), tuple(columns), tuple(sort_keys),
                    node.child.pretty(),
                )
            except OSError:
                cache_key = None
        if cache_key is not None:
            hit = _REBUCKET_CACHE.get(cache_key)
            if hit is not None:
                trace.record("rebucket", "cached")
                return {b: dict(v) for b, v in hit.items()}
        batch = Executor(session).execute(node.child, required_columns=list(columns))
        try:
            key_cols = [batch[c] for c in spec.bucket_columns]
        except KeyError as e:
            raise DeviceUnsupported(f"bucket column missing from appended side: {e}")
        nb = spec.num_buckets
        ids = bucket_ids_np([hash_input_uint32(c) for c in key_cols], nb)
        order = np.argsort(ids, kind="stable")
        sorted_ids = ids[order]
        bounds = np.searchsorted(sorted_ids, np.arange(nb + 1))
        out = {}
        for b in range(nb):
            lo, hi = int(bounds[b]), int(bounds[b + 1])
            if hi > lo:
                idx = order[lo:hi]
                out[b] = _sort_bucket({c: batch[c][idx] for c in columns}, sort_keys)
        if cache_key is not None:
            nbytes = sum(a.nbytes for v in out.values() for a in v.values()
                         if hasattr(a, "nbytes"))
            # retain COPIES of the per-bucket dicts: the caller gets `out`
            # and may add derived keys; both hit and miss paths must hand
            # out equivalently isolated objects
            _REBUCKET_CACHE.put(cache_key, {b: dict(v) for b, v in out.items()}, nbytes)
            trace.record("rebucket", "computed")
        return out
    if isinstance(node, L.BucketUnion):
        parts = [_side_buckets(session, c, columns, sort_keys) for c in node.children()]
        keys = set()
        for p in parts:
            keys |= set(p)
        out = {}
        for b in keys:
            batches = [p[b] for p in parts if b in p]
            merged = batches[0] if len(batches) == 1 else B.concat(batches)
            out[b] = _sort_bucket(merged, sort_keys) if len(batches) > 1 else merged
        return out
    raise DeviceUnsupported(f"join side {type(node).__name__} is not a bucketed shape")


def _side_bucket_readers(session, node: L.LogicalPlan, columns: List[str], sort_keys: List[str]):
    """Lazy per-bucket readers for one join side: ``{bucket -> thunk}`` where
    each thunk decodes (and sorts/filters) ONLY that bucket when called. The
    streaming join walks buckets one at a time through these, so peak memory
    is one bucket pair instead of both whole sides (``_side_buckets``
    materializes everything — fine below the streaming threshold).

    Shapes mirror ``_side_buckets``: IndexScan leaves, layout-preserving
    Filters, Repartition of appended files (appends are small by the hybrid
    scan ratio caps, so that child materializes once, lazily), BucketUnion.
    """
    node, _proj = _strip_projects(node)
    if isinstance(node, L.IndexScan):
        from hyperspace_tpu.indexes.covering import bucket_of_file
        from hyperspace_tpu.exec.io import read_parquet_batch

        per_bucket: Dict[int, List[str]] = {}
        for f in node.files:
            b = bucket_of_file(f)
            if b is None:
                raise DeviceUnsupported(f"index file {f!r} has no bucket id")
            per_bucket.setdefault(b, []).append(f)
        file_cols = [node.file_column_of(c) for c in columns]
        rename = file_cols != list(columns)

        def make(files):
            def read() -> B.Batch:
                batch = read_parquet_batch(files, file_cols)
                if rename:
                    batch = {o: batch[fc] for o, fc in zip(columns, file_cols)}
                if sort_keys and len(files) > 1:
                    batch = _sort_bucket(batch, sort_keys)
                return batch

            return read

        return {b: make(fs) for b, fs in per_bucket.items()}
    if isinstance(node, L.Filter):
        from hyperspace_tpu.plan.expr import as_bool_mask, contains_input_file_name

        if contains_input_file_name(node.condition):
            raise DeviceUnsupported("input_file_name() predicate on a join side")
        refs = [c for c in node.condition.references()]
        inner_cols = list(dict.fromkeys(list(columns) + refs))
        child = _side_bucket_readers(session, node.child, inner_cols, sort_keys)

        def wrap(thunk):
            def read() -> Optional[B.Batch]:
                batch = thunk()
                if batch is None:  # empty bucket from a Repartition/BucketUnion child
                    return None
                mask = as_bool_mask(node.condition.eval(batch))
                kept = B.mask_rows(batch, mask)  # order-preserving: stays sorted
                return {c: kept[c] for c in columns}

            return read

        return {b: wrap(t) for b, t in child.items()}
    if isinstance(node, L.Repartition):
        # appended-files side: bounded small by hybridScan.maxAppendedRatio,
        # so materializing it once (on first bucket access) keeps the
        # streaming walk's memory profile intact
        cell: Dict[str, Dict[int, B.Batch]] = {}

        def load() -> Dict[int, B.Batch]:
            if "b" not in cell:
                cell["b"] = _side_buckets(session, node, columns, sort_keys)
            return cell["b"]

        nb = node.bucket_spec.num_buckets

        def make_r(b):
            def read() -> Optional[B.Batch]:
                return load().get(b)

            return read

        return {b: make_r(b) for b in range(nb)}
    if isinstance(node, L.BucketUnion):
        parts = [_side_bucket_readers(session, c, columns, sort_keys) for c in node.children()]
        keys = set()
        for p in parts:
            keys |= set(p)

        def make_u(b):
            def read() -> Optional[B.Batch]:
                batches = []
                for p in parts:
                    t = p.get(b)
                    if t is None:
                        continue
                    got = t()
                    if got is not None and B.num_rows(got):
                        batches.append(got)
                if not batches:
                    return None
                if len(batches) == 1:
                    return batches[0]
                return _sort_bucket(B.concat(batches), sort_keys)

            return read

        return {b: make_u(b) for b in keys}
    raise DeviceUnsupported(f"join side {type(node).__name__} is not a bucketed shape")


def _stream_join_dtype_hints(
    plan: L.Join, lside, rside, lcols_needed, rcols_needed
) -> Dict[str, np.dtype]:
    """Footer-derived dtypes for the join's output columns: a bucket where
    one side is absent still needs that side's columns typed (the whole-side
    path reads them from other buckets; per-bucket streaming can't), and an
    EMPTY streamed result is constructed entirely from these."""
    import pyarrow.parquet as pq
    from hyperspace_tpu.sources import schema as schema_codec

    def side_dtypes(side, cols) -> Dict[str, np.dtype]:
        scans = L.collect(side, lambda x: isinstance(x, L.IndexScan))
        if not scans or not scans[0].files:
            return {}
        try:
            sch = pq.read_schema(scans[0].files[0])
        except OSError:
            return {}
        out: Dict[str, np.dtype] = {}
        for c in cols:
            fc = scans[0].file_column_of(c)
            if fc in sch.names:
                try:
                    out[c] = schema_codec.arrow_to_numpy_dtype(sch.field(fc).type)
                except Exception:
                    pass
        return out

    lmap = side_dtypes(lside, lcols_needed)
    rmap = side_dtypes(rside, rcols_needed)
    hints: Dict[str, np.dtype] = {}
    for name in plan.output_columns:
        try:
            is_left, col = _join_column_source(name, lcols_needed, rcols_needed)
        except DeviceUnsupported:
            # a column with no resolvable side keeps no hint: cross-bucket
            # dtype promotion for it then depends on which buckets hold rows.
            # Surface the decision instead of silently narrowing it away.
            trace.fallback("join", "dtype_hint")
            trace.record("join", f"dtype-hint-dropped({name})")
            continue
        dt = (lmap if is_left else rmap).get(col)
        if dt is not None:
            hints[name] = dt
    return hints


def _count_join_stream_chunk() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_join_stream_chunks_total",
        "Chunks yielded by the streaming join paths (bucketed SMJ buckets + broadcast probe chunks)",
    ).inc()


def _chunk_nbytes(batch: B.Batch) -> int:
    return sum(int(np.asarray(a).nbytes) for a in batch.values())


def stream_bucketed_join(session, plan: L.Join, _compat=None):
    """Yield the bucketed SMJ's output ONE BUCKET AT A TIME: per bucket, both
    sides decode, spans compute (native merge walk / searchsorted), pairs
    expand, and the chunk is yielded before the next bucket's expansion. No
    operator state spans buckets, so memory stays O(bucket pair + one output
    chunk) at any scale — the out-of-core discipline Spark's streaming
    executors give the reference for free (ref:
    HS/index/covering/JoinIndexRule.scala:604-705, valid at any SF).

    With ``hyperspace.exec.join.pipeline.enabled`` (and the pipeline master
    switch) on, bucket b+1's BOTH side decodes — plus their span-key
    encodings, the expensive host half of the bucket — run on the prefetch
    pipeline (exec/pipeline.py) while bucket b's spans compute on the
    consumer thread, double-buffered under the pipeline depth/byte budgets
    and cancel-safe on generator close. Off, the serial consumer-thread loop
    is preserved bit-for-bit.

    Used above conf ``hyperspace.exec.stream.joinMinBytes`` (estimated from
    file sizes) by ``dispatch_bucketed_join``, and by
    ``DataFrame.to_local_iterator`` for callers that drain results
    incrementally. Chunk dtypes may differ across buckets (a nullable int
    column is float64 only in chunks holding nulls); ``B.concat`` promotes.
    """
    ensure_x64()
    from hyperspace_tpu import native

    compat = _compat if _compat is not None else join_sides_compatible(plan)
    if compat is None:
        raise DeviceUnsupported("join sides are not compatible bucketed index scans")
    lside, rside, lkeys, rkeys = compat
    if plan.how not in ("inner", "left", "right", "outer"):
        raise DeviceUnsupported(f"unsupported join type {plan.how!r}")
    needed = set(plan.output_columns) | {
        n[:-2] for n in plan.output_columns if n.endswith("#r")
    }
    lcols_needed = [c for c in lside.output_columns if c in needed or c in lkeys]
    rcols_needed = [c for c in rside.output_columns if c in needed or c in rkeys]
    lread = _side_bucket_readers(session, lside, lcols_needed, lkeys)
    rread = _side_bucket_readers(session, rside, rcols_needed, rkeys)
    nb = _side_bucket_spec(lside).num_buckets
    keep_left = plan.how in ("left", "outer")
    keep_right = plan.how in ("right", "outer")

    hints = _stream_join_dtype_hints(plan, lside, rside, lcols_needed, rcols_needed)
    parts = [b for b in range(nb) if b in lread or b in rread]

    def decode_pair(b):
        """Producer half: both side decodes + span-key encoding (the
        rank/int64 encode is the bucket's dominant host cost after decode,
        so it prefetches too)."""
        from hyperspace_tpu.reliability.faults import FAULTS

        if FAULTS.active:
            FAULTS.check("join.task")
        lt, rt = lread.get(b), rread.get(b)
        lb = lt() if lt is not None else None
        rb = rt() if rt is not None else None
        if lb is not None and B.num_rows(lb) == 0:
            lb = None
        if rb is not None and B.num_rows(rb) == 0:
            rb = None
        lk = rk = None
        if lb is not None and rb is not None:
            if len(lkeys) == 1:
                try:
                    lk = _join_key_of(lb, lkeys[0])
                    rk = _join_key_of(rb, rkeys[0])
                except DeviceUnsupported:
                    lk = rk = None
            if lk is None:
                lk, rk = _composite_ranks(
                    [lb[k] for k in lkeys], [rb[k] for k in rkeys]
                )
        return lb, rb, lk, rk

    def expand(lb, rb, lk, rk):
        """Consumer half: span walk + pair expansion; None when the bucket
        contributes no output rows."""
        if lb is None and rb is None:
            return None
        if lb is None and not keep_right:
            return None
        if rb is None and not keep_left:
            return None
        span_of = None
        if lb is not None and rb is not None:

            def span_of(_b, lk=lk, rk=rk):
                try:
                    return native.merge_spans(lk, rk)
                except native.NativeUnsupported:
                    return (
                        np.searchsorted(rk, lk, side="left"),
                        np.searchsorted(rk, lk, side="right"),
                    )

        chunk = _expand_join_pairs(
            plan,
            {0: lb} if lb is not None else {},
            {0: rb} if rb is not None else {},
            1,
            lcols_needed,
            rcols_needed,
            span_of,
            dtype_fallback=hints,
        )
        return chunk if B.num_rows(chunk) else None

    conf = session.conf
    if conf.join_pipeline_enabled and conf.pipeline_enabled and len(parts) > 1:
        from hyperspace_tpu.exec.pipeline import ScanPipeline

        def weigh(res):
            lb, rb, _lk, _rk = res
            return sum(_chunk_nbytes(s) for s in (lb, rb) if s is not None)

        pipe = ScanPipeline(
            [lambda b=b: decode_pair(b) for b in parts],
            depth=conf.pipeline_depth,
            max_buffered_bytes=conf.pipeline_max_buffered_bytes,
            weigh=weigh,
        )
        try:
            for lb, rb, lk, rk in pipe:
                chunk = expand(lb, rb, lk, rk)
                if chunk is not None:
                    _count_join_stream_chunk()
                    yield chunk
        finally:
            # generator close mid-stream lands here: cancel queued bucket
            # decodes and wait out in-flight ones so neither side's readers
            # outlive the stream (the pipeline cancel-safety contract)
            pipe.close()
        return

    for b in parts:
        chunk = expand(*decode_pair(b))
        if chunk is not None:
            _count_join_stream_chunk()
            yield chunk


@lru_cache(maxsize=32)
def _bucketed_span_program(mesh, axis: str):
    """Jitted per-bucket match-span program, cached per mesh so repeated joins
    reuse one XLA executable (jit's own cache handles shape variation)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from hyperspace_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    @jax.jit
    def spans(lm, rm):
        @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis)))
        def per_shard(lm_, rm_):
            lo = jax.vmap(lambda lk, rk: jnp.searchsorted(rk, lk, side="left"))(lm_, rm_)
            hi = jax.vmap(lambda lk, rk: jnp.searchsorted(rk, lk, side="right"))(lm_, rm_)
            return lo, hi
        return per_shard(lm, rm)

    return spans


def _join_key_of(batch: B.Batch, key: str) -> np.ndarray:
    """Encode a join-key column; only identity-ordered encodings are
    cross-side comparable."""
    arr = batch[key]
    if arr.dtype.kind in ("i", "u", "b"):
        return arr.astype(np.int64)
    if arr.dtype.kind == "M":
        return arr.view("int64").astype(np.int64)
    raise DeviceUnsupported(f"device join requires integer/datetime keys; got {arr.dtype}")


_FOOTER_ROWS_CACHE: Dict[Tuple[str, int, int], int] = {}


def _file_num_rows(path: str) -> int:
    """Row count from the parquet footer, memoized on (path, mtime, size)."""
    import pyarrow.parquet as pq

    st = os.stat(path)
    key = (path, st.st_mtime_ns, st.st_size)
    got = _FOOTER_ROWS_CACHE.get(key)
    if got is None:
        if len(_FOOTER_ROWS_CACHE) > 65536:
            _FOOTER_ROWS_CACHE.clear()
        got = pq.read_metadata(path).num_rows
        _FOOTER_ROWS_CACHE[key] = got
    return got


def _side_files(node: L.LogicalPlan) -> List[str]:
    files: List[str] = []
    for p in L.collect(node, lambda x: isinstance(x, (L.IndexScan, L.FileScan))):
        files.extend(p.files)
    return files


# composite-key rank encodings keyed on both sides' full identity, byte-capped
# like every other cache (exec/io.py's _io_cache pattern)
from hyperspace_tpu.utils.lru import BytesLRU

_RANK_CACHE = BytesLRU(int(os.environ.get("HS_RANK_CACHE_BYTES", 1 << 29)))

# re-bucketed hybrid-scan appends, keyed on the appended files' identity
# (see the Repartition branch of _side_buckets)
_REBUCKET_CACHE = BytesLRU(int(os.environ.get("HS_REBUCKET_CACHE_BYTES", 1 << 28)))


def _rank_cache_key(lside, rside, lkeys: List[str], rkeys: List[str]):
    """Identity of a rank encoding: both sides' (file, mtime, size) sets, the
    key names, AND the sides' plan text — ranks are computed over rows that
    survive the sides' Filters (lineage NOT-IN, pushed predicates), so a
    changed filter over identical files must miss. None (= don't cache) when
    any file can't be stat'ed."""
    parts = [tuple(lkeys), tuple(rkeys), lside.pretty(), rside.pretty()]
    for side in (lside, rside):
        files = []
        for f in _side_files(side):
            try:
                st = os.stat(f)
            except OSError:
                return None
            files.append((f, st.st_mtime_ns, st.st_size))
        parts.append(tuple(files))
    return tuple(parts)


def dispatch_bucketed_join(session, plan: L.Join) -> B.Batch:
    """Single entry point for the bucketed-SMJ paths: one compatibility
    analysis, then device or host spans by the input-rows threshold. Every
    key shape rides the device span program — single int/date keys feed it
    directly, composite and string keys through the shared per-bucket rank
    encodings (_encoded_join_keys). Raises DeviceUnsupported when the join
    isn't a compatible bucketed pair (the executor then falls back to its
    generic merge join)."""
    ensure_x64()
    compat = join_sides_compatible(plan)
    if compat is None:
        raise DeviceUnsupported("join sides are not compatible bucketed index scans")
    lside, rside, lkeys, rkeys = compat
    try:
        total = sum(
            _file_num_rows(f) for side in (lside, rside) for f in _side_files(side)
        )
    except OSError:
        total = 0  # unreadable footer -> stay on host
    # out-of-core gate: above the streaming threshold (estimated from file
    # sizes — no decode), walk buckets one at a time instead of decoding
    # both whole sides; peak memory drops to O(bucket pair + output)
    stream_min = session.conf.stream_join_min_bytes
    if stream_min and stream_min > 0:
        try:
            input_bytes = sum(
                os.stat(f).st_size for side in (lside, rside) for f in _side_files(side)
            )
        except OSError:
            input_bytes = 0
        if input_bytes >= stream_min:
            # fold chunks incrementally instead of list()-ing the whole
            # stream: peak memory is O(merged result + one pending run), not
            # O(result x2), and the generator is closed on any exit so both
            # sides' bucket readers release mid-stream
            gen = stream_bucketed_join(session, plan, _compat=compat)
            merged = None
            merged_bytes = 0
            pending: List[B.Batch] = []
            pending_bytes = 0
            try:
                for chunk in gen:
                    pending.append(chunk)
                    pending_bytes += _chunk_nbytes(chunk)
                    # geometric fold: concat once the pending run reaches the
                    # merged size, so total copy work stays O(result) while
                    # at most one merged copy + one run are ever alive
                    if merged is None or pending_bytes >= merged_bytes:
                        batches = ([merged] if merged is not None else []) + pending
                        merged = batches[0] if len(batches) == 1 else B.concat(batches)
                        merged_bytes = _chunk_nbytes(merged)
                        pending, pending_bytes = [], 0
            finally:
                gen.close()
            if pending:
                batches = ([merged] if merged is not None else []) + pending
                merged = batches[0] if len(batches) == 1 else B.concat(batches)
            if merged is None:
                # an empty streamed result must NOT fall back to the generic
                # merge — that materializes both multi-GiB sides, the OOM
                # this path exists to prevent; type the empty batch from the
                # index footers instead
                needed = set(plan.output_columns) | {
                    n[:-2] for n in plan.output_columns if n.endswith("#r")
                }
                lc = [c for c in lside.output_columns if c in needed or c in lkeys]
                rc = [c for c in rside.output_columns if c in needed or c in rkeys]
                hints = _stream_join_dtype_hints(plan, lside, rside, lc, rc)
                if all(n in hints for n in plan.output_columns):
                    trace.record("join", "host-span-smj-stream")
                    return {n: np.empty(0, dtype=hints[n]) for n in plan.output_columns}
                raise DeviceUnsupported("streamed join produced no rows")
            trace.record("join", "host-span-smj-stream")
            return merged
    setup = _bucketed_join_setup(session, plan, compat)
    # the device span program's round trip is EXACTLY computable here: the
    # buckets are already decoded, and the key matrices are rectangles of
    # nb_padded x (widest bucket) int64 — skewed buckets pad every other
    # row to the widest, so raw row counts would badly undercount. Keys go
    # up (both rectangles), [lo, hi) comes down (16B per left SLOT). Above
    # the budget the host span walk (zero transfer) wins — the same
    # cost-based stance as joinDeviceMaterializeMaxBytes one level down.
    lbuckets_, rbuckets_, _lk_, _rk_, nb_, _lc_, _rc_ = setup
    n_dev_ = session.mesh.devices.size
    nb_padded_ = nb_ + ((-nb_) % n_dev_)
    wl_ = max((B.num_rows(b) for b in lbuckets_.values()), default=1)
    wr_ = max((B.num_rows(b) for b in rbuckets_.values()), default=1)
    span_bytes = nb_padded_ * (wl_ + wr_) * 8
    # the [lo, hi) matrices (16B/left slot) only come down when the
    # device-materialize path won't consume them on device; a materialize
    # run that later overflows ITS budget falls back to the host gather and
    # does download them once — accepted imprecision, bounded by one rep
    if plan.how != "inner" or not session.conf.join_device_materialize:
        span_bytes += nb_padded_ * wl_ * 16
    if (
        total >= session.conf.device_exec_min_rows
        and span_bytes <= session.conf.join_device_span_max_bytes
    ):
        try:
            out = device_bucketed_join(session, plan, _compat=compat, _setup=setup)
            trace.record("join", "device-smj")
            return out
        except DeviceUnsupported:
            pass  # e.g. a decoded batch outside the device language
    out = host_bucketed_join(session, plan, _compat=compat, _setup=setup)
    trace.record("join", "host-span-smj")
    return out


def _bucketed_join_setup(session, plan: L.Join, compat=None, needed_override=None):
    """Shared validation + per-bucket decode for the bucketed SMJ paths.

    Returns (lbuckets, rbuckets, lkeys, rkeys, nb, lcols_needed,
    rcols_needed). ``needed_override`` = (left cols, right cols) replaces the
    join-output-derived column need (the fused aggregate reads only its
    inputs, not the join's full output).
    """
    if compat is None:
        compat = join_sides_compatible(plan)
    if compat is None:
        raise DeviceUnsupported("join sides are not compatible bucketed index scans")
    lside, rside, lkeys, rkeys = compat
    if plan.how not in ("inner", "left", "right", "outer"):
        raise DeviceUnsupported(f"unsupported join type {plan.how!r}")

    # decode only the columns the consumer (plus keys) needs
    if needed_override is not None:
        need_l, need_r = set(needed_override[0]), set(needed_override[1])
    else:
        needed = set(plan.output_columns) | {n[:-2] for n in plan.output_columns if n.endswith("#r")}
        need_l = need_r = needed
    lcols_needed = [c for c in lside.output_columns if c in need_l or c in lkeys]
    rcols_needed = [c for c in rside.output_columns if c in need_r or c in rkeys]
    lbuckets = _side_buckets(session, lside, lcols_needed, lkeys)
    rbuckets = _side_buckets(session, rside, rcols_needed, rkeys)
    nb = _side_bucket_spec(lside).num_buckets
    return lbuckets, rbuckets, lkeys, rkeys, nb, lcols_needed, rcols_needed


def _expand_join_pairs(
    plan: L.Join,
    lbuckets: Dict[int, B.Batch],
    rbuckets: Dict[int, B.Batch],
    nb: int,
    lcols_needed: List[str],
    rcols_needed: List[str],
    span_of,
    dtype_fallback=None,
) -> B.Batch:
    """Pair expansion (variable-size output) + column gather, shared by the
    device and host span backends. ``span_of(b)`` returns (lo, hi) arrays of
    length len(left bucket b) — the matching right-row span per left row.

    Two passes: spans/counts first, then gathers straight into preallocated
    output columns (a concat of per-bucket batches would copy the whole
    result a second time). Outer joins (left/right/outer) emit unmatched rows
    with the opposite side's columns null (index -1 in the gather arrays;
    ints promote to float64 NaN, matching the pandas-merge fallback)."""
    how = plan.how
    keep_left = how in ("left", "outer")
    keep_right = how in ("right", "outer")
    out_cols = plan.output_columns
    lout = list(lcols_needed)
    rout = list(rcols_needed)

    # pass 1: per-bucket gather index arrays; -1 marks a null (unmatched) row
    from hyperspace_tpu import native

    def expand_inner(lo_b, counts, chunk_total):
        try:
            # int64 hi: expand_pairs itself guards the int32 range and
            # rejects oversize buckets back to the numpy path
            return native.expand_pairs(lo_b, np.asarray(lo_b, dtype=np.int64) + counts, chunk_total)
        except native.NativeUnsupported:
            ll = counts.shape[0]
            lidx = np.repeat(np.arange(ll), counts)
            offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
            ridx = np.arange(chunk_total) - np.repeat(offsets, counts) + np.repeat(lo_b, counts)
            return lidx, ridx

    # pieces hold (bucket, row_count, maker) with maker() -> (lidx, ridx);
    # expansion is deferred to pass 2 so only ONE bucket's index arrays are
    # alive at a time (peak memory matters on large inner joins)
    def matched_maker(lo_b, counts, keep_left_):
        def make():
            if keep_left_:
                # unmatched left rows expand as one (i, lo[i]) pair via the
                # same native kernel, then get their right index nulled
                counts_eff = np.maximum(counts, 1)
                ct = int(counts_eff.sum())
                lidx, ridx = expand_inner(np.asarray(lo_b), counts_eff, ct)
                null_rows = np.repeat(counts == 0, counts_eff)
                if null_rows.any():
                    ridx = np.asarray(ridx, dtype=np.int64)
                    ridx[null_rows] = -1
                return lidx, ridx
            return expand_inner(np.asarray(lo_b), counts, int(counts.sum()))

        return make

    pieces = []  # (bucket, count, maker)
    total = 0
    has_null_left = has_null_right = False
    for b in range(nb):
        lb = lbuckets.get(b)
        rb = rbuckets.get(b)
        ll = B.num_rows(lb) if lb is not None else 0
        rr = B.num_rows(rb) if rb is not None else 0
        if ll and rr:
            lo_b, hi_b = span_of(b)
            counts = (hi_b - lo_b).astype(np.int64)
            if keep_left:
                ct = int(np.maximum(counts, 1).sum())
                if (counts == 0).any():
                    has_null_right = True
                pieces.append((b, ct, matched_maker(lo_b, counts, True)))
                total += ct
            else:
                ct = int(counts.sum())
                if ct:
                    pieces.append((b, ct, matched_maker(lo_b, counts, False)))
                    total += ct
            if keep_right:
                # right rows covered by no span are unmatched
                cover = np.zeros(rr + 1, dtype=np.int64)
                sel = counts > 0
                np.add.at(cover, np.asarray(lo_b)[sel], 1)
                np.add.at(cover, np.asarray(hi_b)[sel], -1)
                unmatched = np.nonzero(np.cumsum(cover[:-1]) == 0)[0]
                if unmatched.size:
                    pieces.append(
                        (b, unmatched.size,
                         lambda u=unmatched: (np.full(u.size, -1, dtype=np.int64), u))
                    )
                    total += unmatched.size
                    has_null_left = True
        elif ll and keep_left:
            pieces.append((b, ll, lambda n_=ll: (np.arange(n_), np.full(n_, -1, dtype=np.int64))))
            total += ll
            has_null_right = True
        elif rr and keep_right:
            pieces.append((b, rr, lambda n_=rr: (np.full(n_, -1, dtype=np.int64), np.arange(n_))))
            total += rr
            has_null_left = True

    sources = {name: _join_column_source(name, lout, rout) for name in out_cols}
    participating = sorted({p[0] for p in pieces})
    # USING-style joins coalesce the key (Spark's df.join(other, on="k")):
    # a right/outer join's unmatched rows show the RIGHT side's key under
    # the left name instead of NULL — map left key output -> right source col
    coalesce_from = {}
    if keep_right and plan.using_pairs:
        for lk, rk in plan.using_pairs:
            if lk in out_cols and rk in rout:
                coalesce_from[lk] = rk

    def out_dtype(name: str) -> np.dtype:
        is_left, col = sources[name]
        src = lbuckets if is_left else rbuckets
        # promote across participating buckets (a nullable int column decodes
        # as float64 only in buckets whose files hold nulls), matching what
        # np.concatenate of per-bucket results used to do
        part = participating or sorted(src)
        dt = _join_column_dtype(
            name, sources[name], lbuckets, rbuckets, part, fallback=dtype_fallback
        )
        nullable = (is_left and has_null_left) or (not is_left and has_null_right)
        if nullable and dt.kind == "b":
            return np.dtype(object)  # pandas merge: bool + NaN -> object
        if nullable and dt.kind in ("i", "u"):
            return np.dtype(np.float64)  # pandas-merge null promotion
        return dt

    out = {name: np.empty(total, dtype=out_dtype(name)) for name in out_cols}
    if total == 0:
        return out

    def null_value(dt: np.dtype):
        if dt.kind == "M":
            return np.datetime64("NaT")
        if dt.kind == "m":
            return np.timedelta64("NaT")
        return np.nan  # float holes; pandas merge also fills object with NaN

    # pass 2: gather into the preallocated columns, expanding one bucket's
    # index arrays at a time
    off = 0
    for b, ct, make in pieces:
        lidx, ridx = make()
        for name in out_cols:
            is_left, col = sources[name]
            src = lbuckets if is_left else rbuckets
            idx = lidx if is_left else ridx
            arr = src.get(b, {}).get(col)
            if arr is None or arr.shape[0] == 0:
                # side absent for this bucket (or filtered to zero rows):
                # every index here is -1 by construction
                out[name][off : off + ct] = null_value(out[name].dtype)
                nulls = np.ones(ct, dtype=bool)
            else:
                nulls = np.asarray(idx) < 0
                if nulls.any():
                    vals = out[name][off : off + ct]
                    vals[:] = arr[np.clip(idx, 0, arr.shape[0] - 1)].astype(
                        out[name].dtype, copy=False
                    )
                    vals[nulls] = null_value(out[name].dtype)
                else:
                    out[name][off : off + ct] = arr[idx]
            alt = coalesce_from.get(name) if is_left else None
            if alt is not None and nulls.any():
                # left-null rows came from right-unmatched emissions: their
                # ridx is valid, so the USING key takes the right side's value
                ralt = rbuckets.get(b, {}).get(alt)
                fill = np.asarray(ridx)[nulls]
                ok = fill >= 0
                if ralt is not None and ralt.shape[0] and ok.any():
                    vals = out[name][off : off + ct]
                    sel = np.nonzero(nulls)[0][ok]
                    vals[sel] = ralt[fill[ok]].astype(out[name].dtype, copy=False)
        off += ct
    return out


def device_bucketed_join(session, plan: L.Join, _compat=None, _setup=None) -> B.Batch:
    """Execute a compatible bucketed equi-join on device.

    Per-bucket sorted runs of both sides are padded to rectangles, sharded over
    the mesh's bucket axis, and each device computes, for every left row, the
    [lo, hi) span of matching right rows via two vmapped ``searchsorted``
    passes — no collective is emitted (the reference's no-exchange SMJ,
    HS/index/covering/JoinIndexRule.scala:604-618). Pair expansion and column
    gathering happen host-side (variable-size output).
    """
    ensure_x64()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    setup = _setup if _setup is not None else _bucketed_join_setup(session, plan, _compat)
    lbuckets, rbuckets, lkeys, rkeys, nb, lcols_needed, rcols_needed = setup

    SENTINEL = np.int64(2**62)
    mesh = session.mesh
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]
    nb_padded = nb + ((-nb) % n_dev)

    # index bucket files are immutable (versioned v__=N dirs), so the sharded
    # key matrices stay resident in HBM across queries — same stance as the
    # predicate-column cache above; only the first execution of a (sides,
    # keys) pair pays the host->device transfer (which crosses a network
    # tunnel in the single-chip harness)
    compat = _compat or join_sides_compatible(plan)
    pair_key = _rank_cache_key(compat[0], compat[1], lkeys, rkeys)
    mesh_tag = (n_dev, axis, tuple(str(d) for d in mesh.devices.flat))
    dev_key = ("join-keymats", pair_key, mesh_tag) if pair_key is not None else None
    cached = _device_cache_get(dev_key) if dev_key is not None else None
    if cached is not None:
        lmat_dev, rmat_dev, llens, rlens = cached
    else:
        # shared per-bucket int64 encodings: identity for single int/date
        # keys, dense cross-side ranks for composite/string keys — so every
        # key shape rides the device span program
        lkeys_by_bucket, rkeys_by_bucket = _encoded_join_keys(
            plan, setup, compat, _pair_key=pair_key
        )

        def stack_side(buckets: Dict[int, B.Batch], keymap: Dict[int, np.ndarray]):
            lens = [B.num_rows(buckets[b]) if b in buckets else 0 for b in range(nb_padded)]
            # bucket the rectangle width so streamed chunks of slightly
            # varying bucket sizes reuse the span program's executable
            width = bucket_rows(max(max(lens), 1), floor=256)
            keys_mat = np.full((nb_padded, width), SENTINEL, dtype=np.int64)
            for b in range(nb_padded):
                enc = keymap.get(b)
                if enc is not None and enc.shape[0]:
                    keys_mat[b, : enc.shape[0]] = enc
            return keys_mat, np.asarray(lens, dtype=np.int64)

        lmat, llens = stack_side(lbuckets, lkeys_by_bucket)
        rmat, rlens = stack_side(rbuckets, rkeys_by_bucket)
        sharding = NamedSharding(mesh, P(axis))
        lmat_dev = jax.device_put(lmat, sharding)
        rmat_dev = jax.device_put(rmat, sharding)
        if dev_key is not None:
            _device_cache_put(
                dev_key, (lmat_dev, rmat_dev, llens, rlens), lmat.nbytes + rmat.nbytes
            )

    spans = _bucketed_span_program(mesh, axis)
    first = _note_compile("join-span", (tuple(lmat_dev.shape), tuple(rmat_dev.shape)))
    _hlo_lint.maybe_verify(
        session.conf, "bucketed-smj-span", _program_key("join-span", mesh),
        spans, (lmat_dev, rmat_dev),
    )
    t0 = _ptime.perf_counter()
    lo, hi = spans(lmat_dev, rmat_dev)
    _stage_ir.count_dispatch("bucketed-smj-span")
    _observe_program("bucketed-smj-span", first, t0)

    if plan.how == "inner" and session.conf.join_device_materialize:
        try:
            return _device_materialize_inner(
                session, plan, lbuckets, rbuckets, lcols_needed, rcols_needed,
                lo, hi, llens, rlens, nb, nb_padded,
                _ident=(pair_key, mesh_tag) if pair_key is not None else None,
            )
        except DeviceUnsupported:
            pass  # e.g. typed-empty output or odd column shapes -> host gather

    lo = np.asarray(lo)
    hi = np.asarray(hi)

    def span_of(b: int):
        ll = int(llens[b])
        return lo[b, :ll], hi[b, :ll]

    return _expand_join_pairs(plan, lbuckets, rbuckets, nb, lcols_needed, rcols_needed, span_of)


def _encoded_join_keys(plan: L.Join, setup, compat, _pair_key=None):
    """Per-bucket int64 key arrays for both sides, order-preserving and
    cross-side comparable. Single int64-comparable keys pass through;
    composite and string keys encode per bucket into shared dense int64
    ranks, cached across queries on the sides' immutable file + filter
    identity. The SAME arrays feed the host merge walk and the device span
    program, so both backends cover every key shape. ``_pair_key`` lets a
    caller that already computed `_rank_cache_key` (one os.stat sweep per
    side) pass it through instead of re-statting."""
    lbuckets, rbuckets, lkeys, rkeys, _nb, _lc, _rc = setup

    single_int = len(lkeys) == 1
    lkeys_by_bucket: Dict[int, np.ndarray] = {}
    rkeys_by_bucket: Dict[int, np.ndarray] = {}
    if single_int:
        try:
            for b, batch in lbuckets.items():
                lkeys_by_bucket[b] = _join_key_of(batch, lkeys[0])
            for b, batch in rbuckets.items():
                rkeys_by_bucket[b] = _join_key_of(batch, rkeys[0])
        except DeviceUnsupported:
            single_int = False
    if not single_int:
        lside, rside = (compat or join_sides_compatible(plan))[:2]
        cache_key = (
            _pair_key
            if _pair_key is not None
            else _rank_cache_key(lside, rside, lkeys, rkeys)
        )
        cached = _RANK_CACHE.get(cache_key) if cache_key is not None else None
        if cached is not None:
            lkeys_by_bucket, rkeys_by_bucket = cached
        else:
            lkeys_by_bucket.clear()
            rkeys_by_bucket.clear()
            for b in set(lbuckets) & set(rbuckets):
                lr, rr = _composite_ranks(
                    [lbuckets[b][k] for k in lkeys], [rbuckets[b][k] for k in rkeys]
                )
                lkeys_by_bucket[b] = lr
                rkeys_by_bucket[b] = rr
            if cache_key is not None:
                nbytes = sum(a.nbytes for d in (lkeys_by_bucket, rkeys_by_bucket) for a in d.values())
                _RANK_CACHE.put(cache_key, (lkeys_by_bucket, rkeys_by_bucket), nbytes)
    return lkeys_by_bucket, rkeys_by_bucket


def _join_column_source(name: str, lout, rout) -> Tuple[bool, str]:
    """(is_left, source column name) for one join output column; the join's
    '#r'-suffixed duplicates resolve to the right side (the single naming
    convention of plan/logical.join_output_names)."""
    if name in lout:
        return True, name
    if name.endswith("#r") and name[:-2] in rout:
        return False, name[:-2]
    if name in rout:
        return False, name
    raise DeviceUnsupported(f"join output column {name!r} not found on either side")


def _join_column_dtype(
    name: str, source, lbuckets, rbuckets, participating, fallback=None
) -> np.dtype:
    """Column dtype promoted across the participating buckets (a nullable int
    column decodes as float64 only in buckets whose files hold nulls).
    ``fallback`` maps column name -> dtype for columns with no decoded data
    in scope — the per-bucket streaming join types a missing side's columns
    from the index footers (the whole-side path always has other buckets)."""
    is_left, col = source
    src = lbuckets if is_left else rbuckets
    dtypes = [src[b][col].dtype for b in participating if col in src.get(b, {})]
    if not dtypes:
        if fallback is not None and name in fallback:
            return fallback[name]
        raise DeviceUnsupported(f"cannot determine dtype of empty join column {name!r}")
    if any(dt == object for dt in dtypes):
        return np.dtype(object)
    return np.result_type(*dtypes)


from functools import lru_cache


@lru_cache(maxsize=32)
def _expand_gather_program(n_pad: int):
    """Jitted inner-join materialization: expand every (left row, matching
    right row) pair AND gather the numeric payload columns in one device
    program — the host receives final columns only (SURVEY §2.9
    "device-local merge-join kernel"). One compile per output size class.

    Shapes: ``lo``/``hi``/``llens`` describe the span matrices ((nb, Wl) and
    (nb,)); ``lcols``/``rcols`` are tuples of (nb, Wl)/(nb, Wr) rectangles.
    Output slot ``t`` maps to its (bucket, left row, right row) via ONE
    global searchsorted over the flattened inclusive pair-count cumsum — no
    (n_pad, Wl) intermediates, so memory stays O(rows + pairs)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(lo, hi, llens, rlens, lcols, rcols, total):
        nb, wl = lo.shape
        # clamp spans to the right side's REAL rows: a left-only bucket's
        # SENTINEL padding keys would otherwise "match" the right rectangle's
        # SENTINEL padding region
        lo = jnp.minimum(lo, rlens[:, None])
        hi = jnp.minimum(hi, rlens[:, None])
        col_idx = jnp.arange(wl)[None, :]
        counts = jnp.where(col_idx < llens[:, None], hi - lo, 0)
        flat_counts = counts.reshape(-1)
        g_incl = jnp.cumsum(flat_counts)
        g_excl = g_incl - flat_counts
        t = jnp.arange(n_pad, dtype=g_incl.dtype)
        f = jnp.clip(jnp.searchsorted(g_incl, t, side="right"), 0, flat_counts.shape[0] - 1)
        valid = t < total
        b = f // wl
        i = f % wl
        p = t - g_excl[f]
        j = jnp.clip(lo.reshape(-1)[f] + p, 0, None)
        louts = tuple(c.reshape(-1)[f] for c in lcols)
        routs = tuple(c[b, jnp.clip(j, 0, c.shape[1] - 1)] for c in rcols)
        return louts, routs, b, i, j, valid

    return run


@lru_cache(maxsize=1)
def _bucket_pair_totals_fn():
    """One jitted per-bucket matched-pair-count reduction shared by every
    device-materialized join (a fresh jit per call would recompile on the
    query hot path)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(lo, hi, ll, rl):
        return jnp.sum(
            jnp.where(
                jnp.arange(lo.shape[1])[None, :] < ll[:, None],
                jnp.minimum(hi, rl[:, None]) - jnp.minimum(lo, rl[:, None]),
                0,
            ),
            axis=1,
        )

    return run


def _bucket_pair_totals(lo, hi, ll, rl):
    return _bucket_pair_totals_fn()(lo, hi, ll, rl)


def _device_materialize_inner(
    session, plan: L.Join, lbuckets, rbuckets, lcols_needed, rcols_needed,
    lo_dev, hi_dev, llens, rlens, nb, nb_padded, _ident=None,
) -> B.Batch:
    """Device-side materialization of a compatible bucketed INNER join: pair
    expansion and numeric column gathers run on device; only string/object
    columns gather host-side (by the downloaded index arrays)."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.sort import padded_size

    if plan.how != "inner":
        raise DeviceUnsupported("device materialization covers inner joins")
    out_cols = plan.output_columns

    participating = sorted(set(lbuckets) & set(rbuckets))
    if not participating:
        # no overlapping buckets: empty inner join; let the host path build
        # the typed empty columns it already knows how to produce
        raise DeviceUnsupported("no overlapping buckets")

    sources = {
        name: _join_column_source(name, lcols_needed, rcols_needed) for name in out_cols
    }
    dtypes = {
        name: _join_column_dtype(name, sources[name], lbuckets, rbuckets, participating)
        for name in out_cols
    }
    device_cols = [n for n in out_cols if dtypes[n].kind in ("i", "u", "f", "b", "M", "m")]
    host_cols = [n for n in out_cols if n not in device_cols]

    # pair totals size the static output; one tiny d2h (nb ints)
    wl = lo_dev.shape[1]
    llens_np = np.asarray(llens)
    rlens_np = np.asarray(rlens)
    bucket_totals = np.asarray(
        _bucket_pair_totals(lo_dev, hi_dev, jnp.asarray(llens_np), jnp.asarray(rlens_np))
    )
    total = int(bucket_totals.sum())
    out: B.Batch = {}
    if total == 0:
        for name in out_cols:
            dt = dtypes[name]
            out[name] = np.empty(0, dtype=dt)
        return out
    # cost-based placement: a device-materialized join downloads its WHOLE
    # output, so above the configured byte budget the host expansion (native
    # C pair kernels, no device->host transfer) wins — measured 282 s device
    # vs ~25 s host on a 37.5M-pair join over a network-tunneled chip.
    # Downloads happen at the PADDED size (next power of two), and a host
    # (string) gather additionally downloads the b/i/j index arrays.
    n_pad = padded_size(total)
    est_bytes = n_pad * max(1, len(device_cols)) * 8
    if host_cols:
        est_bytes += 3 * n_pad * 8
    if est_bytes > session.conf.join_device_materialize_max_bytes:
        raise DeviceUnsupported(
            f"materialized output ~{est_bytes >> 20} MiB exceeds "
            "joinDeviceMaterializeMaxBytes -> host expansion"
        )

    def rectangles(side_buckets, cols, width_of):
        """(name -> (nb_padded, W) device-feedable rectangle) per column."""
        mats = {}
        for name in cols:
            is_left, col = sources[name]
            dt = dtypes[name]
            view_int = dt.kind in ("M", "m")
            base = np.dtype(np.int64) if view_int else (dt if dt.kind != "b" else np.dtype(np.int64))
            width = max(width_of, 1)
            mat = np.zeros((nb_padded, width), dtype=base)
            for b in participating:
                arr = side_buckets[b].get(col)
                if arr is None:
                    raise DeviceUnsupported(f"column {col!r} absent in bucket {b}")
                v = arr.view("int64") if view_int else arr
                mat[b, : v.shape[0]] = v.astype(base, copy=False)
            mats[name] = mat
        return mats

    l_device = [n for n in device_cols if sources[n][0]]
    r_device = [n for n in device_cols if not sources[n][0]]
    # the payload rectangles are pure functions of the sides' immutable
    # decoded buckets, so they stay HBM-resident across queries like the key
    # matrices (only the first execution pays the host->device transfer)
    mats_key = (
        ("join-paymats", _ident, tuple(l_device), tuple(r_device))
        if _ident is not None
        else None
    )
    cached = _device_cache_get(mats_key) if mats_key is not None else None
    if cached is not None:
        llens_dev, rlens_dev, lmats_dev, rmats_dev = cached
    else:
        wr = bucket_rows(max((B.num_rows(rbuckets[b]) for b in participating), default=1), floor=256)
        lmats = rectangles(lbuckets, l_device, wl)
        rmats = rectangles(rbuckets, r_device, wr)
        llens_dev = jax.device_put(llens_np)
        rlens_dev = jax.device_put(rlens_np)
        lmats_dev = tuple(jax.device_put(lmats[n]) for n in l_device)
        rmats_dev = tuple(jax.device_put(rmats[n]) for n in r_device)
        if mats_key is not None:
            nbytes = sum(m.nbytes for m in (*lmats.values(), *rmats.values()))
            _device_cache_put(
                mats_key, (llens_dev, rlens_dev, lmats_dev, rmats_dev), nbytes
            )

    run = _expand_gather_program(n_pad)
    louts, routs, b_idx, i_idx, j_idx, valid = run(
        lo_dev,
        hi_dev,
        llens_dev,
        rlens_dev,
        lmats_dev,
        rmats_dev,
        np.int64(total),
    )

    for name, arr in zip(l_device, louts):
        v = np.asarray(arr)[:total]
        dt = dtypes[name]
        out[name] = v.view(dt) if dt.kind in ("M", "m") else v.astype(dt, copy=False)
    for name, arr in zip(r_device, routs):
        v = np.asarray(arr)[:total]
        dt = dtypes[name]
        out[name] = v.view(dt) if dt.kind in ("M", "m") else v.astype(dt, copy=False)

    if host_cols:
        # string/object columns: download the (bucket-ordered) index arrays
        # once and gather host-side, bucket by bucket
        b_np = np.asarray(b_idx)[:total]
        i_np = np.asarray(i_idx)[:total]
        j_np = np.asarray(j_idx)[:total]
        offsets = np.concatenate([[0], np.cumsum(bucket_totals)])
        for name in host_cols:
            is_left, col = sources[name]
            src = lbuckets if is_left else rbuckets
            idx = i_np if is_left else j_np
            res = np.empty(total, dtype=object)
            for b in participating:
                s, e = int(offsets[b]), int(offsets[b + 1])
                if e > s:
                    res[s:e] = src[b][col][idx[s:e]]
            out[name] = res
    return {name: out[name] for name in out_cols}


def _make_host_span_of(session, plan: L.Join, setup, compat):
    """Build ``span_of(b) -> (lo, hi)`` over the pre-sorted per-bucket runs
    using the shared per-bucket key encodings."""
    lkeys_by_bucket, rkeys_by_bucket = _encoded_join_keys(plan, setup, compat)

    from hyperspace_tpu import native

    def span_of(b: int):
        lk = lkeys_by_bucket[b]
        rk = rkeys_by_bucket[b]
        try:
            # single O(n+m) merge walk in C over the pre-sorted runs
            spans = native.merge_spans(lk, rk)
            trace.record("spans", "native")
            return spans
        except native.NativeUnsupported:
            trace.record("spans", "searchsorted")
            return np.searchsorted(rk, lk, side="left"), np.searchsorted(rk, lk, side="right")

    return span_of


def host_bucketed_join(session, plan: L.Join, _compat=None, _setup=None) -> B.Batch:
    """The shuffle-free bucketed SMJ with spans computed host-side. Used
    below the device-dispatch row threshold and for every key shape the
    device program doesn't cover."""
    ensure_x64()
    setup = _setup if _setup is not None else _bucketed_join_setup(session, plan, _compat)
    lbuckets, rbuckets, lkeys, rkeys, nb, lcols_needed, rcols_needed = setup
    span_of = _make_host_span_of(session, plan, setup, _compat)
    return _expand_join_pairs(plan, lbuckets, rbuckets, nb, lcols_needed, rcols_needed, span_of)


def _agg_side_of(lcols, rcols, col_name: str):
    """Which join side an aggregate input column comes from (and its source
    name there); '#r'-suffixed duplicates resolve to the right side."""
    if col_name.endswith("#r") and col_name[:-2] in rcols:
        return "right", col_name[:-2]
    if col_name in lcols:
        return "left", col_name
    if col_name in rcols:
        return "right", col_name
    raise DeviceUnsupported(f"aggregate input {col_name!r} not on either join side")


def _agg_column_stats(arr: np.ndarray):
    """(values as int64/float64, non-null mask or None, is_int) for a fused
    aggregate input; rejects dtypes the exact paths can't represent."""
    if arr.dtype.kind == "u" and arr.dtype.itemsize == 8:
        # uint64 >= 2^63 would wrap negative under int64 — materialize
        raise DeviceUnsupported("uint64 aggregate input -> materialize")
    if arr.dtype.kind in ("i", "u", "b"):
        return arr.astype(np.int64, copy=False), None, True
    if arr.dtype.kind == "f":
        return arr, ~np.isnan(arr), False
    raise DeviceUnsupported(f"non-numeric aggregate input dtype {arr.dtype}")


def _int_magnitude(vals: np.ndarray) -> int:
    """Largest |value| as a Python int. np.abs(int64.min) wraps negative, so
    take abs() after widening to Python ints, keeping the overflow guards
    sound for columns containing int64.min."""
    return max(abs(int(vals.max())), abs(int(vals.min())))


def _check_agg_input_dtypes(lside, rside, need_l, need_r) -> None:
    """Footer-only eligibility check for fused-aggregate inputs: numeric or
    boolean parquet types only (and not uint64). Sides without an index leaf
    carrying the column are checked later, at decode."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    for side, cols in ((lside, need_l), (rside, need_r)):
        scans = L.collect(side, lambda x: isinstance(x, L.IndexScan))
        scan = scans[0] if scans else None
        if scan is None or not scan.files:
            continue
        try:
            schema = pq.read_schema(scan.files[0])
        except OSError:
            continue
        for c in cols:
            if c not in scan.columns or scan.file_column_of(c) not in schema.names:
                continue
            t = schema.field(scan.file_column_of(c)).type
            if pa.types.is_uint64(t) or not (
                pa.types.is_integer(t) or pa.types.is_floating(t) or pa.types.is_boolean(t)
            ):
                raise DeviceUnsupported(f"aggregate input {c!r} type {t} -> materialize")


def _computed_map(computes, lcols, rcols):
    """name -> (side, expr) for Compute nodes between Aggregate and Join:
    an expression whose references live wholly on one side evaluates per
    bucket on that side's decoded batch (anything cross-side
    materializes)."""
    out = {}
    for name, expr in computes or ():
        refs = set(expr.references())
        if not refs:
            out[name] = ("left", expr)  # constant: broadcasts on either side
        elif refs <= lcols:
            out[name] = ("left", expr)
        elif refs <= rcols:
            out[name] = ("right", expr)
        else:
            raise DeviceUnsupported(
                f"computed aggregate input {name!r} references both sides -> materialize"
            )
    return out


def aggregate_over_bucketed_join(
    session, agg: L.Aggregate, join: L.Join, computes=()
) -> B.Batch:
    """Global aggregates over a compatible bucketed inner join WITHOUT
    materializing the pair expansion: per bucket, the [lo, hi) match spans
    give each left row's multiplicity, so sums become weighted sums and
    right-side sums become prefix-sum differences — O(n+m) per bucket instead
    of O(pairs). Integer sums stay exact (per-bucket int64 dot products with
    overflow guards, accumulated in Python ints). GROUP BY over exactly the
    join keys fuses too (segment reductions over the sorted runs). Raises
    DeviceUnsupported for shapes it can't fuse (other group keys, outer
    joins, min/max of right-side columns, non-numeric inputs, overflow-risk
    int sums); the caller then materializes.

    This is TPU-framework-specific: the reference delegates aggregation to
    Spark above its rewritten scans."""
    ensure_x64()
    if join.how != "inner":
        raise DeviceUnsupported("fused join-aggregate covers inner joins")
    compat = join_sides_compatible(join)
    if compat is None:
        raise DeviceUnsupported("join sides are not compatible bucketed scans")
    lside, rside, lkeys, rkeys = compat
    if agg.keys:
        return _grouped_aggregate_over_join(session, agg, join, compat, computes=computes)

    # which side does each aggregate input column come from?
    lcols = set(lside.output_columns)
    rcols = set(rside.output_columns)
    computed = _computed_map(computes, lcols, rcols)

    plans = []  # (name, fn, side, src, expr|None)
    need_l, need_r = set(), set()
    for name, fn, col_name in agg.aggs:
        if fn not in _AGG_FNS:
            raise DeviceUnsupported(f"unsupported aggregate fn {fn!r} -> materialize")
        if fn == "count" and col_name is None:
            plans.append((name, "count*", None, None, None))
            continue
        if col_name in computed:
            side, expr = computed[col_name]
            src = col_name
            refs = set(expr.references())
        else:
            side, src = _agg_side_of(lcols, rcols, col_name)
            expr, refs = None, {src}
        if fn in ("min", "max") and side == "right":
            # would need segment min over covered spans; not worth it here
            raise DeviceUnsupported("min/max of a right-side column -> materialize")
        plans.append((name, fn, side, src, expr))
        (need_l if side == "left" else need_r).update(refs)

    # cheap footer-level dtype check BEFORE any decode: a string/binary
    # aggregate input must not cost a full read of both sides only to fall
    # back (the overflow guards still bail late — they need values)
    _check_agg_input_dtypes(lside, rside, need_l, need_r)

    # decode only keys + needed inputs
    setup = _bucketed_join_setup(
        session, join, compat, needed_override=(sorted(need_l), sorted(need_r))
    )
    lbuckets, rbuckets, _lk, _rk, nb, _lc, _rc = setup
    span_of = _make_host_span_of(session, join, setup, compat)

    INT_GUARD = 2 ** 62

    def declared_is_int(side: str, src: str, expr=None) -> bool:
        # dtype from ANY decoded bucket, so the output dtype is right even
        # when no bucket has matches (empty-join sum must stay float for
        # float inputs, matching the materialized path)
        for batch in (lbuckets if side == "left" else rbuckets).values():
            if expr is not None:
                _v, _ok, is_int = _agg_column_stats(np.asarray(expr.eval(batch)))
                return is_int
            if src in batch:
                _v, _ok, is_int = _agg_column_stats(batch[src])
                return is_int
        raise DeviceUnsupported(f"aggregate input {src!r} has no decoded bucket")

    total_pairs = 0
    acc = {name: {"sum": 0, "cnt": 0, "min": None, "max": None} for name, *_ in plans}
    is_int_out = {
        name: (declared_is_int(side, src, expr) if side is not None else True)
        for name, fn, side, src, expr in plans
    }
    for b in range(nb):
        lb, rb = lbuckets.get(b), rbuckets.get(b)
        if lb is None or rb is None:
            continue
        ll, rr = B.num_rows(lb), B.num_rows(rb)
        if ll == 0 or rr == 0:
            continue
        lo, hi = span_of(b)
        lo_i = np.asarray(lo, dtype=np.int64)
        hi_i = np.asarray(hi, dtype=np.int64)
        counts = hi_i - lo_i
        bucket_pairs = int(counts.sum())
        total_pairs += bucket_pairs
        if bucket_pairs == 0:
            continue

        # per-(side, column) encodings + prefix sums, shared by every
        # aggregate reading that column in this bucket
        col_cache: Dict[Tuple[str, str], tuple] = {}

        def col_info(side: str, src: str, expr=None):
            got = col_cache.get((side, src))
            if got is not None:
                return got
            batch_ = lb if side == "left" else rb
            if expr is not None:
                arr = np.asarray(expr.eval(batch_))
                if arr.ndim == 0:  # constant expression broadcasts per row
                    arr = np.broadcast_to(arr, (B.num_rows(batch_),))
            else:
                arr = batch_[src]
            vals, ok, is_int = _agg_column_stats(arr)
            pref = prefn = None
            if side == "right":
                if is_int:
                    if vals.size and _int_magnitude(vals) * vals.size >= INT_GUARD:
                        raise DeviceUnsupported("int sum overflow risk -> materialize")
                    pref = np.concatenate([[0], np.cumsum(vals)])
                else:
                    pref = np.concatenate([[0.0], np.cumsum(np.where(ok, vals, 0.0))])
                nn = np.ones(vals.shape[0], dtype=np.int64) if ok is None else ok.astype(np.int64)
                prefn = np.concatenate([[0], np.cumsum(nn)])
            got = (vals, ok, is_int, pref, prefn)
            col_cache[(side, src)] = got
            return got

        for name, fn, side, src, expr in plans:
            a = acc[name]
            if fn == "count*":
                continue
            vals, ok, is_int, pref, prefn = col_info(side, src, expr)
            if side == "left":
                w = counts if ok is None else counts * ok
                if fn in ("sum", "avg"):
                    if is_int:
                        if vals.size and _int_magnitude(vals) * bucket_pairs >= INT_GUARD:
                            raise DeviceUnsupported("int sum overflow risk -> materialize")
                        a["sum"] += int(np.dot(vals, counts))
                    else:
                        a["sum"] += float(np.dot(np.where(ok, vals, 0.0), counts))
                    a["cnt"] += int(w.sum())
                elif fn == "count":
                    a["cnt"] += int(w.sum())
                else:  # min/max over rows that matched at least once
                    sel = (counts > 0) if ok is None else (ok & (counts > 0))
                    if sel.any():
                        mn, mx = vals[sel].min(), vals[sel].max()
                        a["min"] = mn if a["min"] is None else min(a["min"], mn)
                        a["max"] = mx if a["max"] is None else max(a["max"], mx)
            else:
                if fn in ("sum", "avg"):
                    span_sum = (pref[hi_i] - pref[lo_i]).sum()
                    a["sum"] += int(span_sum) if is_int else float(span_sum)
                    a["cnt"] += int((prefn[hi_i] - prefn[lo_i]).sum())
                elif fn == "count":
                    a["cnt"] += int((prefn[hi_i] - prefn[lo_i]).sum())

    out: B.Batch = {}
    for name, fn, side, src, expr in plans:
        a = acc[name]
        if fn == "count*":
            out[name] = np.asarray([total_pairs])
        elif fn == "count":
            out[name] = np.asarray([a["cnt"]])
        elif fn == "sum" and a["cnt"] == 0:
            # SQL: SUM over zero (non-null) rows is NULL, not 0
            out[name] = np.asarray([np.nan])
        elif fn == "sum":
            # int inputs stay int (exact)
            if is_int_out[name] and abs(a["sum"]) >= 2 ** 63:
                # exact Python-int total exceeds int64 across buckets: the
                # materialized path defines the (wrapping/float) behavior
                raise DeviceUnsupported("int sum exceeds int64 -> materialize")
            out[name] = np.asarray([a["sum"]], dtype=np.int64 if is_int_out[name] else np.float64)
        elif fn == "avg":
            out[name] = np.asarray([a["sum"] / a["cnt"] if a["cnt"] else np.nan])
        elif fn == "min":
            v = a["min"]
            out[name] = np.asarray([np.nan if v is None else v])
        else:
            v = a["max"]
            out[name] = np.asarray([np.nan if v is None else v])
    return out


def _grouped_aggregate_over_join(
    session, agg: L.Aggregate, join: L.Join, compat, computes=()
) -> B.Batch:
    """Grouped aggregates over a compatible bucketed inner join WITHOUT
    materializing the pair expansion.

    Groups are discovered as SUB-SEGMENTS of each bucket's sorted left run:
    boundaries fall wherever any join key, any left-side group key, or any
    (per-left-row gathered) right-side group key changes. Per-segment pair
    totals are reduceat sums of span counts; sums reduce count-weighted
    left values or span prefix-sum differences (right). Because equal group
    tuples can recur non-contiguously (group keys need not include every
    join key, and extra keys are unsorted within runs), per-segment
    partials FINAL-MERGE through one output-sized pandas groupby — the
    partial/final split, applied to segments instead of chunks.

    Right-side group keys additionally require the right side to be UNIQUE
    per join key in every bucket (spans of width <= 1, checked per bucket):
    that is what makes the gathered per-left-row value well defined. This
    covers the TPC-H q3 class — GROUP BY l_orderkey, o_orderdate,
    o_shippriority over lineitem JOIN orders (o_orderkey is unique) with a
    computed revenue input — end to end without pair expansion.

    Raises DeviceUnsupported for shapes it can't fuse (outer joins,
    min/max, cross-side computed inputs, non-unique right side under
    right-side group keys); the caller then materializes."""
    lside, rside, lkeys, rkeys = compat
    lcols = set(lside.output_columns)
    rcols = set(rside.output_columns)
    computed = _computed_map(computes, lcols, rcols)

    def resolve(col):
        if col in computed:
            side, expr = computed[col]
            return side, col, expr, set(expr.references())
        side, src = _agg_side_of(lcols, rcols, col)
        return side, src, None, {src}

    for _, fn, _c in agg.aggs:
        if fn not in _AGG_FNS:
            raise DeviceUnsupported(f"unsupported aggregate fn {fn!r} -> materialize")

    # group-key plan: join keys canonicalize to the LEFT key column
    # (matched rows carry equal values); anything else is an "extra"
    key_plan = []  # (out_name, kind, src, expr) kind in jk/lx/rx
    need_l, need_r = set(lkeys), set(rkeys)
    has_right_extra = False
    for k in agg.keys:
        side, src, expr, refs = resolve(k)
        if expr is None and side == "left" and src in lkeys:
            key_plan.append((k, "jk", src, None))
        elif expr is None and side == "right" and src in rkeys:
            key_plan.append((k, "jk", lkeys[rkeys.index(src)], None))
        elif side == "left":
            key_plan.append((k, "lx", src, expr))
            need_l |= refs
        else:
            key_plan.append((k, "rx", src, expr))
            need_r |= refs
            has_right_extra = True

    plans = []  # (name, fn, side, src, expr)
    for name, fn, col_name in agg.aggs:
        if fn == "count" and col_name is None:
            plans.append((name, "count*", None, None, None))
            continue
        side, src, expr, refs = resolve(col_name)
        if fn in ("min", "max"):
            raise DeviceUnsupported("grouped min/max -> materialize")
        plans.append((name, fn, side, src, expr))
        (need_l if side == "left" else need_r).update(refs)

    # footer pre-check covers computed inputs via their REFERENCES, so a
    # string-referencing expression bails before decoding both whole sides
    check_l = {s for _, fn, sd, s, e in plans if sd == "left" and e is None}
    check_r = {s for _, fn, sd, s, e in plans if sd == "right" and e is None}
    for _, fn, sd, _s, e in plans:
        if e is not None:
            (check_l if sd == "left" else check_r).update(e.references())
    _check_agg_input_dtypes(lside, rside, check_l, check_r)
    setup = _bucketed_join_setup(
        session, join, compat, needed_override=(sorted(need_l), sorted(need_r))
    )
    lbuckets, rbuckets, _lk, _rk, nb, _lc, _rc = setup
    span_of = _make_host_span_of(session, join, setup, compat)

    INT_GUARD = 2 ** 62

    key_parts: Dict[str, List[np.ndarray]] = {k: [] for k, *_ in key_plan}
    # per-aggregate partial columns: sum+cnt for sum/avg, cnt for counts
    sum_parts: Dict[str, List[np.ndarray]] = {name: [] for name, *_ in plans}
    cnt_parts: Dict[str, List[np.ndarray]] = {name: [] for name, *_ in plans}
    int_sum = {name: True for name, *_ in plans}

    for b in range(nb):
        lb, rb = lbuckets.get(b), rbuckets.get(b)
        if lb is None or rb is None:
            continue
        ll, rr = B.num_rows(lb), B.num_rows(rb)
        if ll == 0 or rr == 0:
            continue
        lo, hi = span_of(b)
        lo_i = np.asarray(lo, dtype=np.int64)
        hi_i = np.asarray(hi, dtype=np.int64)
        counts = hi_i - lo_i
        if has_right_extra and counts.size and int(counts.max()) > 1:
            raise DeviceUnsupported(
                "right-side group key over a non-unique join side -> materialize"
            )

        def left_col(src, expr):
            if expr is not None:
                arr = np.asarray(expr.eval(lb))
                return (
                    np.broadcast_to(arr, (ll,)) if arr.ndim == 0 else arr
                )
            return lb[src]

        def right_gathered(src, expr):
            arr = np.asarray(expr.eval(rb)) if expr is not None else rb[src]
            if arr.ndim == 0:
                arr = np.broadcast_to(arr, (rr,))
            # valid where counts == 1; count-0 rows carry a neighbor's
            # value, which either forms an empty segment (dropped) or
            # harmlessly extends an equal-valued one
            return arr[np.clip(lo_i, 0, rr - 1)]

        # sub-segment boundaries: change in ANY join key or group extra
        key_arrays = {}  # out_name -> per-left-row values for output
        change = np.zeros(ll, dtype=bool)
        if ll:
            change[0] = True
        for kc in lkeys:
            kv = _order_key_array(lb[kc])
            change[1:] |= kv[1:] != kv[:-1]
        for k, kind, src, expr in key_plan:
            if kind == "jk":
                key_arrays[k] = lb[src]
                continue
            arr = left_col(src, expr) if kind == "lx" else right_gathered(src, expr)
            key_arrays[k] = arr
            kv = _order_key_array(arr)
            change[1:] |= kv[1:] != kv[:-1]
        starts = np.flatnonzero(change)
        run_pairs = np.add.reduceat(counts, starts) if starts.size else np.empty(0, np.int64)
        keep = run_pairs > 0  # inner join: unmatched segments drop out
        if not keep.any():
            continue

        for k, kind, src, expr in key_plan:
            key_parts[k].append(key_arrays[k][starts][keep])

        col_cache: Dict[Tuple[str, str], tuple] = {}

        def col_info(side, src, expr):
            got = col_cache.get((side, src))
            if got is not None:
                return got
            if side == "left":
                arr = left_col(src, expr)
            else:
                arr = np.asarray(expr.eval(rb)) if expr is not None else rb[src]
                if arr.ndim == 0:
                    arr = np.broadcast_to(arr, (rr,))
            vals, ok, is_int = _agg_column_stats(arr)
            if is_int and vals.size and _int_magnitude(vals) * max(int(counts.sum()), 1) >= INT_GUARD:
                raise DeviceUnsupported("int sum overflow risk -> materialize")
            pref = prefn = None
            if side == "right":
                if ok is None:
                    pref = np.concatenate([[0], np.cumsum(vals)])
                    nn = np.ones(vals.shape[0], dtype=np.int64)
                else:
                    pref = np.concatenate([[0.0], np.cumsum(np.where(ok, vals, 0.0))])
                    nn = ok.astype(np.int64)
                prefn = np.concatenate([[0], np.cumsum(nn)])
            got = (vals, ok, is_int, pref, prefn)
            col_cache[(side, src)] = got
            return got

        for name, fn, side, src, expr in plans:
            if fn == "count*":
                cnt_parts[name].append(run_pairs[keep])
                continue
            vals, ok, is_int, pref, prefn = col_info(side, src, expr)
            if not is_int:
                int_sum[name] = False
            if side == "left":
                w = counts if ok is None else counts * ok
                cnts = np.add.reduceat(w, starts)[keep]
                cnt_parts[name].append(cnts)
                if fn in ("sum", "avg"):
                    contrib = vals * counts if ok is None else np.where(ok, vals, 0) * counts
                    sum_parts[name].append(np.add.reduceat(contrib, starts)[keep])
            else:
                row_cnts = prefn[hi_i] - prefn[lo_i]
                cnt_parts[name].append(np.add.reduceat(row_cnts, starts)[keep])
                if fn in ("sum", "avg"):
                    row_sums = pref[hi_i] - pref[lo_i]
                    sum_parts[name].append(np.add.reduceat(row_sums, starts)[keep])

    def declared_dtype(side, src) -> np.dtype:
        for batch in (lbuckets if side == "left" else rbuckets).values():
            if src in batch:
                return batch[src].dtype
        raise DeviceUnsupported(f"aggregate input {src!r} has no decoded bucket")

    def declared_expr_dtype(side, expr) -> np.dtype:
        # a computed column's dtype comes from evaluating it over any
        # decoded bucket (empty-result outputs must still type like the
        # materialized path's)
        for batch in (lbuckets if side == "left" else rbuckets).values():
            arr = np.asarray(expr.eval(batch))
            return arr.dtype
        return np.dtype(np.float64)

    out: B.Batch = {}
    any_parts = any(key_parts[k] for k, *_ in key_plan) if key_plan else False
    if not any_parts:
        for k, kind, src, expr in key_plan:
            if expr is not None:
                out[k] = np.empty(
                    0, dtype=declared_expr_dtype("left" if kind != "rx" else "right", expr)
                )
            else:
                out[k] = np.empty(
                    0, dtype=declared_dtype("left" if kind != "rx" else "right", src)
                )
        for name, fn, side, src, expr in plans:
            if fn in ("count", "count*"):
                dt = np.dtype(np.int64)
            elif fn == "sum" and side is not None:
                _v, _ok, is_int = _agg_column_stats(
                    np.empty(0, dtype=declared_dtype(side, src))
                    if expr is None
                    else np.empty(0, dtype=declared_expr_dtype(side, expr))
                )
                dt = np.dtype(np.int64) if is_int else np.dtype(np.float64)
            else:
                dt = np.dtype(np.float64)
            out[name] = np.empty(0, dtype=dt)
        return out

    # FINAL MERGE: equal group tuples recur across segments (and, when the
    # group keys don't pin the join key, across buckets) — one
    # segment-count-sized pandas groupby folds the partials. Keys enter as
    # null-safe int64 ORDER CODES, never as raw values: strings would pay
    # pandas' Arrow conversion (the round-4 lesson) and datetimes would
    # round-trip to ns; a representative row index maps each group back to
    # its exact original values/dtypes.
    import pandas as pd

    key_arrays_out = {k: np.concatenate(key_parts[k]) for k, *_ in key_plan}
    frame = {
        f"__k{i}": _order_key_array(key_arrays_out[k])
        for i, (k, *_rest) in enumerate(key_plan)
    }
    gcols = list(frame)
    n_seg = len(next(iter(key_arrays_out.values()))) if key_arrays_out else 0
    frame["__pos"] = np.arange(n_seg, dtype=np.int64)
    for name, fn, side, src, expr in plans:
        frame[f"__c_{name}"] = np.concatenate(cnt_parts[name])
        if sum_parts[name]:
            s_part = np.concatenate(sum_parts[name])
            if int_sum[name] and s_part.dtype.kind != "f":
                # pandas sums int64 with wrapping arithmetic; cross-bucket
                # merges could exceed int64 even when every per-bucket
                # partial passed its own guard
                if float(np.abs(s_part.astype(np.float64)).sum()) >= float(INT_GUARD):
                    raise DeviceUnsupported("int sum overflow risk at merge -> materialize")
            frame[f"__s_{name}"] = s_part
    df = pd.DataFrame(frame)
    gb = df.groupby(gcols, dropna=False, sort=False)
    agg_spec = {c: "sum" for c in df.columns if c not in gcols and c != "__pos"}
    agg_spec["__pos"] = "first"
    res = gb.agg(agg_spec).reset_index()

    rep = res["__pos"].to_numpy()
    for k, *_rest in key_plan:
        out[k] = key_arrays_out[k][rep]
    for name, fn, side, src, expr in plans:
        c = res[f"__c_{name}"].to_numpy()
        if fn in ("count", "count*"):
            out[name] = c.astype(np.int64)
            continue
        s = res[f"__s_{name}"].to_numpy()
        if fn == "avg":
            out[name] = np.divide(
                s.astype(np.float64), c, out=np.full(s.shape, np.nan), where=c > 0
            )
            continue
        # sum: SQL NULL (NaN) for all-null groups; int sums stay int when
        # no group needs a NULL hole
        if (c > 0).all():
            out[name] = s.astype(np.int64) if int_sum[name] and s.dtype.kind != "f" else s
        else:
            sf = s.astype(np.float64)
            sf[c == 0] = np.nan
            out[name] = sf
    return out
