"""Bounded prefetching pipeline: the scan engine's decode/transfer/compute overlap.

Stage model (the classic accelerator input pipeline):

  1. **host decode** — chunk k+1's parquet -> numpy materialization runs on
     the pipeline pool. Native-dialect chunks take the row-group fast path
     (exec/io.py ``_native_rg_scan``): every surviving (file × row group ×
     column) chunk decodes in parallel on the shared decode pool, each C call
     writing its slot of ONE √2-bucket-padded buffer per column — assembly is
     concat-free. Everything else fans per-file work onto the decode pool as
     before;
  2. **H2D staging** — an optional ``stage`` hook runs right after decode on
     the same worker, typically ``device.stage_filter_columns``: encode, pad
     to a shape bucket, and ``jax.device_put`` the chunk's filter columns so
     the device cache is warm before the consumer asks. For fast-path chunks
     the pad step adopts the decoder's own padded buffer (pointer-identical —
     zero extra host copies), and dict-backed string columns ship int32 codes
     + dictionary, expanding on device via the fused ``dict-expand`` program.
     When the mesh-sharded path is on (``hyperspace.parallel.enabled``) the
     hook places columns with the executor mesh's ``NamedSharding`` and
     brands the cache entries with its fingerprint, so the consumer's
     shard_map programs hit the same staged columns;
  3. **device compute** — the consumer thread executes chunk k's jitted
     program while stages 1–2 of chunk k+1 proceed concurrently.

Backpressure is double-ended: at most ``depth`` chunks are prefetched ahead
of the consumer, and completed-but-unconsumed results are byte-capped by
``max_buffered_bytes`` (the chunk immediately ahead is always allowed, so a
single oversized chunk can stall but never deadlock the stream).

Why a dedicated pool: prefetch tasks BLOCK on ``_decode_pool().map(...)``;
running them on the decode pool itself would deadlock once decodeThreads <=
pipeline depth (every decode thread parked waiting for a decode thread).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from time import monotonic
from typing import Callable, Dict, List, Optional, Sequence

from hyperspace_tpu.obs import spans
from hyperspace_tpu.reliability.faults import FAULTS

_PIPELINE_POOL = None
_PIPELINE_POOL_LOCK = threading.Lock()


def _pipeline_pool():
    """Shared prefetch pool. Width 4 bounds concurrent chunk materializations
    process-wide (each one multiplies out onto the decode pool); streams
    beyond that queue, which is the correct degradation under serving load."""
    global _PIPELINE_POOL
    if _PIPELINE_POOL is None:
        with _PIPELINE_POOL_LOCK:
            if _PIPELINE_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _PIPELINE_POOL = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="hs-pipeline"
                )
    return _PIPELINE_POOL


def _counters():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return (
        REGISTRY.counter(
            "hs_pipeline_chunks_total",
            "Chunks yielded by the pipelined scan engine",
        ),
        REGISTRY.counter(
            "hs_pipeline_wait_seconds_total",
            "Seconds stream consumers stalled waiting on a prefetched chunk",
        ),
    )


def _cancelled_counter():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_pipeline_cancelled_total",
        "Queued prefetch decodes cancelled by an early stream close (LIMIT "
        "reached, consumer abandoned the stream)",
    )


class ScanPipeline:
    """Ordered bounded prefetch over a list of chunk-producing thunks.

    ``tasks`` are zero-arg callables, one per chunk, run on the pipeline pool
    under the constructing thread's span context (prefetch decode spans land
    in the stream's trace tree, on the worker's own track — that is the
    overlap a Chrome trace export shows). Iteration yields task results in
    list order. ``stage(i, result)`` runs on the producer thread immediately
    after task i. ``weigh(result)`` -> bytes feeds the buffer budget.

    Cancel-safe: ``close()`` (called by ``__exit__``, by generator close via
    the consumer's ``finally``, and at normal exhaustion) cancels queued
    tasks and WAITS for in-flight ones, so no worker touches executor state
    after the stream is gone.
    """

    def __init__(
        self,
        tasks: Sequence[Callable[[], object]],
        *,
        depth: int = 1,
        max_buffered_bytes: Optional[int] = None,
        weigh: Optional[Callable[[object], int]] = None,
        stage: Optional[Callable[[int, object], None]] = None,
    ):
        self._tasks = list(tasks)
        self._depth = max(1, int(depth))
        self._budget = max_buffered_bytes
        self._weigh = weigh
        self._stage = stage
        self._futures: List[Optional[Future]] = [None] * len(self._tasks)
        self._sizes: Dict[int, int] = {}
        self._buffered = 0  # bytes of completed-but-unconsumed results
        self._lock = threading.Lock()
        self._closed = False

    # -- producer side -----------------------------------------------------

    def _run(self, i: int):
        with spans.span("prefetch", cat="pipeline", chunk=i):
            if FAULTS.active:
                FAULTS.check("pipeline.task")
            out = self._tasks[i]()
            if self._stage is not None:
                self._stage(i, out)
            return out

    def _submit(self, i: int) -> None:
        fut = _pipeline_pool().submit(spans.wrap(self._run), i)
        if self._weigh is not None:
            def _done(f: Future, i: int = i) -> None:
                if f.cancelled() or f.exception() is not None:
                    return
                try:
                    w = int(self._weigh(f.result()))
                except Exception:
                    w = 0
                with self._lock:
                    self._sizes[i] = w
                    self._buffered += w

            fut.add_done_callback(_done)
        self._futures[i] = fut

    def _pump(self, k: int) -> None:
        """Submit up through chunk k + depth: chunk k and k+1 unconditionally
        (the double buffer), further lookahead only while under the byte cap."""
        if self._closed:
            return
        for i in range(len(self._tasks)):
            if self._futures[i] is not None:
                continue
            if i > k + self._depth:
                break
            if i > k + 1 and self._budget is not None:
                with self._lock:
                    over = self._buffered >= self._budget
                if over:
                    break
            self._submit(i)

    # -- consumer side -----------------------------------------------------

    def __iter__(self):
        chunks_c, wait_c = _counters()
        try:
            for k in range(len(self._tasks)):
                self._pump(k)
                t0 = monotonic()
                out = self._futures[k].result()
                wait_c.inc(monotonic() - t0)
                chunks_c.inc()
                with self._lock:
                    self._buffered -= self._sizes.pop(k, 0)
                self._pump(k)  # consumed budget frees the next lookahead slot
                yield out
        finally:
            self.close()

    def close(self) -> None:
        """Cancel queued prefetches and drain in-flight ones. Idempotent."""
        self._closed = True
        inflight = []
        cancelled = 0
        for f in self._futures:
            if f is not None and not f.done():
                if f.cancel():
                    cancelled += 1
                else:
                    inflight.append(f)
        if cancelled:
            _cancelled_counter().inc(cancelled)
        for f in inflight:
            try:
                f.result()
            except Exception:
                pass  # the consumer already saw (or abandoned) this error

    def __enter__(self) -> "ScanPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
