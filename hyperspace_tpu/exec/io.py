"""Scan-side IO: parquet files -> columnar batches.

Index files (written uncompressed PLAIN/dictionary by the bucketed writer —
indexes/covering.py) decode through the native C++ path
(hyperspace_tpu.native): mmap -> column-chunk decode straight into numpy
buffers, no JVM and no pyarrow table materialization in the hot loop
(SURVEY.md §7 design stance (c)). Files outside the native dialect
(compressed, nested, unsupported encodings) fall back to pyarrow per file.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs import spans
from hyperspace_tpu.reliability import errors as rerr
from hyperspace_tpu.reliability.degrade import QUARANTINE
from hyperspace_tpu.reliability.faults import FAULTS
from hyperspace_tpu.reliability.retry import with_retry

# ---------------------------------------------------------------------------
# Per-file decoded-batch cache (the framework's buffer pool). Spark gets this
# from the OS page cache + executor columnar caching; here repeated scans of
# the same immutable index/bucket files skip decode entirely. Entries key on
# (path, mtime_ns, size, columns) so any rewrite invalidates naturally.
# ---------------------------------------------------------------------------

from hyperspace_tpu.utils.lru import BytesLRU

_io_cache = BytesLRU(int(os.environ.get("HS_IO_CACHE_BYTES", 1 << 31)))


def _batch_nbytes(batch: B.Batch) -> int:
    total = 0
    for a in batch.values():
        if a.dtype == object and len(a):
            # strings: numpy reports pointer size only; estimate payload by
            # scaling a bounded sample to the full length
            k = min(len(a), 64)
            sample = sum(len(str(v)) for v in a[:k])
            total += int(a.nbytes) + int(sample * len(a) / k)
        else:
            total += int(a.nbytes)
    return total


def _io_cache_key(path: str, columns: Optional[List[str]]):
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (path, st.st_mtime_ns, st.st_size, tuple(columns) if columns is not None else None)


def _io_cache_get(key) -> Optional[B.Batch]:
    if key is None:
        return None
    got = _io_cache.get(key)
    if got is not None:
        return dict(got)  # callers may add/remove dict keys
    return None


def _io_cache_put(key, batch: B.Batch) -> None:
    if key is None:
        return
    # cached buffers are shared with every future reader of this file —
    # freeze them so an in-place mutation of a collected result raises
    # instead of silently corrupting the cache (collect() results can be
    # read-only views; copy before mutating)
    for a in batch.values():
        a.setflags(write=False)
    _io_cache.put(key, dict(batch), _batch_nbytes(batch))


def clear_io_cache() -> None:
    _io_cache.clear()


def _key_mentions_path(key, paths) -> bool:
    # cache keys are nested tuples whose leaves include the source path
    # string: file keys are (path, mtime, size, cols), concat keys wrap a
    # tuple of per-file keys, row-group keys append a suffix tuple — a
    # recursive scan covers every shape without coupling to each layout
    if isinstance(key, str):
        return key in paths
    if isinstance(key, tuple):
        return any(_key_mentions_path(part, paths) for part in key)
    return False


def purge_io_cache(paths) -> int:
    """Drop every cached batch derived from any of ``paths`` (data-version
    commit invalidation); returns the number of entries removed."""
    wanted = set(paths)
    if not wanted:
        return 0
    removed = 0
    for key in _io_cache.keys():
        if _key_mentions_path(key, wanted) and _io_cache.discard(key):
            removed += 1
    return removed


_DECODE_POOL = None
_DECODE_POOL_LOCK = threading.Lock()
_DECODE_POOL_SIZE = None  # width the live pool was created with
_CONFIGURED_THREADS: Optional[int] = None  # from conf, via set_decode_threads


def decode_threads() -> int:
    """Effective decode-pool width: HS_DECODE_THREADS env > session conf
    (``hyperspace.exec.io.decodeThreads``) > default 8."""
    env = os.environ.get("HS_DECODE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, _CONFIGURED_THREADS or 8)


def set_decode_threads(n: Optional[int]) -> None:
    """Record the conf-requested pool width (called on Session construction).
    An already-built pool of a different width is retired — its in-flight
    decodes finish on the old threads — and the next scan builds the new one."""
    global _CONFIGURED_THREADS, _DECODE_POOL, _DECODE_POOL_SIZE
    with _DECODE_POOL_LOCK:
        _CONFIGURED_THREADS = int(n) if n else None
        if _DECODE_POOL is not None and _DECODE_POOL_SIZE != decode_threads():
            _DECODE_POOL.shutdown(wait=False)
            _DECODE_POOL = None
            _DECODE_POOL_SIZE = None


def _decode_pool():
    """Shared decode thread pool — per-call pools would pay thread spin-up on
    every scan. Init is locked: serving workers scan concurrently, and a
    double-create here leaked a whole thread pool."""
    global _DECODE_POOL, _DECODE_POOL_SIZE
    if _DECODE_POOL is None:
        with _DECODE_POOL_LOCK:
            if _DECODE_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _DECODE_POOL_SIZE = decode_threads()
                _DECODE_POOL = ThreadPoolExecutor(
                    max_workers=_DECODE_POOL_SIZE, thread_name_prefix="hs-decode"
                )
    return _DECODE_POOL


def _dtype_hints(schema: pa.Schema, columns: List[str]) -> Optional[Dict[str, np.dtype]]:
    """Numpy dtypes for native INT64-backed logical types (timestamps/dates).

    Returns None when any requested column's arrow type has no faithful
    numpy/native mapping (decimal, nested, ...) — the caller then uses pyarrow.
    """
    hints: Dict[str, np.dtype] = {}
    for c in columns:
        t = schema.field(c).type
        if pa.types.is_timestamp(t):
            hints[c] = np.dtype(f"datetime64[{t.unit}]")
        elif pa.types.is_date32(t):
            # INT32 days since epoch; pyarrow surfaces datetime64[D] — the
            # native wrapper widens int32 -> datetime64[D] by astype
            hints[c] = np.dtype("datetime64[D]")
        elif pa.types.is_date64(t):
            hints[c] = np.dtype("datetime64[ms]")
        elif (
            pa.types.is_time(t)       # time32/time64 surface as datetime.time objects
            or pa.types.is_duration(t)
            or pa.types.is_decimal(t)
            or pa.types.is_nested(t)
            or pa.types.is_dictionary(t)
        ):
            return None
    return hints


# ---------------------------------------------------------------------------
# Row-group pruning: a scan's pushed-down predicate is evaluated against the
# parquet footers' per-row-group min/max statistics BEFORE any decode, through
# the data-skipping rule's three-valued _SketchEvaluator (reused, not
# duplicated): "definitely no matching rows" skips the row group, anything
# uncertain decodes it. The Filter above re-applies the full predicate, so
# pruning is conservative by construction and never changes results.
# ---------------------------------------------------------------------------


def _rg_counters():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return (
        REGISTRY.counter(
            "hs_rowgroups_scanned_total",
            "Parquet row groups decoded by predicate-pushdown scans",
        ),
        REGISTRY.counter(
            "hs_rowgroups_skipped_total",
            "Parquet row groups skipped by min/max statistics pruning",
        ),
        REGISTRY.counter(
            "hs_rowgroup_bytes_skipped_total",
            "Bytes of parquet row groups skipped by min/max statistics pruning",
        ),
    )


def _stats_array(vals: List) -> np.ndarray:
    """Per-row-group min or max values as an array the sketch evaluator's
    comparisons understand. None entries (absent statistics) survive as
    object-array nulls, which the evaluator keeps unconditionally."""
    import datetime

    if not vals or any(v is None for v in vals):
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out
    v0 = vals[0]
    if isinstance(v0, datetime.datetime):
        return np.array(vals, dtype="datetime64[us]")
    if isinstance(v0, datetime.date):
        return np.array(vals, dtype="datetime64[D]")
    if isinstance(v0, bytes):
        vals = [v.decode("utf-8", "surrogateescape") for v in vals]
    out = np.asarray(vals)
    if out.dtype.kind in ("U", "S"):
        out = out.astype(object)
    return out


def prune_row_groups(path: str, predicate) -> Optional[List[int]]:
    """Row-group indices of ``path`` that *might* hold rows matching
    ``predicate``, judged by footer min/max statistics; None when nothing can
    be pruned (every group kept). Columns without statistics — or predicate
    shapes outside the evaluator's language — keep their groups."""
    from hyperspace_tpu.indexes.dataskipping import MinMaxSketch
    from hyperspace_tpu.rules.dataskipping_rule import _SketchEvaluator

    refs = sorted(set(predicate.references()))
    if not refs:
        return None
    try:
        if FAULTS.active:
            FAULTS.check("io.footer", path)
        md = pq.read_metadata(path)
    except (OSError, pa.ArrowInvalid) as exc:
        # pruning is an optimization: the full decode below still answers
        # (and will surface/classify a genuinely bad file) — but the footer
        # failure itself is counted, never silently ignored
        rerr.count_io_error("io.footer", exc, swallowed=True)
        return None
    n_rg = md.num_row_groups
    if n_rg == 0:
        return None
    rg0 = md.row_group(0)
    col_idx = {rg0.column(j).path_in_schema: j for j in range(rg0.num_columns)}
    lower_idx = {name.lower(): j for name, j in col_idx.items()}
    sketches, table = [], {}
    for c in refs:
        j = col_idx.get(c, lower_idx.get(c.lower()))
        if j is None:
            continue  # partition / computed column: no file statistics
        mins: List = []
        maxs: List = []
        for i in range(n_rg):
            st = md.row_group(i).column(j).statistics
            if st is not None and st.has_min_max:
                mins.append(st.min)
                maxs.append(st.max)
            else:
                mins.append(None)
                maxs.append(None)
        s = MinMaxSketch(c)
        mn_name, mx_name = s.output_names()
        table[mn_name] = _stats_array(mins)
        table[mx_name] = _stats_array(maxs)
        sketches.append(s)
    if not sketches:
        return None
    try:
        mask = _SketchEvaluator(sketches, table, n_rg).eval(predicate)
    except Exception:
        return None  # pruning must never break a read the full decode answers
    if mask is None or mask.all():
        return None
    return [int(i) for i in np.nonzero(mask)[0]]


def _read_row_groups(
    f: str, columns: Optional[List[str]], schema: pa.Schema, keep: List[int], dsp
) -> B.Batch:
    """Decode only the surviving row groups of one file (pyarrow path; the
    native decoder reads whole column chunks). Fully-pruned files return a
    typed empty batch from the file schema."""
    scanned_c, skipped_c, bytes_c = _rg_counters()
    md = pq.read_metadata(f)
    n_rg = md.num_row_groups
    kept = set(keep)
    sk_bytes = sum(
        md.row_group(i).total_byte_size for i in range(n_rg) if i not in kept
    )
    scanned_c.inc(len(keep))
    skipped_c.inc(n_rg - len(keep))
    bytes_c.inc(sk_bytes)
    dsp.set(rowgroups_skipped=n_rg - len(keep), rowgroup_bytes_skipped=int(sk_bytes))
    if not keep:
        trace.record("decode", "rowgroup-pruned")
        t = schema.empty_table()
        if columns is not None:
            t = t.select(columns)
        return B.table_to_batch(t)
    ckey = _io_cache_key(f, columns)
    ckey = ckey + (("rg",) + tuple(keep),) if ckey is not None else None
    got = _io_cache_get(ckey)
    if got is not None:
        trace.record("decode", "cached")
        return got
    trace.record("decode", "pyarrow-rowgroups")
    t = pq.ParquetFile(f).read_row_groups(keep, columns=columns)
    got = B.table_to_batch(t)
    dsp.set(rows=B.num_rows(got))
    _io_cache_put(ckey, got)
    return got


def read_parquet_batch(
    files: List[str], columns: Optional[List[str]], predicate=None
) -> B.Batch:
    """Read ``columns`` of ``files`` into one concatenated batch, native-first.

    Schema-evolved datasets (a file missing a requested column, or differing
    per-file schemas when ``columns`` is None) go through a single
    dataset-level pyarrow read, which unifies schemas and null-fills — the
    per-file native path requires every file to carry every column.

    ``predicate`` (a pushed-down filter Expr) enables row-group min/max
    pruning: groups its statistics definitively exclude are never decoded.
    The caller's Filter still applies the predicate, so a cached full-file
    batch (more rows) is always an acceptable answer.
    """
    from hyperspace_tpu import native

    def _dataset_read() -> B.Batch:
        trace.record("decode", "pyarrow-dataset")
        try:
            # unify per-file schemas so evolved columns survive regardless of
            # file order (a bare dataset takes the FIRST fragment's schema)
            unified = pa.unify_schemas([pq.read_schema(f) for f in files])
            ds = pads.dataset(files, format="parquet", schema=unified)
        except (OSError, pa.ArrowInvalid, pa.ArrowTypeError) as exc:
            # schema unification is best-effort (first-fragment schema is a
            # correct fallback for homogeneous files); count the classified
            # failure — a truly bad file still raises out of to_table below
            rerr.count_io_error("io.footer", exc, swallowed=True)
            ds = pads.dataset(files, format="parquet")
        cols = columns
        if columns is not None and any("." in c and c not in ds.schema.names for c in columns):
            # nested struct paths (hybrid scan's appended-file side of a
            # nested index): project leaves into flat columns
            import pyarrow.compute as pc

            from hyperspace_tpu.plan.expr import strip_nested_prefix

            def resolve_path(dotted: str):
                # case-insensitive per segment (the resolver only exact-cases
                # the root; pc.field is case-sensitive)
                parts = dotted.split(".")
                out, fields = [], list(ds.schema)
                for i, p in enumerate(parts):
                    hit = next((f for f in fields if f.name.lower() == p.lower()), None)
                    if hit is None:
                        return parts  # let arrow raise its own error
                    out.append(hit.name)
                    if i < len(parts) - 1:
                        t = hit.type
                        fields = [t.field(j) for j in range(t.num_fields)] if pa.types.is_struct(t) else []
                return out

            cols = {}
            for c in columns:
                if c in ds.schema.names:
                    cols[c] = pc.field(c)
                else:
                    cols[c] = pc.field(*resolve_path(strip_nested_prefix(c)))
        t = ds.to_table(columns=cols)
        return B.table_to_batch(t)

    # a multi-file scan's CONCATENATED batch is itself cacheable (same
    # immutability argument as the per-file entries): re-concatenating 6M
    # rows cost ~0.7 s per execution of TPC-H q1 at sf=1. The entry lives in
    # the same byte-capped LRU; trace events mirror the per-file cached path
    # so dispatch goldens are insensitive to which cache tier answered.
    concat_key = None
    if columns is not None and len(files) > 1:
        per_file = [_io_cache_key(f, columns) for f in files]
        # a None per-file key (stat failed) disables caching everywhere
        # else; embedding it in the tuple would collide unrelated scans
        if all(k is not None for k in per_file):
            concat_key = ("concat", tuple(per_file))
            got = _io_cache_get(concat_key)
            if got is not None:
                for _ in files:
                    trace.record("decode", "cached")
                return got

    # fully-cached scan with an explicit projection: every cached batch holds
    # exactly ``columns``, so concatenation is schema-safe and the pq schema
    # pre-scan can be skipped. With columns=None per-file schemas may differ
    # (cached entries then have heterogeneous keys), so that case still goes
    # through the pre-scan below before trusting the cache.
    cached = [_io_cache_get(_io_cache_key(f, columns)) for f in files]
    if columns is not None and cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        if len(cached) == 1:
            return cached[0]
        out = B.concat(cached)
        if concat_key is not None:
            _io_cache_put(concat_key, out)
        return out

    # pre-scan schemas; any inconsistency -> unified dataset read. A corrupt
    # footer is NOT an inconsistency: falling back would re-read the same bad
    # bytes, so it surfaces typed (and strikes the owning index's breaker)
    try:
        schemas = []
        for f in files:
            if FAULTS.active:
                FAULTS.check("io.footer", f)
            try:
                schemas.append(pq.read_schema(f))
            except (pa.ArrowInvalid, pa.ArrowTypeError) as exc:
                err = rerr.classify(exc, path=f)
                rerr.count_io_error("io.footer", err)
                if QUARANTINE.enabled and isinstance(err, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise err from exc
    except OSError as exc:
        rerr.count_io_error("io.footer", exc, swallowed=True)
        return _dataset_read()
    if columns is None:
        names0 = list(schemas[0].names)
        if any(list(s.names) != names0 for s in schemas[1:]):
            return _dataset_read()
    else:
        for s in schemas:
            if any(c not in s.names for c in columns):
                return _dataset_read()

    def read_one(f: str, schema) -> B.Batch:
        with spans.span("decode", cat="io", file=os.path.basename(f)) as dsp:
            ckey = _io_cache_key(f, columns)
            got = _io_cache_get(ckey)
            if got is not None:
                trace.record("decode", "cached")
                return got
            if predicate is not None:
                keep = prune_row_groups(f, predicate)
                if keep is not None:
                    return _read_row_groups(f, columns, schema, keep, dsp)
            def _decode() -> B.Batch:
                if FAULTS.active:
                    FAULTS.check("io.decode", f)
                try:
                    cols = list(columns) if columns is not None else list(schema.names)
                    hints = _dtype_hints(schema, cols)
                    out = native.read_columns(f, cols, hints) if hints is not None else None
                except (native.NativeUnsupported, OSError, KeyError) as e:
                    # dialect mismatches are the expected fallback path; real
                    # IO failures falling through to the pyarrow re-read are
                    # classified and counted, never silently ignored
                    if not isinstance(e, native.NativeUnsupported):
                        rerr.count_io_error("io.decode", e, swallowed=True)
                    if os.environ.get("HS_DEBUG_DECODE_FALLBACK"):
                        import sys

                        print(f"DECODE-FALLBACK {f}: {type(e).__name__}: {e}", file=sys.stderr)
                    out = None
                if out is None:
                    trace.record("decode", "pyarrow")
                    t = pads.dataset([f], format="parquet").to_table(columns=columns)
                    out = B.table_to_batch(t)
                else:
                    trace.record("decode", "native")
                return out

            try:
                got = with_retry(_decode, op="io.decode")
            except rerr.ReliabilityError as exc:
                rerr.count_io_error("io.decode", exc)
                if QUARANTINE.enabled and isinstance(exc, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise
            except (OSError, pa.ArrowInvalid, pa.ArrowTypeError) as exc:
                err = rerr.classify(exc, path=f)
                rerr.count_io_error("io.decode", err)
                if QUARANTINE.enabled and isinstance(err, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise err from exc
            if QUARANTINE.enabled:
                QUARANTINE.note_ok(f)
            dsp.set(rows=B.num_rows(got))
            _io_cache_put(ckey, got)
            return got

    # decode files concurrently (pyarrow and the native decoder release the
    # GIL); list order — bucket sortedness — is preserved by mapping, not by
    # completion. Fully-cached reads (here: the columns=None case, now known
    # schema-consistent) skip the pool: no decode to parallelize.
    if cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        batches = cached
    elif len(files) > 1:
        # spans.wrap binds the submitting request's current span into the
        # pool workers — contextvars do NOT cross ThreadPoolExecutor on
        # their own, and decode spans must land in the caller's tree
        batches = list(_decode_pool().map(spans.wrap(read_one), files, schemas))
    else:
        batches = [read_one(f, s) for f, s in zip(files, schemas)]
    if not batches:
        return _dataset_read()
    if len(batches) == 1:
        return batches[0]
    out = B.concat(batches)
    # a predicate-pruned concatenation holds FEWER rows than the full scan;
    # caching it under the unpruned concat key would poison predicate-less
    # readers of the same files with silently missing rows
    if concat_key is not None and predicate is None:
        _io_cache_put(concat_key, out)
    return out
