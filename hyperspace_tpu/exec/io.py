"""Scan-side IO: parquet files -> columnar batches.

Index files (written uncompressed PLAIN/dictionary by the bucketed writer —
indexes/covering.py) decode through the native C++ path
(hyperspace_tpu.native): mmap -> column-chunk decode straight into numpy
buffers, no JVM and no pyarrow table materialization in the hot loop
(SURVEY.md §7 design stance (c)). Files outside the native dialect
(compressed, nested, unsupported encodings) fall back to pyarrow per file.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs import spans

# ---------------------------------------------------------------------------
# Per-file decoded-batch cache (the framework's buffer pool). Spark gets this
# from the OS page cache + executor columnar caching; here repeated scans of
# the same immutable index/bucket files skip decode entirely. Entries key on
# (path, mtime_ns, size, columns) so any rewrite invalidates naturally.
# ---------------------------------------------------------------------------

from hyperspace_tpu.utils.lru import BytesLRU

_io_cache = BytesLRU(int(os.environ.get("HS_IO_CACHE_BYTES", 1 << 31)))


def _batch_nbytes(batch: B.Batch) -> int:
    total = 0
    for a in batch.values():
        if a.dtype == object and len(a):
            # strings: numpy reports pointer size only; estimate payload by
            # scaling a bounded sample to the full length
            k = min(len(a), 64)
            sample = sum(len(str(v)) for v in a[:k])
            total += int(a.nbytes) + int(sample * len(a) / k)
        else:
            total += int(a.nbytes)
    return total


def _io_cache_key(path: str, columns: Optional[List[str]]):
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (path, st.st_mtime_ns, st.st_size, tuple(columns) if columns is not None else None)


def _io_cache_get(key) -> Optional[B.Batch]:
    if key is None:
        return None
    got = _io_cache.get(key)
    if got is not None:
        return dict(got)  # callers may add/remove dict keys
    return None


def _io_cache_put(key, batch: B.Batch) -> None:
    if key is None:
        return
    # cached buffers are shared with every future reader of this file —
    # freeze them so an in-place mutation of a collected result raises
    # instead of silently corrupting the cache (collect() results can be
    # read-only views; copy before mutating)
    for a in batch.values():
        a.setflags(write=False)
    _io_cache.put(key, dict(batch), _batch_nbytes(batch))


def clear_io_cache() -> None:
    _io_cache.clear()


_DECODE_POOL = None
_DECODE_POOL_LOCK = threading.Lock()


def _decode_pool():
    """Shared decode thread pool — per-call pools would pay thread spin-up on
    every scan. Init is locked: serving workers scan concurrently, and a
    double-create here leaked a whole thread pool."""
    global _DECODE_POOL
    if _DECODE_POOL is None:
        with _DECODE_POOL_LOCK:
            if _DECODE_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _DECODE_POOL = ThreadPoolExecutor(max_workers=8, thread_name_prefix="hs-decode")
    return _DECODE_POOL


def _dtype_hints(schema: pa.Schema, columns: List[str]) -> Optional[Dict[str, np.dtype]]:
    """Numpy dtypes for native INT64-backed logical types (timestamps/dates).

    Returns None when any requested column's arrow type has no faithful
    numpy/native mapping (decimal, nested, ...) — the caller then uses pyarrow.
    """
    hints: Dict[str, np.dtype] = {}
    for c in columns:
        t = schema.field(c).type
        if pa.types.is_timestamp(t):
            hints[c] = np.dtype(f"datetime64[{t.unit}]")
        elif pa.types.is_date32(t):
            # INT32 days since epoch; pyarrow surfaces datetime64[D] — the
            # native wrapper widens int32 -> datetime64[D] by astype
            hints[c] = np.dtype("datetime64[D]")
        elif pa.types.is_date64(t):
            hints[c] = np.dtype("datetime64[ms]")
        elif (
            pa.types.is_time(t)       # time32/time64 surface as datetime.time objects
            or pa.types.is_duration(t)
            or pa.types.is_decimal(t)
            or pa.types.is_nested(t)
            or pa.types.is_dictionary(t)
        ):
            return None
    return hints


def read_parquet_batch(files: List[str], columns: Optional[List[str]]) -> B.Batch:
    """Read ``columns`` of ``files`` into one concatenated batch, native-first.

    Schema-evolved datasets (a file missing a requested column, or differing
    per-file schemas when ``columns`` is None) go through a single
    dataset-level pyarrow read, which unifies schemas and null-fills — the
    per-file native path requires every file to carry every column.
    """
    from hyperspace_tpu import native

    def _dataset_read() -> B.Batch:
        trace.record("decode", "pyarrow-dataset")
        try:
            # unify per-file schemas so evolved columns survive regardless of
            # file order (a bare dataset takes the FIRST fragment's schema)
            unified = pa.unify_schemas([pq.read_schema(f) for f in files])
            ds = pads.dataset(files, format="parquet", schema=unified)
        except (OSError, pa.ArrowInvalid, pa.ArrowTypeError):
            ds = pads.dataset(files, format="parquet")
        cols = columns
        if columns is not None and any("." in c and c not in ds.schema.names for c in columns):
            # nested struct paths (hybrid scan's appended-file side of a
            # nested index): project leaves into flat columns
            import pyarrow.compute as pc

            from hyperspace_tpu.plan.expr import strip_nested_prefix

            def resolve_path(dotted: str):
                # case-insensitive per segment (the resolver only exact-cases
                # the root; pc.field is case-sensitive)
                parts = dotted.split(".")
                out, fields = [], list(ds.schema)
                for i, p in enumerate(parts):
                    hit = next((f for f in fields if f.name.lower() == p.lower()), None)
                    if hit is None:
                        return parts  # let arrow raise its own error
                    out.append(hit.name)
                    if i < len(parts) - 1:
                        t = hit.type
                        fields = [t.field(j) for j in range(t.num_fields)] if pa.types.is_struct(t) else []
                return out

            cols = {}
            for c in columns:
                if c in ds.schema.names:
                    cols[c] = pc.field(c)
                else:
                    cols[c] = pc.field(*resolve_path(strip_nested_prefix(c)))
        t = ds.to_table(columns=cols)
        return B.table_to_batch(t)

    # a multi-file scan's CONCATENATED batch is itself cacheable (same
    # immutability argument as the per-file entries): re-concatenating 6M
    # rows cost ~0.7 s per execution of TPC-H q1 at sf=1. The entry lives in
    # the same byte-capped LRU; trace events mirror the per-file cached path
    # so dispatch goldens are insensitive to which cache tier answered.
    concat_key = None
    if columns is not None and len(files) > 1:
        per_file = [_io_cache_key(f, columns) for f in files]
        # a None per-file key (stat failed) disables caching everywhere
        # else; embedding it in the tuple would collide unrelated scans
        if all(k is not None for k in per_file):
            concat_key = ("concat", tuple(per_file))
            got = _io_cache_get(concat_key)
            if got is not None:
                for _ in files:
                    trace.record("decode", "cached")
                return got

    # fully-cached scan with an explicit projection: every cached batch holds
    # exactly ``columns``, so concatenation is schema-safe and the pq schema
    # pre-scan can be skipped. With columns=None per-file schemas may differ
    # (cached entries then have heterogeneous keys), so that case still goes
    # through the pre-scan below before trusting the cache.
    cached = [_io_cache_get(_io_cache_key(f, columns)) for f in files]
    if columns is not None and cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        if len(cached) == 1:
            return cached[0]
        out = B.concat(cached)
        if concat_key is not None:
            _io_cache_put(concat_key, out)
        return out

    # pre-scan schemas; any inconsistency -> unified dataset read
    try:
        schemas = [pq.read_schema(f) for f in files]
    except OSError:
        return _dataset_read()
    if columns is None:
        names0 = list(schemas[0].names)
        if any(list(s.names) != names0 for s in schemas[1:]):
            return _dataset_read()
    else:
        for s in schemas:
            if any(c not in s.names for c in columns):
                return _dataset_read()

    def read_one(f: str, schema) -> B.Batch:
        with spans.span("decode", cat="io", file=os.path.basename(f)) as dsp:
            ckey = _io_cache_key(f, columns)
            got = _io_cache_get(ckey)
            if got is not None:
                trace.record("decode", "cached")
                return got
            try:
                cols = list(columns) if columns is not None else list(schema.names)
                hints = _dtype_hints(schema, cols)
                got = native.read_columns(f, cols, hints) if hints is not None else None
            except (native.NativeUnsupported, OSError, KeyError) as e:
                if os.environ.get("HS_DEBUG_DECODE_FALLBACK"):
                    import sys

                    print(f"DECODE-FALLBACK {f}: {type(e).__name__}: {e}", file=sys.stderr)
                got = None
            if got is None:
                trace.record("decode", "pyarrow")
                t = pads.dataset([f], format="parquet").to_table(columns=columns)
                got = B.table_to_batch(t)
            else:
                trace.record("decode", "native")
            dsp.set(rows=B.num_rows(got))
            _io_cache_put(ckey, got)
            return got

    # decode files concurrently (pyarrow and the native decoder release the
    # GIL); list order — bucket sortedness — is preserved by mapping, not by
    # completion. Fully-cached reads (here: the columns=None case, now known
    # schema-consistent) skip the pool: no decode to parallelize.
    if cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        batches = cached
    elif len(files) > 1:
        # spans.wrap binds the submitting request's current span into the
        # pool workers — contextvars do NOT cross ThreadPoolExecutor on
        # their own, and decode spans must land in the caller's tree
        batches = list(_decode_pool().map(spans.wrap(read_one), files, schemas))
    else:
        batches = [read_one(f, s) for f, s in zip(files, schemas)]
    if not batches:
        return _dataset_read()
    if len(batches) == 1:
        return batches[0]
    out = B.concat(batches)
    if concat_key is not None:
        _io_cache_put(concat_key, out)
    return out
