"""Scan-side IO: parquet files -> columnar batches.

Index files (written uncompressed PLAIN/dictionary by the bucketed writer —
indexes/covering.py) decode through the native C++ path
(hyperspace_tpu.native): mmap -> column-chunk decode straight into numpy
buffers, no JVM and no pyarrow table materialization in the hot loop
(SURVEY.md §7 design stance (c)). Files outside the native dialect
(compressed, nested, unsupported encodings) fall back to pyarrow per file.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.exec import batch as B
from hyperspace_tpu.exec import trace
from hyperspace_tpu.obs import spans
from hyperspace_tpu.reliability import errors as rerr
from hyperspace_tpu.reliability.degrade import QUARANTINE
from hyperspace_tpu.reliability.faults import FAULTS
from hyperspace_tpu.reliability.retry import with_retry

# ---------------------------------------------------------------------------
# Per-file decoded-batch cache (the framework's buffer pool). Spark gets this
# from the OS page cache + executor columnar caching; here repeated scans of
# the same immutable index/bucket files skip decode entirely. Entries key on
# (path, mtime_ns, size, columns) so any rewrite invalidates naturally.
# ---------------------------------------------------------------------------

from hyperspace_tpu.utils.lru import BytesLRU

_io_cache = BytesLRU(int(os.environ.get("HS_IO_CACHE_BYTES", 1 << 31)))


def _batch_nbytes(batch: B.Batch) -> int:
    total = 0
    for a in batch.values():
        if a.dtype == object and len(a):
            # strings: numpy reports pointer size only; estimate payload by
            # scaling a bounded sample to the full length
            k = min(len(a), 64)
            sample = sum(len(str(v)) for v in a[:k])
            total += int(a.nbytes) + int(sample * len(a) / k)
        else:
            total += int(a.nbytes)
    return total


def _io_cache_key(path: str, columns: Optional[List[str]]):
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (path, st.st_mtime_ns, st.st_size, tuple(columns) if columns is not None else None)


def _io_cache_get(key) -> Optional[B.Batch]:
    if key is None:
        return None
    got = _io_cache.get(key)
    if got is not None:
        return dict(got)  # callers may add/remove dict keys
    return None


def _io_cache_put(key, batch: B.Batch) -> None:
    if key is None:
        return
    # cached buffers are shared with every future reader of this file —
    # freeze them so an in-place mutation of a collected result raises
    # instead of silently corrupting the cache (collect() results can be
    # read-only views; copy before mutating)
    for a in batch.values():
        a.setflags(write=False)
    _io_cache.put(key, dict(batch), _batch_nbytes(batch))


def clear_io_cache() -> None:
    _io_cache.clear()


def _key_mentions_path(key, paths) -> bool:
    # cache keys are nested tuples whose leaves include the source path
    # string: file keys are (path, mtime, size, cols), concat keys wrap a
    # tuple of per-file keys, row-group keys append a suffix tuple — a
    # recursive scan covers every shape without coupling to each layout
    if isinstance(key, str):
        return key in paths
    if isinstance(key, tuple):
        return any(_key_mentions_path(part, paths) for part in key)
    return False


def purge_io_cache(paths) -> int:
    """Drop every cached batch derived from any of ``paths`` (data-version
    commit invalidation); returns the number of entries removed."""
    wanted = set(paths)
    if not wanted:
        return 0
    removed = 0
    for key in _io_cache.keys():
        if _key_mentions_path(key, wanted) and _io_cache.discard(key):
            removed += 1
    return removed


_DECODE_POOL = None
_DECODE_POOL_LOCK = threading.Lock()
_DECODE_POOL_SIZE = None  # width the live pool was created with
_CONFIGURED_THREADS: Optional[int] = None  # from conf, via set_decode_threads


def decode_threads() -> int:
    """Effective decode-pool width: HS_DECODE_THREADS env > session conf
    (``hyperspace.exec.io.decodeThreads``) > default 8."""
    env = os.environ.get("HS_DECODE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, _CONFIGURED_THREADS or 8)


def set_decode_threads(n: Optional[int]) -> None:
    """Record the conf-requested pool width (called on Session construction).
    An already-built pool of a different width is retired — its in-flight
    decodes finish on the old threads — and the next scan builds the new one."""
    global _CONFIGURED_THREADS, _DECODE_POOL, _DECODE_POOL_SIZE
    with _DECODE_POOL_LOCK:
        _CONFIGURED_THREADS = int(n) if n else None
        if _DECODE_POOL is not None and _DECODE_POOL_SIZE != decode_threads():
            _DECODE_POOL.shutdown(wait=False)
            _DECODE_POOL = None
            _DECODE_POOL_SIZE = None


def _decode_pool():
    """Shared decode thread pool — per-call pools would pay thread spin-up on
    every scan. Init is locked: serving workers scan concurrently, and a
    double-create here leaked a whole thread pool."""
    global _DECODE_POOL, _DECODE_POOL_SIZE
    if _DECODE_POOL is None:
        with _DECODE_POOL_LOCK:
            if _DECODE_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _DECODE_POOL_SIZE = decode_threads()
                _DECODE_POOL = ThreadPoolExecutor(
                    max_workers=_DECODE_POOL_SIZE, thread_name_prefix="hs-decode"
                )
    return _DECODE_POOL


def _dtype_hints(schema: pa.Schema, columns: List[str]) -> Optional[Dict[str, np.dtype]]:
    """Numpy dtypes for native INT64-backed logical types (timestamps/dates).

    Returns None when any requested column's arrow type has no faithful
    numpy/native mapping (decimal, nested, ...) — the caller then uses pyarrow.
    """
    hints: Dict[str, np.dtype] = {}
    for c in columns:
        t = schema.field(c).type
        if pa.types.is_timestamp(t):
            hints[c] = np.dtype(f"datetime64[{t.unit}]")
        elif pa.types.is_date32(t):
            # INT32 days since epoch; pyarrow surfaces datetime64[D] — the
            # native wrapper widens int32 -> datetime64[D] by astype
            hints[c] = np.dtype("datetime64[D]")
        elif pa.types.is_date64(t):
            hints[c] = np.dtype("datetime64[ms]")
        elif (
            pa.types.is_time(t)       # time32/time64 surface as datetime.time objects
            or pa.types.is_duration(t)
            or pa.types.is_decimal(t)
            or pa.types.is_nested(t)
            or pa.types.is_dictionary(t)
        ):
            return None
    return hints


# ---------------------------------------------------------------------------
# Row-group pruning: a scan's pushed-down predicate is evaluated against the
# parquet footers' per-row-group min/max statistics BEFORE any decode, through
# the data-skipping rule's three-valued _SketchEvaluator (reused, not
# duplicated): "definitely no matching rows" skips the row group, anything
# uncertain decodes it. The Filter above re-applies the full predicate, so
# pruning is conservative by construction and never changes results.
# ---------------------------------------------------------------------------


def _rg_counters():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return (
        REGISTRY.counter(
            "hs_rowgroups_scanned_total",
            "Parquet row groups decoded by predicate-pushdown scans",
        ),
        REGISTRY.counter(
            "hs_rowgroups_skipped_total",
            "Parquet row groups skipped by min/max statistics pruning",
        ),
        REGISTRY.counter(
            "hs_rowgroup_bytes_skipped_total",
            "Bytes of parquet row groups skipped by min/max statistics pruning",
        ),
    )


def _stats_array(vals: List) -> np.ndarray:
    """Per-row-group min or max values as an array the sketch evaluator's
    comparisons understand. None entries (absent statistics) survive as
    object-array nulls, which the evaluator keeps unconditionally."""
    import datetime

    if not vals or any(v is None for v in vals):
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out
    v0 = vals[0]
    if isinstance(v0, datetime.datetime):
        return np.array(vals, dtype="datetime64[us]")
    if isinstance(v0, datetime.date):
        return np.array(vals, dtype="datetime64[D]")
    if isinstance(v0, bytes):
        vals = [v.decode("utf-8", "surrogateescape") for v in vals]
    out = np.asarray(vals)
    if out.dtype.kind in ("U", "S"):
        out = out.astype(object)
    return out


def prune_row_groups(path: str, predicate) -> Optional[List[int]]:
    """Row-group indices of ``path`` that *might* hold rows matching
    ``predicate``, judged by footer min/max statistics; None when nothing can
    be pruned (every group kept). Columns without statistics — or predicate
    shapes outside the evaluator's language — keep their groups."""
    from hyperspace_tpu.indexes.dataskipping import MinMaxSketch
    from hyperspace_tpu.rules.dataskipping_rule import _SketchEvaluator

    refs = sorted(set(predicate.references()))
    if not refs:
        return None
    try:
        if FAULTS.active:
            FAULTS.check("io.footer", path)
        md = pq.read_metadata(path)
    except (OSError, pa.ArrowInvalid) as exc:
        # pruning is an optimization: the full decode below still answers
        # (and will surface/classify a genuinely bad file) — but the footer
        # failure itself is counted, never silently ignored
        rerr.count_io_error("io.footer", exc, swallowed=True)
        return None
    n_rg = md.num_row_groups
    if n_rg == 0:
        return None
    rg0 = md.row_group(0)
    col_idx = {rg0.column(j).path_in_schema: j for j in range(rg0.num_columns)}
    lower_idx = {name.lower(): j for name, j in col_idx.items()}
    sketches, table = [], {}
    for c in refs:
        j = col_idx.get(c, lower_idx.get(c.lower()))
        if j is None:
            continue  # partition / computed column: no file statistics
        mins: List = []
        maxs: List = []
        for i in range(n_rg):
            st = md.row_group(i).column(j).statistics
            if st is not None and st.has_min_max:
                mins.append(st.min)
                maxs.append(st.max)
            else:
                mins.append(None)
                maxs.append(None)
        s = MinMaxSketch(c)
        mn_name, mx_name = s.output_names()
        table[mn_name] = _stats_array(mins)
        table[mx_name] = _stats_array(maxs)
        sketches.append(s)
    if not sketches:
        return None
    try:
        mask = _SketchEvaluator(sketches, table, n_rg).eval(predicate)
    except Exception:
        return None  # pruning must never break a read the full decode answers
    if mask is None or mask.all():
        return None
    return [int(i) for i in np.nonzero(mask)[0]]


def _read_row_groups(
    f: str, columns: Optional[List[str]], schema: pa.Schema, keep: List[int], dsp
) -> B.Batch:
    """Decode only the surviving row groups of one file (pyarrow path; the
    native decoder reads whole column chunks). Fully-pruned files return a
    typed empty batch from the file schema."""
    scanned_c, skipped_c, bytes_c = _rg_counters()
    md = pq.read_metadata(f)
    n_rg = md.num_row_groups
    kept = set(keep)
    sk_bytes = sum(
        md.row_group(i).total_byte_size for i in range(n_rg) if i not in kept
    )
    scanned_c.inc(len(keep))
    skipped_c.inc(n_rg - len(keep))
    bytes_c.inc(sk_bytes)
    dsp.set(rowgroups_skipped=n_rg - len(keep), rowgroup_bytes_skipped=int(sk_bytes))
    if not keep:
        trace.record("decode", "rowgroup-pruned")
        t = schema.empty_table()
        if columns is not None:
            t = t.select(columns)
        return B.table_to_batch(t)
    ckey = _io_cache_key(f, columns)
    ckey = ckey + (("rg",) + tuple(keep),) if ckey is not None else None
    got = _io_cache_get(ckey)
    if got is not None:
        trace.record("decode", "cached")
        return got
    trace.record("decode", "pyarrow-rowgroups")
    t = pq.ParquetFile(f).read_row_groups(keep, columns=columns)
    got = B.table_to_batch(t)
    dsp.set(rows=B.num_rows(got))
    _io_cache_put(ckey, got)
    return got


# ---------------------------------------------------------------------------
# Native row-group fast path: one scan decodes every surviving (file × row
# group × column) chunk in parallel on the hs-decode pool, each chunk writing
# straight into its slot of ONE √2-shape-bucket-padded buffer per column.
# Assembly is concat-free — the batch's column arrays are prefix views of the
# padded buffers, and the H2D staging hook (exec/device.py) detects the padded
# base and hands jax.device_put the exact memory the C decoder wrote.
# ---------------------------------------------------------------------------

_NATIVE_ENABLED = True  # hyperspace.exec.io.native.enabled
_NATIVE_RG = True  # hyperspace.exec.io.native.rowGroupDecode
_MAX_DICT = 4096  # hyperspace.exec.io.native.maxDictEntries
_STAGING_PAD = 1  # device-count multiple for padded buffers (set lazily)


def set_native_options(
    enabled: Optional[bool] = None,
    rowgroup: Optional[bool] = None,
    max_dict_entries: Optional[int] = None,
) -> None:
    """Record the conf-requested native decode knobs (called on Session
    construction, most-recent-wins — same contract as set_decode_threads)."""
    global _NATIVE_ENABLED, _NATIVE_RG, _MAX_DICT
    if enabled is not None:
        _NATIVE_ENABLED = bool(enabled)
    if rowgroup is not None:
        _NATIVE_RG = bool(rowgroup)
    if max_dict_entries is not None:
        _MAX_DICT = int(max_dict_entries)


def set_staging_pad(m: int) -> None:
    """Device-count multiple the staging padder rounds to; wired when a
    session materializes its mesh. A stale value only costs the zero-copy
    handoff (device._pad_to_bucket falls back to a pad copy), never rows."""
    global _STAGING_PAD
    _STAGING_PAD = max(1, int(m))


def _native_decode_counter(codec: str):
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_native_decode_total",
        "Column chunks decoded by the native row-group fast path",
        codec=codec,
    )


def _native_bytes_counter():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_native_decode_bytes_total",
        "Logical bytes written into decode buffers by the native fast path",
    )


def _native_fallback_counter(reason: str):
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_native_fallback_total",
        "Decode attempts that left the native path for pyarrow",
        reason=reason,
    )


def _padded_rows(n: int) -> int:
    """Rows to allocate for ``n`` decoded rows: the √2 shape bucket the device
    padder would pick, rounded up to the mesh's device-count multiple — so the
    staged array IS the decode buffer, no pad copy."""
    if n <= 0:
        return 0
    from hyperspace_tpu.exec.device import bucket_rows

    t = bucket_rows(n)
    m = max(1, _STAGING_PAD)
    return t + (-t) % m


def _native_rg_scan(
    files: List[str],
    columns: Optional[List[str]],
    schemas: List[pa.Schema],
    predicate,
    concat_key,
) -> Optional[B.Batch]:
    """Decode a whole scan natively at row-group granularity; None when the
    scan can't be answered natively end to end (caller falls back to the
    per-file path, which keeps its own native-first discipline).

    Requirements checked here: every file opens in the native dialect, every
    requested column decodes to one consistent dtype, and nothing about the
    scan is already cached. Row-group pruning applies per file with the same
    counter accounting as _read_row_groups; a pruned scan skips all cache
    writes (a pruned batch under an unpruned key would poison later readers).
    """
    from hyperspace_tpu import native

    env = os.environ.get("HS_NATIVE_RG")
    if env is not None and env.strip().lower() in ("0", "false", "off"):
        return None
    if not (_NATIVE_ENABLED and _NATIVE_RG) or not files:
        return None
    cols = list(columns) if columns is not None else list(schemas[0].names)
    if not cols:
        return None
    hints = _dtype_hints(schemas[0], cols)
    if hints is None:
        return None  # per-file path counts the dtype fallback
    # one shared buffer per column needs ONE dtype: identical arrow types
    # across files (same-name/new-type evolution goes through the per-file path)
    t0 = {c: schemas[0].field(c).type for c in cols}
    for s in schemas[1:]:
        if any(not s.field(c).type.equals(t0[c]) for c in cols):
            return None

    handles: List[native.NativeParquetFile] = []
    try:
        try:
            for f in files:
                handles.append(native.NativeParquetFile(f))
        except native.NativeUnsupported:
            return None  # per-file path retries native and counts the fallback
        except OSError as exc:
            rerr.count_io_error("io.decode", exc, swallowed=True)
            _native_fallback_counter("io-error").inc()
            return None
        return _native_rg_decode(files, cols, columns, hints, predicate, concat_key, handles)
    finally:
        for h in handles:
            h.close()


def _native_rg_decode(
    files: List[str],
    cols: List[str],
    columns: Optional[List[str]],
    hints: Dict[str, np.dtype],
    predicate,
    concat_key,
    handles,
) -> Optional[B.Batch]:
    from hyperspace_tpu import native

    # -- per-column plan: buffer dtype (None = strings -> object array) ------
    col_dtype: Dict[str, Optional[np.dtype]] = {}
    col_scratch32 = set()  # date32: int32 chunk scratch astype'd into datetime64[D]
    col_opt: Dict[str, bool] = {}
    try:
        for c in cols:
            nd = handles[0].column_numpy_dtype(c)
            hint = hints.get(c)
            if nd is None:
                dt = None
            elif hint is not None and nd.kind in ("i", "u"):
                if hint.itemsize == nd.itemsize:
                    dt = hint  # timestamps/date64: decode int64 straight into the view
                elif hint.kind == "M":
                    dt = hint
                    col_scratch32.add(c)
                else:
                    dt = nd
            else:
                dt = nd
            col_dtype[c] = dt
            col_opt[c] = any(h.column_optional(c) for h in handles)
    except native.NativeUnsupported:
        return None  # per-file path retries native and counts the fallback

    # -- per-file row plan + pruning (same counters as _read_row_groups) -----
    per_file_keep: List[List[int]] = []
    file_rows: List[int] = []
    file_skip: List[Optional[tuple]] = []  # (groups skipped, bytes skipped)
    fully_pruned: List[bool] = []
    pruned_any = False
    try:
        for f, h in zip(files, handles):
            keep = prune_row_groups(f, predicate) if predicate is not None else None
            if keep is None:
                ks = list(range(h.num_row_groups))
                file_skip.append(None)
            else:
                pruned_any = True
                ks = keep
                kept = set(ks)
                md = pq.read_metadata(f)
                sk_bytes = sum(
                    md.row_group(i).total_byte_size
                    for i in range(h.num_row_groups)
                    if i not in kept
                )
                scanned_c, skipped_c, bytes_c = _rg_counters()
                scanned_c.inc(len(ks))
                skipped_c.inc(h.num_row_groups - len(ks))
                bytes_c.inc(sk_bytes)
                file_skip.append((h.num_row_groups - len(ks), int(sk_bytes)))
            per_file_keep.append(ks)
            fully_pruned.append(keep is not None and not ks)
            file_rows.append(sum(h.rg_rows[g] for g in ks))
    except (OSError, pa.ArrowInvalid) as exc:
        rerr.count_io_error("io.footer", exc, swallowed=True)
        _native_fallback_counter("io-error").inc()
        return None

    total = sum(file_rows)
    starts: List[int] = []
    acc = 0
    for r in file_rows:
        starts.append(acc)
        acc += r
    padded = _padded_rows(total)

    # -- shared decode buffers, tail pre-filled like device._pad_to_bucket ---
    buffers: Dict[str, np.ndarray] = {}
    validity: Dict[str, np.ndarray] = {}
    for c in cols:
        dt = col_dtype[c]
        if dt is None:
            buffers[c] = np.empty(total, dtype=object)
        else:
            buf = np.empty(padded, dtype=dt)
            if padded > total:
                if dt == np.float64:
                    buf[total:] = np.nan
                elif dt.kind == "M":
                    buf.view(np.int64)[total:] = 0
                else:
                    buf[total:] = 0
            buffers[c] = buf
            if col_opt[c]:
                validity[c] = np.ones(total, dtype=np.uint8)

    # -- dictionary-shipping plan for low-cardinality string columns ---------
    # every surviving chunk must be fully dictionary-encoded with a dictionary
    # within maxDictEntries; chunk dictionaries remap into one global one so
    # codes are consistent across files/row groups
    chunks = [(fi, g) for fi in range(len(files)) for g in per_file_keep[fi]]
    dict_plan: Dict[str, tuple] = {}  # c -> (codes buffer, remaps, global uniques)
    if _MAX_DICT > 0:
        for c in cols:
            if col_dtype[c] is not None:
                continue
            try:
                if not all(
                    0 < handles[fi].rg_dict_count(g, c) <= _MAX_DICT for fi, g in chunks
                ):
                    continue
                dicts = [handles[fi].read_dict_rg_arrow(g, c) for fi, g in chunks]
            # not a pyarrow fallback: the column still decodes natively below,
            # just with materialized strings instead of shipped codes
            except native.NativeUnsupported:  # hscheck: disable=native-fallback
                continue
            # one global dictionary + per-chunk remaps from a single C++
            # hash pass (arrow dictionary_encode over the decoder's raw
            # buffers): a per-entry Python merge loop here once cost as much
            # as the C decode itself, and only the global uniques ever
            # materialize as Python strings
            remaps: Dict[tuple, np.ndarray] = {}
            if dicts:
                ends = np.cumsum([len(d) for d in dicts])
                enc = (
                    pa.concat_arrays(dicts) if len(dicts) > 1 else dicts[0]
                ).dictionary_encode()
                gu = enc.dictionary.to_numpy(zero_copy_only=False)
                inv = enc.indices.to_numpy().astype(np.int32, copy=False)
                remaps = {
                    key: inv[end - len(d) : end]
                    for key, d, end in zip(chunks, dicts, ends)
                }
            else:
                gu = np.empty(0, dtype=object)
            cbuf = np.empty(padded, dtype=np.int32)
            if padded > total:
                cbuf[total:] = 0
            dict_plan[c] = (cbuf, remaps, gu)

    # -- parallel chunk decode ------------------------------------------------
    bytes_c = _native_bytes_counter()

    def _decode_chunk(fi: int, g: int, c: str, start: int, nrows: int) -> None:
        h = handles[fi]
        codec = h.rg_codec(g, c)
        plan = dict_plan.get(c)
        if plan is not None:
            cbuf, remaps, _gu = plan
            codes = h.read_codes_rg(g, c)
            rm = remaps[(fi, g)]
            cbuf[start : start + nrows] = np.where(
                codes >= 0, rm[np.maximum(codes, 0)], np.int32(-1)
            )
            nb = nrows * 4
        elif col_dtype[c] is None:
            vals, v8, nb = h.read_binary_rg(g, c)
            if v8 is not None and not v8.all():
                vals[v8 == 0] = None
            buffers[c][start : start + nrows] = vals
        else:
            dst = buffers[c][start : start + nrows]
            v8 = validity[c][start : start + nrows] if c in validity else None
            if c in col_scratch32:
                scratch = np.empty(nrows, dtype=np.int32)
                h.read_fixed_rg_into(g, c, scratch, v8)
                dst[...] = scratch.astype(col_dtype[c])
            else:
                h.read_fixed_rg_into(g, c, dst, v8)
            nb = nrows * col_dtype[c].itemsize
        _native_decode_counter(codec).inc()
        bytes_c.inc(int(nb))

    # per-file scans (partition attach, file-name columns) call
    # read_parquet_batch FROM a decode-pool worker; submitting chunk tasks
    # back onto that same pool and blocking would deadlock once every worker
    # is such a caller — decode inline on this thread instead (still
    # zero-copy into the shared buffers, just serial for this one file)
    inline = threading.current_thread().name.startswith("hs-decode")
    pool = None if inline else _decode_pool()
    errors: Dict[int, List[BaseException]] = {}
    futs_by_file: List[list] = []
    all_futs: list = []
    try:
        for fi, f in enumerate(files):
            futs: list = []
            futs_by_file.append(futs)
            try:
                if FAULTS.active:
                    FAULTS.check("io.decode", f)  # the "before the C call" seam
            except Exception as exc:
                errors.setdefault(fi, []).append(exc)
                continue
            row = starts[fi]
            for g in per_file_keep[fi]:
                nrows = handles[fi].rg_rows[g]
                for c in cols:
                    if inline:
                        try:
                            _decode_chunk(fi, g, c, row, nrows)
                        except Exception as exc:
                            errors.setdefault(fi, []).append(exc)
                    else:
                        futs.append(
                            pool.submit(_decode_chunk, fi, g, c, row, nrows)
                        )
                row += nrows
            all_futs.extend(futs)
        for fi, f in enumerate(files):
            for fut in futs_by_file[fi]:
                try:
                    fut.result()
                except Exception as exc:
                    errors.setdefault(fi, []).append(exc)
            if fi not in errors and FAULTS.active:
                try:
                    FAULTS.check("io.decode", f)  # the "after the C call" seam
                except Exception as exc:
                    errors.setdefault(fi, []).append(exc)
    finally:
        # handles close right after we return — nothing may still be decoding
        if all_futs:
            from concurrent.futures import wait as _futures_wait

            _futures_wait(all_futs)

    if errors:
        # corrupt data surfaces typed and strikes quarantine — falling back
        # would re-read the same bad bytes (mirrors read_one's discipline)
        for fi, es in errors.items():
            for e in es:
                err = (
                    e
                    if isinstance(e, rerr.ReliabilityError)
                    else rerr.classify(e, path=files[fi])
                    if isinstance(e, (OSError, pa.ArrowInvalid, pa.ArrowTypeError))
                    else None
                )
                if isinstance(err, rerr.CorruptDataError):
                    rerr.count_io_error("io.decode", err)
                    if QUARANTINE.enabled:
                        QUARANTINE.note_corrupt(files[fi])
                    raise err from e
        # transient/dialect failures: count, then the per-file path answers
        # (with retry) — a consumed one-shot fault must not go unrecorded
        for es in errors.values():
            for e in es:
                if isinstance(e, native.NativeUnsupported):
                    _native_fallback_counter("dialect").inc()
                else:
                    rerr.count_io_error("io.decode", e, swallowed=True)
                    _native_fallback_counter("io-error").inc()
        return None

    # -- assemble: prefix views of the padded buffers, pyarrow null parity ---
    out: B.Batch = {}
    for c in cols:
        plan = dict_plan.get(c)
        if plan is not None:
            cbuf, _remaps, gu = plan
            codes_v = cbuf[:total]
            if gu.size:
                nulls = codes_v < 0
                if nulls.any():
                    exp = gu[np.where(nulls, np.int32(0), codes_v)]
                    exp[nulls] = None
                else:
                    exp = gu[codes_v]
            else:
                exp = np.full(total, None, dtype=object)
            out[c] = B.dict_backed(np.asarray(exp, dtype=object), codes_v, gu)
        elif col_dtype[c] is None:
            out[c] = buffers[c]
        else:
            vals = buffers[c][:total]
            v8 = validity.get(c)
            if v8 is not None and not v8.all():
                # parity with pyarrow's to_numpy (see native.read_columns)
                if vals.dtype.kind == "f":
                    vals = vals.copy()
                    vals[v8 == 0] = np.nan
                elif vals.dtype.kind == "M":
                    vals = vals.copy()
                    vals[v8 == 0] = np.datetime64("NaT")
                elif vals.dtype.kind == "b":
                    vals = vals.astype(object)
                    vals[v8 == 0] = None
                elif vals.dtype.kind in ("i", "u"):
                    vals = vals.astype(np.float64)
                    vals[v8 == 0] = np.nan
            out[c] = vals

    for fi, f in enumerate(files):
        with spans.span("decode", cat="io", file=os.path.basename(f)) as dsp:
            dsp.set(rows=file_rows[fi])
            if file_skip[fi] is not None:
                dsp.set(
                    rowgroups_skipped=file_skip[fi][0],
                    rowgroup_bytes_skipped=file_skip[fi][1],
                )
            trace.record("decode", "rowgroup-pruned" if fully_pruned[fi] else "native-rg")
        if QUARANTINE.enabled:
            QUARANTINE.note_ok(f)

    if not pruned_any:
        for fi, f in enumerate(files):
            s, e = starts[fi], starts[fi] + file_rows[fi]
            _io_cache_put(_io_cache_key(f, columns), {c: out[c][s:e] for c in cols})
        if concat_key is not None:
            _io_cache_put(concat_key, dict(out))
    return out


def read_parquet_batch(
    files: List[str], columns: Optional[List[str]], predicate=None
) -> B.Batch:
    """Read ``columns`` of ``files`` into one concatenated batch, native-first.

    Schema-evolved datasets (a file missing a requested column, or differing
    per-file schemas when ``columns`` is None) go through a single
    dataset-level pyarrow read, which unifies schemas and null-fills — the
    per-file native path requires every file to carry every column.

    ``predicate`` (a pushed-down filter Expr) enables row-group min/max
    pruning: groups its statistics definitively exclude are never decoded.
    The caller's Filter still applies the predicate, so a cached full-file
    batch (more rows) is always an acceptable answer.
    """
    from hyperspace_tpu import native

    def _dataset_read() -> B.Batch:
        trace.record("decode", "pyarrow-dataset")
        try:
            # unify per-file schemas so evolved columns survive regardless of
            # file order (a bare dataset takes the FIRST fragment's schema)
            unified = pa.unify_schemas([pq.read_schema(f) for f in files])
            ds = pads.dataset(files, format="parquet", schema=unified)
        except (OSError, pa.ArrowInvalid, pa.ArrowTypeError) as exc:
            # schema unification is best-effort (first-fragment schema is a
            # correct fallback for homogeneous files); count the classified
            # failure — a truly bad file still raises out of to_table below
            rerr.count_io_error("io.footer", exc, swallowed=True)
            ds = pads.dataset(files, format="parquet")
        cols = columns
        if columns is not None and any("." in c and c not in ds.schema.names for c in columns):
            # nested struct paths (hybrid scan's appended-file side of a
            # nested index): project leaves into flat columns
            import pyarrow.compute as pc

            from hyperspace_tpu.plan.expr import strip_nested_prefix

            def resolve_path(dotted: str):
                # case-insensitive per segment (the resolver only exact-cases
                # the root; pc.field is case-sensitive)
                parts = dotted.split(".")
                out, fields = [], list(ds.schema)
                for i, p in enumerate(parts):
                    hit = next((f for f in fields if f.name.lower() == p.lower()), None)
                    if hit is None:
                        return parts  # let arrow raise its own error
                    out.append(hit.name)
                    if i < len(parts) - 1:
                        t = hit.type
                        fields = [t.field(j) for j in range(t.num_fields)] if pa.types.is_struct(t) else []
                return out

            cols = {}
            for c in columns:
                if c in ds.schema.names:
                    cols[c] = pc.field(c)
                else:
                    cols[c] = pc.field(*resolve_path(strip_nested_prefix(c)))
        t = ds.to_table(columns=cols)
        return B.table_to_batch(t)

    # a multi-file scan's CONCATENATED batch is itself cacheable (same
    # immutability argument as the per-file entries): re-concatenating 6M
    # rows cost ~0.7 s per execution of TPC-H q1 at sf=1. The entry lives in
    # the same byte-capped LRU; trace events mirror the per-file cached path
    # so dispatch goldens are insensitive to which cache tier answered.
    concat_key = None
    if columns is not None and len(files) > 1:
        per_file = [_io_cache_key(f, columns) for f in files]
        # a None per-file key (stat failed) disables caching everywhere
        # else; embedding it in the tuple would collide unrelated scans
        if all(k is not None for k in per_file):
            concat_key = ("concat", tuple(per_file))
            got = _io_cache_get(concat_key)
            if got is not None:
                for _ in files:
                    trace.record("decode", "cached")
                return got

    # fully-cached scan with an explicit projection: every cached batch holds
    # exactly ``columns``, so concatenation is schema-safe and the pq schema
    # pre-scan can be skipped. With columns=None per-file schemas may differ
    # (cached entries then have heterogeneous keys), so that case still goes
    # through the pre-scan below before trusting the cache.
    cached = [_io_cache_get(_io_cache_key(f, columns)) for f in files]
    if columns is not None and cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        if len(cached) == 1:
            return cached[0]
        out = B.concat(cached)
        if concat_key is not None:
            _io_cache_put(concat_key, out)
        return out

    # pre-scan schemas; any inconsistency -> unified dataset read. A corrupt
    # footer is NOT an inconsistency: falling back would re-read the same bad
    # bytes, so it surfaces typed (and strikes the owning index's breaker)
    try:
        schemas = []
        for f in files:
            if FAULTS.active:
                FAULTS.check("io.footer", f)
            try:
                schemas.append(pq.read_schema(f))
            except (pa.ArrowInvalid, pa.ArrowTypeError) as exc:
                err = rerr.classify(exc, path=f)
                rerr.count_io_error("io.footer", err)
                if QUARANTINE.enabled and isinstance(err, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise err from exc
    except OSError as exc:
        rerr.count_io_error("io.footer", exc, swallowed=True)
        return _dataset_read()
    evolved: set = set()
    unified: Optional[pa.Schema] = None
    if columns is None:
        names0 = list(schemas[0].names)
        if any(list(s.names) != names0 for s in schemas[1:]):
            return _dataset_read()
    else:
        missing = [f for f, s in zip(files, schemas) if any(c not in s.names for c in columns)]
        if missing:
            # schema-evolved files decode per file against the unified schema
            # (null-filling their missing columns) while native-dialect
            # siblings keep the native path — the old all-or-nothing gate sent
            # the WHOLE scan through one pyarrow dataset read
            if len(missing) == len(files):
                return _dataset_read()
            try:
                unified = pa.unify_schemas(schemas)
            except (pa.ArrowInvalid, pa.ArrowTypeError) as exc:
                rerr.count_io_error("io.footer", exc, swallowed=True)
                return _dataset_read()
            if any(c not in unified.names for c in columns):
                return _dataset_read()  # nested projection paths etc.
            evolved = set(missing)
            _native_fallback_counter("schema-evolved").inc(len(missing))

    if not evolved and not any(b is not None for b in cached):
        got = _native_rg_scan(files, columns, schemas, predicate, concat_key)
        if got is not None:
            return got

    def read_one(f: str, schema) -> B.Batch:
        with spans.span("decode", cat="io", file=os.path.basename(f)) as dsp:
            ckey = _io_cache_key(f, columns)
            got = _io_cache_get(ckey)
            if got is not None:
                trace.record("decode", "cached")
                return got
            if predicate is not None and f not in evolved:
                keep = prune_row_groups(f, predicate)
                if keep is not None:
                    return _read_row_groups(f, columns, schema, keep, dsp)
            def _decode() -> B.Batch:
                if FAULTS.active:
                    FAULTS.check("io.decode", f)
                if f in evolved:
                    # decode against the unified schema so this file's missing
                    # columns null-fill with their siblings' types
                    trace.record("decode", "pyarrow")
                    t = pads.dataset([f], format="parquet", schema=unified).to_table(
                        columns=columns
                    )
                    return B.table_to_batch(t)
                try:
                    cols = list(columns) if columns is not None else list(schema.names)
                    hints = _dtype_hints(schema, cols) if _NATIVE_ENABLED else None
                    if hints is None:
                        if _NATIVE_ENABLED:
                            _native_fallback_counter("dtype").inc()
                        out = None
                    else:
                        out = native.read_columns(f, cols, hints)
                except (native.NativeUnsupported, OSError, KeyError) as e:
                    # dialect mismatches are the expected fallback path; real
                    # IO failures falling through to the pyarrow re-read are
                    # classified and counted, never silently ignored
                    if isinstance(e, native.NativeUnsupported):
                        _native_fallback_counter("dialect").inc()
                    else:
                        rerr.count_io_error("io.decode", e, swallowed=True)
                        _native_fallback_counter("io-error").inc()
                    if os.environ.get("HS_DEBUG_DECODE_FALLBACK"):
                        import sys

                        print(f"DECODE-FALLBACK {f}: {type(e).__name__}: {e}", file=sys.stderr)
                    out = None
                if out is None:
                    trace.record("decode", "pyarrow")
                    t = pads.dataset([f], format="parquet").to_table(columns=columns)
                    out = B.table_to_batch(t)
                else:
                    trace.record("decode", "native")
                return out

            try:
                got = with_retry(_decode, op="io.decode")
            except rerr.ReliabilityError as exc:
                rerr.count_io_error("io.decode", exc)
                if QUARANTINE.enabled and isinstance(exc, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise
            except (OSError, pa.ArrowInvalid, pa.ArrowTypeError) as exc:
                err = rerr.classify(exc, path=f)
                rerr.count_io_error("io.decode", err)
                if QUARANTINE.enabled and isinstance(err, rerr.CorruptDataError):
                    QUARANTINE.note_corrupt(f)
                raise err from exc
            if QUARANTINE.enabled:
                QUARANTINE.note_ok(f)
            dsp.set(rows=B.num_rows(got))
            _io_cache_put(ckey, got)
            return got

    # decode files concurrently (pyarrow and the native decoder release the
    # GIL); list order — bucket sortedness — is preserved by mapping, not by
    # completion. Fully-cached reads (here: the columns=None case, now known
    # schema-consistent) skip the pool: no decode to parallelize.
    if cached and all(b is not None for b in cached):
        for _ in cached:
            trace.record("decode", "cached")
        batches = cached
    elif len(files) > 1:
        # spans.wrap binds the submitting request's current span into the
        # pool workers — contextvars do NOT cross ThreadPoolExecutor on
        # their own, and decode spans must land in the caller's tree
        batches = list(_decode_pool().map(spans.wrap(read_one), files, schemas))
    else:
        batches = [read_one(f, s) for f, s in zip(files, schemas)]
    if not batches:
        return _dataset_read()
    if len(batches) == 1:
        return batches[0]
    out = B.concat(batches)
    # a predicate-pruned concatenation holds FEWER rows than the full scan;
    # caching it under the unpruned concat key would poison predicate-less
    # readers of the same files with silently missing rows
    if concat_key is not None and predicate is None:
        _io_cache_put(concat_key, out)
    return out
