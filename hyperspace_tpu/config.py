"""Configuration system.

All config keys and defaults centralized here, mirroring the reference's
``IndexConstants`` (ref: HS/index/IndexConstants.scala:21-131) and the typed
accessors of ``HyperspaceConf`` (ref: HS/util/HyperspaceConf.scala:27-153).
Keys are namespaced ``hyperspace.*`` (the reference uses ``spark.hyperspace.*``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


class keys:
    """All configuration keys (ref: HS/index/IndexConstants.scala:21-131)."""

    SYSTEM_PATH = "hyperspace.system.path"
    NUM_BUCKETS = "hyperspace.index.numBuckets"
    HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
    HYBRID_SCAN_MAX_DELETED_RATIO = "hyperspace.index.hybridscan.maxDeletedRatio"
    HYBRID_SCAN_MAX_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
    FILTER_RULE_USE_BUCKET_SPEC = "hyperspace.index.filterRule.useBucketSpec"
    NESTED_COLUMN_ENABLED = "hyperspace.index.nestedColumn.enabled"
    CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
    LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
    OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
    SOURCE_BUILDERS = "hyperspace.index.sources.fileBasedBuilders"
    # Accepted for reference compatibility but inert here: plan fingerprints
    # canonicalize path spelling away, so glob-addressed and dir-addressed
    # reads of the same files already signature-match (sources/signatures.py).
    GLOBBING_PATTERN = "hyperspace.source.globbingPattern"
    DATASKIPPING_TARGET_FILE_SIZE = "hyperspace.index.dataskipping.targetIndexDataFileSize"
    EVENT_LOGGER_CLASS = "hyperspace.eventLoggerClass"
    DISPLAY_MODE = "hyperspace.explain.displayMode"
    HIGHLIGHT_BEGIN_TAG = "hyperspace.explain.displayMode.highlight.beginTag"
    HIGHLIGHT_END_TAG = "hyperspace.explain.displayMode.highlight.endTag"
    # TPU-specific knobs (no reference counterpart: the reference delegates
    # execution tuning to Spark; here the framework owns the execution layer).
    TPU_ROWS_PER_SHARD_CAPACITY_FACTOR = "hyperspace.tpu.rebucket.capacityFactor"
    TPU_MESH_AXIS = "hyperspace.tpu.mesh.axis"
    TPU_BUILD_BATCH_ROWS = "hyperspace.tpu.build.batchRows"
    TPU_BUILD_DISTRIBUTED_MIN_ROWS = "hyperspace.tpu.build.distributedMinRows"
    TPU_QUERY_DEVICE_EXECUTION = "hyperspace.tpu.query.deviceExecution"
    TPU_QUERY_DEVICE_MIN_ROWS = "hyperspace.tpu.query.deviceMinRows"
    TPU_JOIN_DEVICE_MATERIALIZE = "hyperspace.tpu.join.deviceMaterialize"
    TPU_JOIN_DEVICE_MATERIALIZE_MAX_BYTES = "hyperspace.tpu.join.deviceMaterializeMaxBytes"
    TPU_JOIN_DEVICE_SPAN_MAX_BYTES = "hyperspace.tpu.join.deviceSpanMaxBytes"
    # Mesh-sharded execution (hyperspace_tpu/parallel/): shard_map scans and
    # collective-merged grouped aggregates over a 1-D ("buckets",) mesh, and
    # the distributed index-build exchange. Default-off: with the master
    # switch false every path compiles the single-logical-device programs.
    PARALLEL_ENABLED = "hyperspace.parallel.enabled"
    PARALLEL_MESH_DEVICES = "hyperspace.parallel.mesh.devices"
    PARALLEL_MIN_ROWS = "hyperspace.parallel.minRows"
    PARALLEL_BUILD_ENABLED = "hyperspace.parallel.build.enabled"
    # Out-of-core execution (round-5): thresholds routing large operators
    # onto the streaming paths so no operator materializes a full table
    # (the reference inherits this from Spark's streaming executors).
    EXEC_STREAM_JOIN_MIN_BYTES = "hyperspace.exec.stream.joinMinBytes"
    EXEC_STREAM_AGG_MIN_BYTES = "hyperspace.exec.stream.aggMinBytes"
    EXEC_STREAM_CHUNK_BYTES = "hyperspace.exec.stream.chunkBytes"
    EXEC_JOIN_SPILL_MIN_ROWS = "hyperspace.exec.join.spillMinRows"
    # Streaming join engine (exec/join_stream.py + the pipelined bucketed
    # SMJ): broadcast-side size gate, shared-build-side LRU budget, and the
    # per-bucket prefetch master switch.
    EXEC_JOIN_BROADCAST_MAX_BYTES = "hyperspace.exec.join.broadcastMaxBytes"
    EXEC_JOIN_BUILD_CACHE_MAX_BYTES = "hyperspace.exec.join.buildCache.maxBytes"
    EXEC_JOIN_PIPELINE_ENABLED = "hyperspace.exec.join.pipeline.enabled"
    # Scan IO + pipelined streaming (hyperspace_tpu/exec/pipeline.py):
    # decode-pool width, chunk prefetch depth/budget, and row-group pruning.
    EXEC_IO_DECODE_THREADS = "hyperspace.exec.io.decodeThreads"
    EXEC_IO_ROWGROUP_PRUNING = "hyperspace.exec.io.rowGroupPruning"
    EXEC_IO_NATIVE_ENABLED = "hyperspace.exec.io.native.enabled"
    EXEC_IO_NATIVE_ROWGROUP = "hyperspace.exec.io.native.rowGroupDecode"
    EXEC_IO_NATIVE_MAX_DICT = "hyperspace.exec.io.native.maxDictEntries"
    EXEC_PIPELINE_ENABLED = "hyperspace.exec.pipeline.enabled"
    EXEC_PIPELINE_DEPTH = "hyperspace.exec.pipeline.depth"
    EXEC_PIPELINE_MAX_BUFFERED_BYTES = "hyperspace.exec.pipeline.maxBufferedBytes"
    # Device grouped aggregation (exec/device.py sort-based segment
    # reduction): master switch, host-spill cardinality bound, and the
    # smallest segment-capacity bucket.
    EXEC_AGG_DEVICE_GROUPED = "hyperspace.exec.agg.enabled"
    EXEC_AGG_MAX_GROUPS = "hyperspace.exec.agg.maxGroups"
    EXEC_AGG_CAPACITY_FLOOR = "hyperspace.exec.agg.capacityFloor"
    # Streaming device top-k (exec/topk.py): ORDER BY ... LIMIT k over a
    # chunked scan folds a device-resident candidate buffer instead of
    # materializing + host-sorting; master switch, the largest k served on
    # device, and the running-threshold row-group-pruning feedback toggle.
    EXEC_TOPK_ENABLED = "hyperspace.exec.topk.enabled"
    EXEC_TOPK_MAX_K = "hyperspace.exec.topk.maxK"
    EXEC_TOPK_THRESHOLD_PUSHDOWN = "hyperspace.exec.topk.thresholdPushdown"
    # Whole-plan fusion (exec/stage_ir.py): compile a chunk's
    # filter→project→fold chain into ONE jitted stage program per
    # (pipeline skeleton, shape bucket, mesh fingerprint), and donate the
    # streamed fold state so it updates in place instead of reallocating
    # every chunk.
    EXEC_FUSION_ENABLED = "hyperspace.exec.fusion.enabled"
    EXEC_FUSION_DONATION = "hyperspace.exec.fusion.donation"
    # Query-serving runtime (hyperspace_tpu/serving/): concurrent request
    # admission, compiled-plan caching, micro-batching, bucket prefetch.
    SERVING_QUEUE_DEPTH = "hyperspace.serving.queueDepth"
    SERVING_WORKERS = "hyperspace.serving.workers"
    SERVING_DEFAULT_TIMEOUT_SECONDS = "hyperspace.serving.defaultTimeoutSeconds"
    SERVING_PLAN_CACHE_ENABLED = "hyperspace.serving.planCache.enabled"
    SERVING_PLAN_CACHE_MAX_ENTRIES = "hyperspace.serving.planCache.maxEntries"
    SERVING_MICRO_BATCH_ENABLED = "hyperspace.serving.microBatch.enabled"
    SERVING_MICRO_BATCH_MAX_REQUESTS = "hyperspace.serving.microBatch.maxRequests"
    SERVING_MICRO_BATCH_MAX_WAIT_MS = "hyperspace.serving.microBatch.maxWaitMs"
    SERVING_BUCKET_CACHE_BYTES = "hyperspace.serving.bucketCache.bytes"
    SERVING_PREFETCH_ENABLED = "hyperspace.serving.prefetch.enabled"
    SERVING_PREFETCH_WORKERS = "hyperspace.serving.prefetch.workers"
    # Cost-aware scheduling (serving/scheduler.py): tenant-fair dispatch
    # ordered by predicted-cost class + deadline slack, predicted-work load
    # shedding, per-tenant token buckets, SLO-burn-driven priority.
    SERVING_SCHED_ENABLED = "hyperspace.serving.sched.enabled"
    SERVING_SCHED_INTERACTIVE_MS = "hyperspace.serving.sched.interactiveMs"
    SERVING_SCHED_HEAVY_MS = "hyperspace.serving.sched.heavyMs"
    SERVING_SCHED_MIN_CONFIDENCE = "hyperspace.serving.sched.minConfidence"
    SERVING_SCHED_MAX_QUEUED_SECONDS = "hyperspace.serving.sched.maxQueuedSeconds"
    SERVING_SCHED_TENANT_WEIGHTS = "hyperspace.serving.sched.tenantWeights"
    SERVING_SCHED_TENANT_RATE = "hyperspace.serving.sched.tenantRatePerSecond"
    SERVING_SCHED_TENANT_BURST = "hyperspace.serving.sched.tenantBurst"
    SERVING_SCHED_BURN_THRESHOLD = "hyperspace.serving.sched.burnBoostThreshold"
    SERVING_SCHED_BURN_FACTOR = "hyperspace.serving.sched.burnBoostFactor"
    # Semantic result cache (serving/result_cache.py): version-branded
    # byte-budgeted LRU above the plan cache (exact + subsumed-predicate hits).
    SERVING_RESULT_CACHE_ENABLED = "hyperspace.serving.resultCache.enabled"
    SERVING_RESULT_CACHE_BYTES = "hyperspace.serving.resultCache.bytes"
    SERVING_RESULT_CACHE_MAX_ENTRY_BYTES = "hyperspace.serving.resultCache.maxEntryBytes"
    SERVING_RESULT_CACHE_SUBSUMPTION = "hyperspace.serving.resultCache.subsumption"
    # Observability (hyperspace_tpu/obs/): span tracing, metrics registry,
    # query profiles. Tracing is opt-in; metrics are always-on (bumping a
    # counter is cheaper than checking whether to).
    OBS_TRACING_ENABLED = "hyperspace.obs.tracing.enabled"
    OBS_TRACE_MAX_SPANS = "hyperspace.obs.trace.maxSpans"
    OBS_METRICS_ENABLED = "hyperspace.obs.metrics.enabled"
    OBS_PROFILE_HISTORY = "hyperspace.obs.profile.history"
    OBS_PROFILE_WHY_NOT = "hyperspace.obs.profile.whyNot"
    # Query intelligence (obs/history.py, obs/slo.py, obs/export.py):
    # fingerprint-keyed profile history + cost estimates, the slow-query
    # flight recorder, latency-SLO burn-rate tracking, and the HTTP
    # telemetry endpoint.
    OBS_HISTORY_ENABLED = "hyperspace.obs.history.enabled"
    OBS_HISTORY_MAX_FINGERPRINTS = "hyperspace.obs.history.maxFingerprints"
    OBS_HISTORY_PERSIST = "hyperspace.obs.history.persist"
    OBS_SLOW_QUERY_MS = "hyperspace.obs.slowQueryMs"
    OBS_SLOW_QUERY_MAX_ENTRIES = "hyperspace.obs.slowQuery.maxEntries"
    OBS_SLOW_QUERY_DIR = "hyperspace.obs.slowQuery.dir"
    OBS_SLO_TARGET_MS = "hyperspace.obs.slo.targetMs"
    OBS_SLO_OBJECTIVE = "hyperspace.obs.slo.objective"
    OBS_SLO_WINDOWS_SECONDS = "hyperspace.obs.slo.windowsSeconds"
    OBS_HTTP_PORT = "hyperspace.obs.http.port"
    OBS_HTTP_HOST = "hyperspace.obs.http.host"
    # Distributed observability over the serving fabric (obs/spans.py +
    # fabric/frontdoor.py): trace-context propagation on routed requests,
    # cross-process span-tree stitching, and federation fan-out bounds.
    OBS_FABRIC_PROPAGATE = "hyperspace.obs.fabric.propagate"
    OBS_FABRIC_STITCH_ENABLED = "hyperspace.obs.fabric.stitch.enabled"
    OBS_FABRIC_STITCH_MAX_SPANS = "hyperspace.obs.fabric.stitch.maxSpans"
    OBS_FABRIC_STITCH_MAX_BYTES = "hyperspace.obs.fabric.stitch.maxBytes"
    OBS_FABRIC_FEDERATION_TIMEOUT_SECONDS = "hyperspace.obs.fabric.federationTimeoutSeconds"
    # Static-analysis / runtime-contract checks (hyperspace_tpu/check/):
    # HLO program-contract verification at program-cache-fill time, and the
    # lock-order watcher. Both default off — they are CI/diagnostic tools.
    CHECK_HLO_ENABLED = "hyperspace.check.hlo.enabled"
    CHECK_LOCKS = "hyperspace.check.locks"
    # Live-data lifecycle (hyperspace_tpu/lifecycle/): per-request snapshot
    # pinning, the background refresh manager, and the device lineage
    # anti-semi-join for hybrid-scan delete filtering.
    LIFECYCLE_SNAPSHOT_ENABLED = "hyperspace.lifecycle.snapshot.enabled"
    LIFECYCLE_REFRESH_ENABLED = "hyperspace.lifecycle.refresh.enabled"
    LIFECYCLE_REFRESH_INTERVAL_SECONDS = "hyperspace.lifecycle.refresh.intervalSeconds"
    LIFECYCLE_REFRESH_MODE = "hyperspace.lifecycle.refresh.mode"
    LIFECYCLE_DEVICE_LINEAGE_ENABLED = "hyperspace.lifecycle.deviceLineage.enabled"
    LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS = "hyperspace.lifecycle.deviceLineage.minRows"
    # Reliability subsystem (hyperspace_tpu/reliability/): deterministic
    # fault injection at the lake IO seams, deadline-aware retry of
    # transient IO errors, and the per-index quarantine circuit breaker.
    # ALL default-off: with these at defaults, behavior and plans are
    # byte-identical to a build without the subsystem.
    RELIABILITY_FAULTS_ENABLED = "hyperspace.reliability.faults.enabled"
    RELIABILITY_FAULTS_SPEC = "hyperspace.reliability.faults.spec"
    RELIABILITY_FAULTS_SEED = "hyperspace.reliability.faults.seed"
    RELIABILITY_RETRY_ENABLED = "hyperspace.reliability.retry.enabled"
    RELIABILITY_RETRY_MAX_ATTEMPTS = "hyperspace.reliability.retry.maxAttempts"
    RELIABILITY_RETRY_BASE_MS = "hyperspace.reliability.retry.baseMs"
    RELIABILITY_RETRY_CAP_MS = "hyperspace.reliability.retry.capMs"
    RELIABILITY_QUARANTINE_ENABLED = "hyperspace.reliability.quarantine.enabled"
    RELIABILITY_QUARANTINE_THRESHOLD = "hyperspace.reliability.quarantine.threshold"
    RELIABILITY_QUARANTINE_COOLDOWN_SECONDS = "hyperspace.reliability.quarantine.cooldownSeconds"
    # Scale-out serving fabric (hyperspace_tpu/fabric/): multi-process
    # serving over one lake, with the operation log as the coherence
    # transport — lake-persisted commit records, a CommitWatcher replaying
    # remote commits onto the local invalidation bus, and a coherence
    # sidecar sharing quarantine strikes and SLO/rate accounting.
    # ALL default-off: with these at defaults, plans, results, and metrics
    # are byte-identical to a single-process build (docs/scale-out.md).
    FABRIC_ENABLED = "hyperspace.fabric.enabled"
    FABRIC_NODE_ID = "hyperspace.fabric.nodeId"
    FABRIC_WATCHER_ENABLED = "hyperspace.fabric.watcher.enabled"
    FABRIC_POLL_INTERVAL_SECONDS = "hyperspace.fabric.watcher.pollIntervalSeconds"
    FABRIC_QUARANTINE_SHARED = "hyperspace.fabric.quarantine.shared"
    FABRIC_SLO_SHARED = "hyperspace.fabric.slo.shared"
    FABRIC_SLO_PUBLISH_INTERVAL_SECONDS = "hyperspace.fabric.slo.publishIntervalSeconds"
    # Fabric crash tolerance: lake-persisted refresh leases with fencing
    # tokens, health-aware FrontDoor failover, and fsck lake garbage
    # collection. ALL default-off on top of the fabric's own default-off.
    FABRIC_LEASE_ENABLED = "hyperspace.fabric.lease.enabled"
    FABRIC_LEASE_TTL_SECONDS = "hyperspace.fabric.lease.ttlSeconds"
    FABRIC_LEASE_RENEW_INTERVAL_SECONDS = "hyperspace.fabric.lease.renewIntervalSeconds"
    FABRIC_HEALTH_ENABLED = "hyperspace.fabric.health.enabled"
    FABRIC_HEALTH_FAILURE_THRESHOLD = "hyperspace.fabric.health.failureThreshold"
    FABRIC_HEALTH_PROBE_INTERVAL_SECONDS = "hyperspace.fabric.health.probeIntervalSeconds"
    FABRIC_HEALTH_HEARTBEAT_INTERVAL_SECONDS = "hyperspace.fabric.health.heartbeatIntervalSeconds"
    FABRIC_HEALTH_MISSED_BEATS = "hyperspace.fabric.health.missedBeats"
    FABRIC_HEALTH_MAX_COMMIT_LAG = "hyperspace.fabric.health.maxCommitLag"
    FABRIC_HEALTH_HEDGE_MS = "hyperspace.fabric.health.hedgeMs"
    FABRIC_FSCK_ENABLED = "hyperspace.fabric.fsck.enabled"
    FABRIC_FSCK_RETENTION_SECONDS = "hyperspace.fabric.fsck.retentionSeconds"
    FABRIC_FSCK_DEAD_NODE_SECONDS = "hyperspace.fabric.fsck.deadNodeSeconds"
    FABRIC_FSCK_INTERVAL_SECONDS = "hyperspace.fabric.fsck.intervalSeconds"


# Defaults (ref: HS/index/IndexConstants.scala — e.g. numBuckets default is
# spark.sql.shuffle.partitions' default of 200, hybrid-scan ratios 0.2/0.3,
# optimize threshold 256 MiB, cache TTL 300 s).
DEFAULTS: Dict[str, Any] = {
    keys.SYSTEM_PATH: None,  # resolved by PathResolver; must be set per session
    keys.NUM_BUCKETS: 200,
    keys.HYBRID_SCAN_ENABLED: False,
    keys.HYBRID_SCAN_MAX_DELETED_RATIO: 0.2,
    keys.HYBRID_SCAN_MAX_APPENDED_RATIO: 0.3,
    keys.FILTER_RULE_USE_BUCKET_SPEC: False,
    keys.NESTED_COLUMN_ENABLED: False,
    keys.CACHE_EXPIRY_SECONDS: 300,
    keys.LINEAGE_ENABLED: False,
    keys.OPTIMIZE_FILE_SIZE_THRESHOLD: 256 * 1024 * 1024,
    keys.SOURCE_BUILDERS: (
        "hyperspace_tpu.sources.default.DefaultFileBasedSourceBuilder,"
        "hyperspace_tpu.sources.delta.DeltaLakeSourceBuilder,"
        "hyperspace_tpu.sources.iceberg.IcebergSourceBuilder"
    ),
    keys.GLOBBING_PATTERN: None,
    keys.DATASKIPPING_TARGET_FILE_SIZE: 256 * 1024 * 1024,
    keys.EVENT_LOGGER_CLASS: None,
    keys.DISPLAY_MODE: "console",
    keys.HIGHLIGHT_BEGIN_TAG: "",
    keys.HIGHLIGHT_END_TAG: "",
    keys.TPU_ROWS_PER_SHARD_CAPACITY_FACTOR: 2.0,
    keys.TPU_MESH_AXIS: "buckets",
    # 2M-row chunks: large enough to saturate the device sort, small enough
    # that the one-chunk-deep build pipeline overlaps device<->host transfer
    # with parquet writes (measured ~1.4x over a single 4M-row shot on a
    # tunneled chip); each chunk adds one sorted run per bucket, which the
    # join path re-sorts lazily and optimizeIndex compacts
    keys.TPU_BUILD_BATCH_ROWS: 2_000_000,
    # When the session mesh spans >1 device, index builds with at least this
    # many rows run the distributed exchange (hash -> all_to_all -> per-device
    # sort) instead of the single-device program. 0 = always distributed on a
    # multi-device mesh; single-device meshes always use the fused one-chip
    # program regardless.
    keys.TPU_BUILD_DISTRIBUTED_MIN_ROWS: 0,
    # Mesh-sharded execution master switch. Off by default: behavior is
    # byte-identical to the single-device programs, and turning it on only
    # changes WHERE the same math runs (per-shard via shard_map, partials
    # merged with collectives). Requires a >1-device runtime to take effect.
    keys.PARALLEL_ENABLED: False,
    # 0 = span the whole local runtime; N > 0 = shard over the first N
    # devices (must not oversubscribe — make_mesh raises).
    keys.PARALLEL_MESH_DEVICES: 0,
    # Below this many rows a chunk is not worth sharding: per-shard padding
    # and the collective merge dominate. Gates the query-side sharded paths
    # only; the build gate stays hyperspace.tpu.build.distributedMinRows.
    keys.PARALLEL_MIN_ROWS: 1 << 16,
    # Subordinate switch for the distributed index build (bucketize -> one
    # all_to_all -> per-device sort); only consulted when parallel.enabled.
    keys.PARALLEL_BUILD_ENABLED: True,
    keys.TPU_QUERY_DEVICE_EXECUTION: True,
    # Below this many rows a host<->device round trip costs more than the
    # compute it offloads; the executor keeps small batches on host. Tune to 0
    # on co-located TPU hosts where the whole pipeline stays device-resident.
    keys.TPU_QUERY_DEVICE_MIN_ROWS: 1 << 25,
    # Inner-join pair expansion + numeric column gather on device (host
    # gathers only string/object columns); False reverts to the host
    # expansion for every column.
    keys.TPU_JOIN_DEVICE_MATERIALIZE: True,
    # Materialization placement is cost-based: the pair count is known from
    # the span program BEFORE any payload moves, and a device-materialized
    # join must download its whole output. Above this many estimated output
    # bytes the expansion runs on host (native C pair kernels) instead —
    # measured 282 s device vs ~25 s host for a 37.5M-pair join on a
    # network-tunneled chip, where the device->host link is the bottleneck.
    # Raise (or set very large) on directly-attached hosts.
    keys.TPU_JOIN_DEVICE_MATERIALIZE_MAX_BYTES: 256 * 1024 * 1024,
    # The device span program's transfers are also known before dispatch:
    # keys go up (8B/row/side) and the [lo, hi) matrices come down
    # (16B/left row). Above this estimated round-trip the host span walk
    # (np.searchsorted / native merge, zero transfer) wins; the 256 MiB
    # default matches the materialize budget so the whole join dispatch
    # shares one stance: "device round trips above ~256 MiB estimated
    # transfer default to host". NOTE: with the default deviceMinRows
    # (2^25 rows ≈ 768 MiB of span traffic) this makes the device-join
    # window EMPTY by default — device SMJ is opt-in: co-located hosts
    # lower deviceMinRows AND raise this budget together.
    keys.TPU_JOIN_DEVICE_SPAN_MAX_BYTES: 256 * 1024 * 1024,
    # Above this many estimated input bytes (sum of both sides' file sizes)
    # a compatible bucketed join streams bucket-by-bucket: peak host memory
    # becomes O(one bucket pair + output) instead of O(both sides + output).
    keys.EXEC_STREAM_JOIN_MIN_BYTES: 1 << 30,
    # Above this many estimated source bytes, aggregates over a scan chain
    # execute in file chunks with partial-aggregate merge (Spark's
    # partial/final aggregation split), bounding memory by chunk size +
    # group cardinality.
    keys.EXEC_STREAM_AGG_MIN_BYTES: 1 << 30,
    # Target bytes per streamed scan chunk (file groups round up to it).
    keys.EXEC_STREAM_CHUNK_BYTES: 256 * 1024 * 1024,
    # Above this many rows on a generic-join side, the hash merge runs
    # partitioned (grace-join style): both sides split by key hash and each
    # partition merges independently, bounding the merge intermediate.
    keys.EXEC_JOIN_SPILL_MIN_ROWS: 1 << 26,
    # When one join side's estimated input (sum of its leaf file sizes) fits
    # under this, that side builds ONCE as a device-resident sorted hash
    # table and the other side streams through it chunk-by-chunk — the
    # build-once/probe-streaming discipline that keeps dimension-table joins
    # off the materialize-both-sides path. 0 disables broadcast hash joins.
    keys.EXEC_JOIN_BROADCAST_MAX_BYTES: 64 * 1024 * 1024,
    # Byte budget of the shared build-side LRU (serving/build_cache.py):
    # micro-batched requests joining the same dimension table reuse one
    # built hash table instead of rebuilding per request. Entries key on
    # (scan signature, keys, data-version brand) and purge on brand
    # rotation, like the result cache.
    keys.EXEC_JOIN_BUILD_CACHE_MAX_BYTES: 256 * 1024 * 1024,
    # Route the streaming bucketed SMJ's per-bucket side decodes through the
    # prefetch pipeline (exec/pipeline.py): bucket b+1's two sides decode
    # while bucket b's spans compute, under the pipeline depth/byte budgets.
    # False restores the serial consumer-thread decode loop.
    keys.EXEC_JOIN_PIPELINE_ENABLED: True,
    # Width of the shared parquet decode pool (exec/io.py). Applied when a
    # Session is constructed; the HS_DECODE_THREADS env var overrides both.
    keys.EXEC_IO_DECODE_THREADS: 8,
    # Evaluate pushed-down scan predicates against parquet row-group min/max
    # statistics so definitely-non-matching row groups are never decoded
    # (three-valued, conservative — pruning never changes results).
    keys.EXEC_IO_ROWGROUP_PRUNING: True,
    # Native decode fast path (exec/io.py + native/hs_native.cc). `enabled`
    # gates all native decode (row-group fast path AND the per-file
    # native-first reader); `rowGroupDecode` gates just the parallel
    # row-group fast path that decodes straight into device-ready padded
    # buffers; `maxDictEntries` bounds the dictionary size under which
    # RLE_DICTIONARY string columns ship codes+dictionary to the device
    # instead of expanded values (0 disables dictionary shipping).
    keys.EXEC_IO_NATIVE_ENABLED: True,
    keys.EXEC_IO_NATIVE_ROWGROUP: True,
    keys.EXEC_IO_NATIVE_MAX_DICT: 4096,
    # Pipelined streamed scans (exec/pipeline.py): while the chain executes
    # over chunk k, up to `depth` later chunks decode on the pipeline pool
    # (and pre-stage their H2D transfer). depth=1 is classic double
    # buffering: one chunk in compute, one in decode.
    keys.EXEC_PIPELINE_ENABLED: True,
    keys.EXEC_PIPELINE_DEPTH: 2,
    # Byte cap on decoded-but-unconsumed prefetched chunks; prefetch stalls
    # above it (one chunk ahead is always allowed, or the pipeline would
    # degenerate to serial on a single oversized chunk).
    keys.EXEC_PIPELINE_MAX_BUFFERED_BYTES: 1 << 30,
    # Grouped aggregates over index/file scans run on device as one fused
    # predicate + sort-based segment-reduction program (exec/device.py);
    # False routes every group-by back to the host pandas path.
    keys.EXEC_AGG_DEVICE_GROUPED: True,
    # When the observed group cardinality exceeds this, the device grouped
    # path spills to the host hash-combine (pandas) path — segment capacity
    # (and the per-group output tables) stay bounded on device.
    keys.EXEC_AGG_MAX_GROUPS: 1 << 20,
    # Smallest `num_segments` capacity bucket; capacities grow geometrically
    # (powers of sqrt(2)) above it so arbitrary cardinalities land on a
    # handful of cached executables.
    keys.EXEC_AGG_CAPACITY_FLOOR: 256,
    # ORDER BY + LIMIT over multi-chunk scans executes as a streaming device
    # top-k (exec/topk.py): per-chunk select + device-resident candidate
    # merge, byte-identical to the host sort path. False routes back to
    # materialize + host lexsort.
    keys.EXEC_TOPK_ENABLED: True,
    # Largest LIMIT the device top-k path serves; beyond it the candidate
    # buffer would dominate chunk sizes and the host sort wins.
    keys.EXEC_TOPK_MAX_K: 4096,
    # Feed the running k-th-candidate key value back into parquet row-group
    # min/max pruning as a dynamic filter (only row groups that provably
    # cannot beat the current k-th candidate are skipped).
    keys.EXEC_TOPK_THRESHOLD_PUSHDOWN: True,
    # Whole-plan fusion: fold each streamed chunk with ONE jitted program
    # (chunk select + state merge in a single XLA executable) instead of the
    # per-family chunk-then-merge dispatch pair. Default off this release:
    # the per-family path stays the reference; flip on after soak. Results
    # are byte-identical either way (proved by the fusion test tier).
    keys.EXEC_FUSION_ENABLED: False,
    # With fusion on, pass the device-resident fold state via
    # `donate_argnums` so XLA reuses its buffers for the outputs (in-place
    # update, no per-chunk HBM realloc). Only consulted when fusion is
    # enabled; off = same fused program without donation.
    keys.EXEC_FUSION_DONATION: True,
    # Serving runtime. Queue depth bounds memory under overload: submits
    # beyond it are REJECTED (AdmissionRejected), never silently queued.
    keys.SERVING_QUEUE_DEPTH: 64,
    keys.SERVING_WORKERS: 4,
    # None = no deadline; floats are seconds from submit to result.
    keys.SERVING_DEFAULT_TIMEOUT_SECONDS: 30.0,
    keys.SERVING_PLAN_CACHE_ENABLED: True,
    keys.SERVING_PLAN_CACHE_MAX_ENTRIES: 256,
    keys.SERVING_MICRO_BATCH_ENABLED: True,
    keys.SERVING_MICRO_BATCH_MAX_REQUESTS: 16,
    # How long a worker lingers draining the queue to fill a batch; the
    # latency cost of coalescing is bounded by this.
    keys.SERVING_MICRO_BATCH_MAX_WAIT_MS: 2.0,
    keys.SERVING_BUCKET_CACHE_BYTES: 1 << 30,
    keys.SERVING_PREFETCH_ENABLED: True,
    keys.SERVING_PREFETCH_WORKERS: 2,
    # Cost-aware scheduler. Off by default: with both sched and resultCache
    # disabled the server is byte-for-byte the FIFO runtime above.
    keys.SERVING_SCHED_ENABLED: False,
    # Predicted-latency class cut points: under interactiveMs -> interactive,
    # over heavyMs -> heavy, between -> standard. Estimates whose confidence
    # is below minConfidence classify as "unknown" (scheduled after standard
    # but before heavy — unknown shapes must not starve, nor jump the line).
    keys.SERVING_SCHED_INTERACTIVE_MS: 50.0,
    keys.SERVING_SCHED_HEAVY_MS: 500.0,
    keys.SERVING_SCHED_MIN_CONFIDENCE: 0.3,
    # Shed when the confident predicted work already queued exceeds this many
    # seconds (0 = depth-only shedding, the FIFO discipline).
    keys.SERVING_SCHED_MAX_QUEUED_SECONDS: 0.0,
    # "tenantA=4,tenantB=1" weighted fair shares; unlisted tenants weigh 1.
    keys.SERVING_SCHED_TENANT_WEIGHTS: "",
    # Per-tenant token-bucket admission rate (requests/s); 0 = unlimited.
    keys.SERVING_SCHED_TENANT_RATE: 0.0,
    keys.SERVING_SCHED_TENANT_BURST: 32,
    # A tenant whose own SLO burn rate >= threshold gets its weight
    # multiplied by factor (recovery boost); a tenant hogging the most work
    # while ANOTHER tenant burns gets its weight divided by factor.
    keys.SERVING_SCHED_BURN_THRESHOLD: 2.0,
    keys.SERVING_SCHED_BURN_FACTOR: 2.0,
    # Semantic result cache. Off by default (see sched.enabled note).
    keys.SERVING_RESULT_CACHE_ENABLED: False,
    keys.SERVING_RESULT_CACHE_BYTES: 256 * 1024 * 1024,
    keys.SERVING_RESULT_CACHE_MAX_ENTRY_BYTES: 16 * 1024 * 1024,
    # Serve a request whose predicate provably implies a cached superset
    # predicate by re-filtering the cached batch.
    keys.SERVING_RESULT_CACHE_SUBSUMPTION: True,
    # Span tracing is opt-in: when off, each instrumentation point costs one
    # contextvar read (bench.py --obs-overhead pins the bar at <= 3%).
    keys.OBS_TRACING_ENABLED: False,
    # Per-trace span budget; beyond it the tree stops growing and the trace
    # reports droppedSpans (bounded memory under pathological plans).
    keys.OBS_TRACE_MAX_SPANS: 100_000,
    keys.OBS_METRICS_ENABLED: True,
    # How many per-request QueryProfiles a QueryServer retains.
    keys.OBS_PROFILE_HISTORY: 16,
    # Run the why-not analysis on traced queries (extra optimizer passes per
    # query — diagnostic sessions only).
    keys.OBS_PROFILE_WHY_NOT: False,
    # Fold every completed query into the fingerprint-keyed ProfileHistory
    # (streaming stats + cost estimates). O(1) per query, bounded memory —
    # on by default; tracing is NOT required (latency/rows fold regardless).
    keys.OBS_HISTORY_ENABLED: True,
    # LRU bound on distinct fingerprints retained by a history instance.
    keys.OBS_HISTORY_MAX_FINGERPRINTS: 512,
    # Append one JSON line per completed query to
    # <system.path>/_telemetry/profile_history.jsonl (the workload log the
    # index advisor replays). Off by default: it is per-query disk IO.
    keys.OBS_HISTORY_PERSIST: False,
    # Flight-record queries slower than this many milliseconds (and every
    # errored/rejected request). 0 disables the recorder entirely.
    keys.OBS_SLOW_QUERY_MS: 0.0,
    # Bound on the flight recorder's in-memory and on-disk rings.
    keys.OBS_SLOW_QUERY_MAX_ENTRIES: 32,
    # On-disk ring directory; None derives <system.path>/_telemetry/slow
    # when a system path is configured, "" keeps entries memory-only.
    keys.OBS_SLOW_QUERY_DIR: None,
    # Latency-SLO target per served request, in milliseconds; 0 disables
    # SLO tracking. Good/bad counters and burn-rate gauges are per-tenant.
    keys.OBS_SLO_TARGET_MS: 1000.0,
    # Fraction of requests that must meet the target (error budget = 1-x).
    keys.OBS_SLO_OBJECTIVE: 0.999,
    # Comma-separated burn-rate window lengths in seconds.
    keys.OBS_SLO_WINDOWS_SECONDS: "300,3600",
    # Port for the HTTP telemetry endpoint (/metrics, /statusz, /profilez)
    # a QueryServer starts alongside itself. None disables; 0 binds an
    # ephemeral port (read it from server.telemetry.port).
    keys.OBS_HTTP_PORT: None,
    keys.OBS_HTTP_HOST: "127.0.0.1",
    # Stamp a W3C traceparent header (plus the stitch budget header when
    # stitching is on) onto FrontDoor /query requests whenever the router is
    # tracing. Off => routed requests are byte-identical to a build without
    # distributed tracing.
    keys.OBS_FABRIC_PROPAGATE: True,
    # Ship the worker's serialized span tree back in the /query response so
    # the router can graft it into one end-to-end trace. Off by default:
    # it grows every traced response by up to stitch.maxBytes.
    keys.OBS_FABRIC_STITCH_ENABLED: False,
    # Bounds on the stitched payload a worker may return: spans survive
    # tree-prefix truncation up to maxSpans, and the JSON encoding degrades
    # to the root alone past maxBytes (droppedSpans/truncated stay visible).
    keys.OBS_FABRIC_STITCH_MAX_SPANS: 512,
    keys.OBS_FABRIC_STITCH_MAX_BYTES: 262_144,
    # Per-worker HTTP timeout for /profilez and /statusz federation sweeps.
    keys.OBS_FABRIC_FEDERATION_TIMEOUT_SECONDS: 30.0,
    # Verify every newly compiled device program against its registered
    # ProgramContract (collective budget + forbidden ops) and bump
    # hs_check_violations_total on breach. Costs one HLO text dump per
    # compile — compile-time only, nothing on the cached-execution path.
    # HS_CHECK_HLO=1 flips the default on for a whole process, so existing
    # suites can run under verification without touching their sessions.
    keys.CHECK_HLO_ENABLED: os.environ.get("HS_CHECK_HLO", "") not in ("", "0"),
    # Wrap named internal mutexes in the lock-order watcher (cross-thread
    # acquisition-order cycle detection). Construction-time flag: locks
    # created before a Session enabled it stay plain.
    keys.CHECK_LOCKS: False,
    # Pin a SnapshotHandle (index-log roster frozen at admission) per served
    # request, so a refresh committing mid-flight never changes a running
    # query's answer (docs/lifecycle.md).
    keys.LIFECYCLE_SNAPSHOT_ENABLED: True,
    # Run the background RefreshManager alongside serving; off by default —
    # refreshes are an explicit operational decision.
    keys.LIFECYCLE_REFRESH_ENABLED: False,
    # Seconds between RefreshManager drift polls.
    keys.LIFECYCLE_REFRESH_INTERVAL_SECONDS: 5.0,
    # Refresh mode the manager schedules: "auto" picks incremental when the
    # appended/deleted ratios exceed the hybrid-scan thresholds (the index
    # would stop qualifying for hybrid scan) and quick otherwise; or pin
    # "incremental" / "quick" / "full" explicitly.
    keys.LIFECYCLE_REFRESH_MODE: "auto",
    # Evaluate the hybrid-scan deleted-row filter (NOT IN over the lineage
    # column) as a fused device anti-semi-join instead of host set ops.
    keys.LIFECYCLE_DEVICE_LINEAGE_ENABLED: True,
    # Below this row count the host np.isin oracle wins (device dispatch
    # overhead); counted as hs_device_fallback_total{op="lineage"}.
    keys.LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS: 4096,
    # Fault injection. Off means the registry stays empty and every seam
    # costs one attribute read; the spec string installs seeded rules
    # ("site:kind[:glob=..][:nth=N][:p=F][:delay=S][:max=N]" joined by ";").
    keys.RELIABILITY_FAULTS_ENABLED: False,
    keys.RELIABILITY_FAULTS_SPEC: "",
    keys.RELIABILITY_FAULTS_SEED: 0,
    # Retry of transient IO errors with decorrelated-jitter backoff; never
    # sleeps past the serving request's admission deadline. Off by default:
    # a failing read surfaces immediately, exactly as before this subsystem.
    keys.RELIABILITY_RETRY_ENABLED: False,
    keys.RELIABILITY_RETRY_MAX_ATTEMPTS: 4,
    keys.RELIABILITY_RETRY_BASE_MS: 5.0,
    keys.RELIABILITY_RETRY_CAP_MS: 100.0,
    # Index quarantine circuit breaker: this many corrupt-data errors on one
    # index's files trip it out of planning (fallback to source scans) until
    # a half-open probe after the cooldown reads clean.
    keys.RELIABILITY_QUARANTINE_ENABLED: False,
    keys.RELIABILITY_QUARANTINE_THRESHOLD: 3,
    keys.RELIABILITY_QUARANTINE_COOLDOWN_SECONDS: 30.0,
    # Master fabric switch. Off: no commit records are written, no watcher
    # or sidecar thread starts, every hook is one conf read — single-process
    # behavior is byte-identical to a build without the subsystem.
    keys.FABRIC_ENABLED: False,
    # Stable identity stamped as the origin of this process's commit
    # records (self-commit dedupe) and its sidecar node file. Empty means
    # "<hostname>:<pid>", which is unique per process on one host.
    keys.FABRIC_NODE_ID: "",
    # Run the CommitWatcher thread when the fabric is on. A pure writer
    # process (refresh driver) can turn this off and only publish.
    keys.FABRIC_WATCHER_ENABLED: True,
    # Watcher poll interval — the cross-process staleness bound: a commit
    # in process A is replayed in process B within one interval.
    keys.FABRIC_POLL_INTERVAL_SECONDS: 0.25,
    # Merge remote quarantine strikes/trips from peers' commit records and
    # sidecar files, so one process's corrupt reads protect the others.
    keys.FABRIC_QUARANTINE_SHARED: True,
    # Publish/merge per-tenant SLO good/bad counts and token-bucket drains
    # through the sidecar, so burn rates and rate limits hold globally.
    keys.FABRIC_SLO_SHARED: True,
    # Seconds between sidecar publish/merge rounds.
    keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS: 1.0,
    # Lake-persisted refresh lease: when on (and the fabric is on), the
    # RefreshManager acquires a per-index lease before building, so exactly
    # one *process* refreshes an index, and the lease's fencing token is
    # verified at every operation-log write — a holder that paused past
    # expiry and was taken over fails its late commit instead of landing it.
    keys.FABRIC_LEASE_ENABLED: False,
    # How long an unrenewed lease stays exclusive; also the takeover bound
    # for a holder killed mid-refresh.
    keys.FABRIC_LEASE_TTL_SECONDS: 30.0,
    # Heartbeat renewal cadence while a refresh holds its lease. Keep well
    # under the TTL (a renewal extends the expiry by one full TTL).
    keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS: 10.0,
    # Health-aware FrontDoor membership: consecutive failures / missed
    # sidecar heartbeats / commit-seq staleness eject a worker from the
    # rendezvous set (tenants re-hash to survivors); a half-open probe
    # re-admits it. Also enables retry-on-next-candidate failover.
    keys.FABRIC_HEALTH_ENABLED: False,
    # Consecutive transport/transient failures before ejection.
    keys.FABRIC_HEALTH_FAILURE_THRESHOLD: 3,
    # Cooldown before an ejected worker gets one half-open probe request.
    keys.FABRIC_HEALTH_PROBE_INTERVAL_SECONDS: 5.0,
    # Expected sidecar heartbeat cadence (the ledger publish interval of
    # the workers being watched); beat age is judged against this.
    keys.FABRIC_HEALTH_HEARTBEAT_INTERVAL_SECONDS: 1.0,
    # A worker whose ledger heartbeat is older than this many intervals is
    # ejected as dead — the failover detection bound is 2 intervals.
    keys.FABRIC_HEALTH_MISSED_BEATS: 2,
    # Eject a worker whose /healthz last-applied commit_seq lags the fleet
    # max by more than this (a wedged watcher serves stale answers while
    # looking alive). 0 disables staleness ejection.
    keys.FABRIC_HEALTH_MAX_COMMIT_LAG: 0,
    # Hedged reads: if the primary worker hasn't answered within this many
    # milliseconds, mirror the (idempotent) query to the next rendezvous
    # candidate and take whichever answers first. 0 disables hedging.
    keys.FABRIC_HEALTH_HEDGE_MS: 0.0,
    # Run the fsck garbage collector (fabric/fsck.py) at session start and
    # then periodically: compacts old/torn commit records, superseded lease
    # tokens, expired leases, and dead-node ledgers.
    keys.FABRIC_FSCK_ENABLED: False,
    # Commit records older than this are compacted (the newest record per
    # index is always kept so watcher cursors stay monotonic).
    keys.FABRIC_FSCK_RETENTION_SECONDS: 3600.0,
    # Node ledgers silent for longer than this are removed.
    keys.FABRIC_FSCK_DEAD_NODE_SECONDS: 600.0,
    # Seconds between periodic fsck passes when enabled.
    keys.FABRIC_FSCK_INTERVAL_SECONDS: 300.0,
}

REFRESH_MODE_INCREMENTAL = "incremental"
REFRESH_MODE_FULL = "full"
REFRESH_MODE_QUICK = "quick"
REFRESH_MODES = (REFRESH_MODE_INCREMENTAL, REFRESH_MODE_FULL, REFRESH_MODE_QUICK)

OPTIMIZE_MODE_QUICK = "quick"
OPTIMIZE_MODE_FULL = "full"
OPTIMIZE_MODES = (OPTIMIZE_MODE_QUICK, OPTIMIZE_MODE_FULL)

# Operation-log layout constants (ref: HS/index/IndexConstants.scala:93-95).
HYPERSPACE_LOG_DIR = "_hyperspace_log"
INDEX_VERSION_DIR_PREFIX = "v__"
INDEXES_DIR = "indexes"

# Lineage column name (ref: HS/index/IndexConstants.scala:104).
DATA_FILE_NAME_ID = "_data_file_id"
# Default id for a file whose id is unknown (ref: HS/index/IndexConstants.scala:116).
UNKNOWN_FILE_ID = -1

# Index metadata property names (ref: HS/index/IndexConstants.scala:118-127).
LINEAGE_PROPERTY = "lineage"
HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY = "hasParquetAsSourceFormat"
HYPERSPACE_VERSION_PROPERTY = "hyperspaceVersion"
INDEX_LOG_VERSION_PROPERTY = "indexLogVersion"


def _coerce(value: Any, like: Any) -> Any:
    """Coerce a raw (possibly string) conf value to the type of the default."""
    if value is None or like is None:
        return value
    if isinstance(like, bool):
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes")
        return bool(value)
    if isinstance(like, int) and not isinstance(like, bool):
        return int(value)
    if isinstance(like, float):
        return float(value)
    return value


class HyperspaceConf:
    """A mutable string-keyed configuration with typed accessors.

    Mirrors HS/util/HyperspaceConf.scala:27-153: every accessor reads the raw
    key and falls back to the centralized default.
    """

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._conf: Dict[str, Any] = dict(overrides or {})

    def set(self, key: str, value: Any) -> "HyperspaceConf":
        self._conf[key] = value
        return self

    def unset(self, key: str) -> "HyperspaceConf":
        self._conf.pop(key, None)
        return self

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._conf:
            return _coerce(self._conf[key], DEFAULTS.get(key, default))
        if key in DEFAULTS:
            return DEFAULTS[key] if default is None else default
        return default

    def copy(self) -> "HyperspaceConf":
        return HyperspaceConf(dict(self._conf))

    # Typed accessors -------------------------------------------------------
    @property
    def system_path(self) -> Optional[str]:
        return self.get(keys.SYSTEM_PATH)

    @property
    def num_buckets(self) -> int:
        return int(self.get(keys.NUM_BUCKETS))

    @property
    def hybrid_scan_enabled(self) -> bool:
        return bool(self.get(keys.HYBRID_SCAN_ENABLED))

    @property
    def hybrid_scan_deleted_ratio_threshold(self) -> float:
        return float(self.get(keys.HYBRID_SCAN_MAX_DELETED_RATIO))

    @property
    def hybrid_scan_appended_ratio_threshold(self) -> float:
        return float(self.get(keys.HYBRID_SCAN_MAX_APPENDED_RATIO))

    @property
    def use_bucket_spec(self) -> bool:
        return bool(self.get(keys.FILTER_RULE_USE_BUCKET_SPEC))

    @property
    def nested_column_enabled(self) -> bool:
        return bool(self.get(keys.NESTED_COLUMN_ENABLED))

    @property
    def cache_expiry_seconds(self) -> int:
        return int(self.get(keys.CACHE_EXPIRY_SECONDS))

    @property
    def lineage_enabled(self) -> bool:
        return bool(self.get(keys.LINEAGE_ENABLED))

    @property
    def optimize_file_size_threshold(self) -> int:
        return int(self.get(keys.OPTIMIZE_FILE_SIZE_THRESHOLD))

    @property
    def source_builders(self) -> str:
        return str(self.get(keys.SOURCE_BUILDERS))

    @property
    def dataskipping_target_file_size(self) -> int:
        return int(self.get(keys.DATASKIPPING_TARGET_FILE_SIZE))

    @property
    def rebucket_capacity_factor(self) -> float:
        return float(self.get(keys.TPU_ROWS_PER_SHARD_CAPACITY_FACTOR))

    @property
    def mesh_axis(self) -> str:
        return str(self.get(keys.TPU_MESH_AXIS))

    @property
    def build_batch_rows(self) -> int:
        return int(self.get(keys.TPU_BUILD_BATCH_ROWS))

    @property
    def distributed_build_min_rows(self) -> int:
        return int(self.get(keys.TPU_BUILD_DISTRIBUTED_MIN_ROWS))

    @property
    def parallel_enabled(self) -> bool:
        return bool(self.get(keys.PARALLEL_ENABLED))

    @property
    def parallel_mesh_devices(self) -> int:
        return int(self.get(keys.PARALLEL_MESH_DEVICES))

    @property
    def parallel_min_rows(self) -> int:
        return int(self.get(keys.PARALLEL_MIN_ROWS))

    @property
    def parallel_build_enabled(self) -> bool:
        return bool(self.get(keys.PARALLEL_BUILD_ENABLED))

    @property
    def device_execution_enabled(self) -> bool:
        return bool(self.get(keys.TPU_QUERY_DEVICE_EXECUTION))

    @property
    def device_exec_min_rows(self) -> int:
        return int(self.get(keys.TPU_QUERY_DEVICE_MIN_ROWS))

    @property
    def join_device_materialize(self) -> bool:
        return bool(self.get(keys.TPU_JOIN_DEVICE_MATERIALIZE))

    @property
    def join_device_materialize_max_bytes(self) -> int:
        return int(self.get(keys.TPU_JOIN_DEVICE_MATERIALIZE_MAX_BYTES))

    @property
    def join_device_span_max_bytes(self) -> int:
        return int(self.get(keys.TPU_JOIN_DEVICE_SPAN_MAX_BYTES))

    @property
    def stream_join_min_bytes(self) -> int:
        return int(self.get(keys.EXEC_STREAM_JOIN_MIN_BYTES))

    @property
    def stream_agg_min_bytes(self) -> int:
        return int(self.get(keys.EXEC_STREAM_AGG_MIN_BYTES))

    @property
    def stream_chunk_bytes(self) -> int:
        return int(self.get(keys.EXEC_STREAM_CHUNK_BYTES))

    @property
    def join_spill_min_rows(self) -> int:
        return int(self.get(keys.EXEC_JOIN_SPILL_MIN_ROWS))

    @property
    def join_broadcast_max_bytes(self) -> int:
        return int(self.get(keys.EXEC_JOIN_BROADCAST_MAX_BYTES))

    @property
    def join_build_cache_max_bytes(self) -> int:
        return int(self.get(keys.EXEC_JOIN_BUILD_CACHE_MAX_BYTES))

    @property
    def join_pipeline_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_JOIN_PIPELINE_ENABLED))

    @property
    def io_decode_threads(self) -> int:
        return int(self.get(keys.EXEC_IO_DECODE_THREADS))

    @property
    def rowgroup_pruning_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_IO_ROWGROUP_PRUNING))

    @property
    def io_native_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_IO_NATIVE_ENABLED))

    @property
    def io_native_rowgroup(self) -> bool:
        return bool(self.get(keys.EXEC_IO_NATIVE_ROWGROUP))

    @property
    def io_native_max_dict_entries(self) -> int:
        return int(self.get(keys.EXEC_IO_NATIVE_MAX_DICT))

    @property
    def pipeline_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_PIPELINE_ENABLED))

    @property
    def pipeline_depth(self) -> int:
        return int(self.get(keys.EXEC_PIPELINE_DEPTH))

    @property
    def pipeline_max_buffered_bytes(self) -> int:
        return int(self.get(keys.EXEC_PIPELINE_MAX_BUFFERED_BYTES))

    @property
    def agg_device_grouped_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_AGG_DEVICE_GROUPED))

    @property
    def agg_max_groups(self) -> int:
        return int(self.get(keys.EXEC_AGG_MAX_GROUPS))

    @property
    def agg_capacity_floor(self) -> int:
        return int(self.get(keys.EXEC_AGG_CAPACITY_FLOOR))

    @property
    def topk_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_TOPK_ENABLED))

    @property
    def topk_max_k(self) -> int:
        return int(self.get(keys.EXEC_TOPK_MAX_K))

    @property
    def topk_threshold_pushdown(self) -> bool:
        return bool(self.get(keys.EXEC_TOPK_THRESHOLD_PUSHDOWN))

    @property
    def fusion_enabled(self) -> bool:
        return bool(self.get(keys.EXEC_FUSION_ENABLED))

    @property
    def fusion_donation(self) -> bool:
        return bool(self.get(keys.EXEC_FUSION_DONATION))

    # Serving runtime --------------------------------------------------------
    @property
    def serving_queue_depth(self) -> int:
        return int(self.get(keys.SERVING_QUEUE_DEPTH))

    @property
    def serving_workers(self) -> int:
        return int(self.get(keys.SERVING_WORKERS))

    @property
    def serving_default_timeout_seconds(self) -> Optional[float]:
        v = self.get(keys.SERVING_DEFAULT_TIMEOUT_SECONDS)
        return None if v is None else float(v)

    @property
    def serving_plan_cache_enabled(self) -> bool:
        return bool(self.get(keys.SERVING_PLAN_CACHE_ENABLED))

    @property
    def serving_plan_cache_max_entries(self) -> int:
        return int(self.get(keys.SERVING_PLAN_CACHE_MAX_ENTRIES))

    @property
    def serving_micro_batch_enabled(self) -> bool:
        return bool(self.get(keys.SERVING_MICRO_BATCH_ENABLED))

    @property
    def serving_micro_batch_max_requests(self) -> int:
        return int(self.get(keys.SERVING_MICRO_BATCH_MAX_REQUESTS))

    @property
    def serving_micro_batch_max_wait_ms(self) -> float:
        return float(self.get(keys.SERVING_MICRO_BATCH_MAX_WAIT_MS))

    @property
    def serving_bucket_cache_bytes(self) -> int:
        return int(self.get(keys.SERVING_BUCKET_CACHE_BYTES))

    @property
    def serving_prefetch_enabled(self) -> bool:
        return bool(self.get(keys.SERVING_PREFETCH_ENABLED))

    @property
    def serving_prefetch_workers(self) -> int:
        return int(self.get(keys.SERVING_PREFETCH_WORKERS))

    @property
    def serving_sched_enabled(self) -> bool:
        return bool(self.get(keys.SERVING_SCHED_ENABLED))

    @property
    def serving_sched_interactive_ms(self) -> float:
        return float(self.get(keys.SERVING_SCHED_INTERACTIVE_MS))

    @property
    def serving_sched_heavy_ms(self) -> float:
        return float(self.get(keys.SERVING_SCHED_HEAVY_MS))

    @property
    def serving_sched_min_confidence(self) -> float:
        return float(self.get(keys.SERVING_SCHED_MIN_CONFIDENCE))

    @property
    def serving_sched_max_queued_seconds(self) -> float:
        return float(self.get(keys.SERVING_SCHED_MAX_QUEUED_SECONDS))

    @property
    def serving_sched_tenant_weights(self) -> Dict[str, float]:
        raw = str(self.get(keys.SERVING_SCHED_TENANT_WEIGHTS) or "")
        out: Dict[str, float] = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition("=")
            try:
                out[name.strip()] = float(w)
            except ValueError:
                raise ValueError(
                    f"bad tenant weight {part!r} in {keys.SERVING_SCHED_TENANT_WEIGHTS}"
                ) from None
        return out

    @property
    def serving_sched_tenant_rate(self) -> float:
        return float(self.get(keys.SERVING_SCHED_TENANT_RATE))

    @property
    def serving_sched_tenant_burst(self) -> int:
        return int(self.get(keys.SERVING_SCHED_TENANT_BURST))

    @property
    def serving_sched_burn_threshold(self) -> float:
        return float(self.get(keys.SERVING_SCHED_BURN_THRESHOLD))

    @property
    def serving_sched_burn_factor(self) -> float:
        return float(self.get(keys.SERVING_SCHED_BURN_FACTOR))

    @property
    def serving_result_cache_enabled(self) -> bool:
        return bool(self.get(keys.SERVING_RESULT_CACHE_ENABLED))

    @property
    def serving_result_cache_bytes(self) -> int:
        return int(self.get(keys.SERVING_RESULT_CACHE_BYTES))

    @property
    def serving_result_cache_max_entry_bytes(self) -> int:
        return int(self.get(keys.SERVING_RESULT_CACHE_MAX_ENTRY_BYTES))

    @property
    def serving_result_cache_subsumption(self) -> bool:
        return bool(self.get(keys.SERVING_RESULT_CACHE_SUBSUMPTION))

    # Observability ----------------------------------------------------------
    @property
    def obs_tracing_enabled(self) -> bool:
        return bool(self.get(keys.OBS_TRACING_ENABLED))

    @property
    def obs_trace_max_spans(self) -> int:
        return int(self.get(keys.OBS_TRACE_MAX_SPANS))

    @property
    def obs_metrics_enabled(self) -> bool:
        return bool(self.get(keys.OBS_METRICS_ENABLED))

    @property
    def obs_profile_history(self) -> int:
        return int(self.get(keys.OBS_PROFILE_HISTORY))

    @property
    def obs_profile_why_not(self) -> bool:
        return bool(self.get(keys.OBS_PROFILE_WHY_NOT))

    @property
    def obs_history_enabled(self) -> bool:
        return bool(self.get(keys.OBS_HISTORY_ENABLED))

    @property
    def obs_history_max_fingerprints(self) -> int:
        return int(self.get(keys.OBS_HISTORY_MAX_FINGERPRINTS))

    @property
    def obs_history_persist(self) -> bool:
        return bool(self.get(keys.OBS_HISTORY_PERSIST))

    @property
    def obs_slow_query_ms(self) -> float:
        return float(self.get(keys.OBS_SLOW_QUERY_MS))

    @property
    def obs_slow_query_max_entries(self) -> int:
        return int(self.get(keys.OBS_SLOW_QUERY_MAX_ENTRIES))

    @property
    def obs_slow_query_dir(self) -> Optional[str]:
        v = self.get(keys.OBS_SLOW_QUERY_DIR)
        return None if v is None else str(v)

    @property
    def obs_slo_target_ms(self) -> float:
        return float(self.get(keys.OBS_SLO_TARGET_MS))

    @property
    def obs_slo_objective(self) -> float:
        return float(self.get(keys.OBS_SLO_OBJECTIVE))

    @property
    def obs_slo_windows_seconds(self) -> tuple:
        raw = str(self.get(keys.OBS_SLO_WINDOWS_SECONDS))
        out = []
        for part in raw.split(","):
            part = part.strip()
            if part:
                out.append(float(part))
        return tuple(out) or (300.0, 3600.0)

    @property
    def obs_http_port(self) -> Optional[int]:
        v = self.get(keys.OBS_HTTP_PORT)
        return None if v is None else int(v)

    @property
    def obs_http_host(self) -> str:
        return str(self.get(keys.OBS_HTTP_HOST))

    @property
    def obs_fabric_propagate(self) -> bool:
        return bool(self.get(keys.OBS_FABRIC_PROPAGATE))

    @property
    def obs_fabric_stitch_enabled(self) -> bool:
        return bool(self.get(keys.OBS_FABRIC_STITCH_ENABLED))

    @property
    def obs_fabric_stitch_max_spans(self) -> int:
        return int(self.get(keys.OBS_FABRIC_STITCH_MAX_SPANS))

    @property
    def obs_fabric_stitch_max_bytes(self) -> int:
        return int(self.get(keys.OBS_FABRIC_STITCH_MAX_BYTES))

    @property
    def obs_fabric_federation_timeout_seconds(self) -> float:
        return float(self.get(keys.OBS_FABRIC_FEDERATION_TIMEOUT_SECONDS))

    @property
    def check_hlo_enabled(self) -> bool:
        return bool(self.get(keys.CHECK_HLO_ENABLED))

    @property
    def check_locks_enabled(self) -> bool:
        return bool(self.get(keys.CHECK_LOCKS))

    @property
    def lifecycle_snapshot_enabled(self) -> bool:
        return bool(self.get(keys.LIFECYCLE_SNAPSHOT_ENABLED))

    @property
    def lifecycle_refresh_enabled(self) -> bool:
        return bool(self.get(keys.LIFECYCLE_REFRESH_ENABLED))

    @property
    def lifecycle_refresh_interval_seconds(self) -> float:
        return float(self.get(keys.LIFECYCLE_REFRESH_INTERVAL_SECONDS))

    @property
    def lifecycle_refresh_mode(self) -> str:
        return str(self.get(keys.LIFECYCLE_REFRESH_MODE)).lower()

    @property
    def lifecycle_device_lineage_enabled(self) -> bool:
        return bool(self.get(keys.LIFECYCLE_DEVICE_LINEAGE_ENABLED))

    @property
    def lifecycle_device_lineage_min_rows(self) -> int:
        return int(self.get(keys.LIFECYCLE_DEVICE_LINEAGE_MIN_ROWS))

    @property
    def reliability_faults_enabled(self) -> bool:
        return bool(self.get(keys.RELIABILITY_FAULTS_ENABLED))

    @property
    def reliability_faults_spec(self) -> str:
        return str(self.get(keys.RELIABILITY_FAULTS_SPEC) or "")

    @property
    def reliability_faults_seed(self) -> int:
        return int(self.get(keys.RELIABILITY_FAULTS_SEED))

    @property
    def reliability_retry_enabled(self) -> bool:
        return bool(self.get(keys.RELIABILITY_RETRY_ENABLED))

    @property
    def reliability_retry_max_attempts(self) -> int:
        return int(self.get(keys.RELIABILITY_RETRY_MAX_ATTEMPTS))

    @property
    def reliability_retry_base_ms(self) -> float:
        return float(self.get(keys.RELIABILITY_RETRY_BASE_MS))

    @property
    def reliability_retry_cap_ms(self) -> float:
        return float(self.get(keys.RELIABILITY_RETRY_CAP_MS))

    @property
    def reliability_quarantine_enabled(self) -> bool:
        return bool(self.get(keys.RELIABILITY_QUARANTINE_ENABLED))

    @property
    def reliability_quarantine_threshold(self) -> int:
        return int(self.get(keys.RELIABILITY_QUARANTINE_THRESHOLD))

    @property
    def reliability_quarantine_cooldown_seconds(self) -> float:
        return float(self.get(keys.RELIABILITY_QUARANTINE_COOLDOWN_SECONDS))

    @property
    def fabric_enabled(self) -> bool:
        return bool(self.get(keys.FABRIC_ENABLED))

    @property
    def fabric_node_id(self) -> str:
        return str(self.get(keys.FABRIC_NODE_ID) or "")

    @property
    def fabric_watcher_enabled(self) -> bool:
        return bool(self.get(keys.FABRIC_WATCHER_ENABLED))

    @property
    def fabric_poll_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_POLL_INTERVAL_SECONDS))

    @property
    def fabric_quarantine_shared(self) -> bool:
        return bool(self.get(keys.FABRIC_QUARANTINE_SHARED))

    @property
    def fabric_slo_shared(self) -> bool:
        return bool(self.get(keys.FABRIC_SLO_SHARED))

    @property
    def fabric_slo_publish_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_SLO_PUBLISH_INTERVAL_SECONDS))

    @property
    def fabric_lease_enabled(self) -> bool:
        return bool(self.get(keys.FABRIC_LEASE_ENABLED))

    @property
    def fabric_lease_ttl_seconds(self) -> float:
        return float(self.get(keys.FABRIC_LEASE_TTL_SECONDS))

    @property
    def fabric_lease_renew_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_LEASE_RENEW_INTERVAL_SECONDS))

    @property
    def fabric_health_enabled(self) -> bool:
        return bool(self.get(keys.FABRIC_HEALTH_ENABLED))

    @property
    def fabric_health_failure_threshold(self) -> int:
        return int(self.get(keys.FABRIC_HEALTH_FAILURE_THRESHOLD))

    @property
    def fabric_health_probe_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_HEALTH_PROBE_INTERVAL_SECONDS))

    @property
    def fabric_health_heartbeat_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_HEALTH_HEARTBEAT_INTERVAL_SECONDS))

    @property
    def fabric_health_missed_beats(self) -> int:
        return int(self.get(keys.FABRIC_HEALTH_MISSED_BEATS))

    @property
    def fabric_health_max_commit_lag(self) -> int:
        return int(self.get(keys.FABRIC_HEALTH_MAX_COMMIT_LAG))

    @property
    def fabric_health_hedge_ms(self) -> float:
        return float(self.get(keys.FABRIC_HEALTH_HEDGE_MS))

    @property
    def fabric_fsck_enabled(self) -> bool:
        return bool(self.get(keys.FABRIC_FSCK_ENABLED))

    @property
    def fabric_fsck_retention_seconds(self) -> float:
        return float(self.get(keys.FABRIC_FSCK_RETENTION_SECONDS))

    @property
    def fabric_fsck_dead_node_seconds(self) -> float:
        return float(self.get(keys.FABRIC_FSCK_DEAD_NODE_SECONDS))

    @property
    def fabric_fsck_interval_seconds(self) -> float:
        return float(self.get(keys.FABRIC_FSCK_INTERVAL_SECONDS))

    def deltas(self) -> Dict[str, Any]:
        """Explicitly-set keys whose value differs from the centralized
        default — the "what is non-standard about this session" record the
        flight recorder stamps on every captured query."""
        out: Dict[str, Any] = {}
        for k, v in self._conf.items():
            default = DEFAULTS.get(k)
            if _coerce(v, default) != default:
                out[k] = v
        return out

    def __repr__(self) -> str:
        return f"HyperspaceConf({self._conf!r})"
