"""Fingerprint-keyed query intelligence: profile history + slow-query capture.

Two stores, both bounded, both thread-safe, both fed by completion hooks
(``QueryServer._seal``, ``DataFrame.collect``'s traced path):

- :class:`ProfileHistory` folds every completed query into **streaming
  per-fingerprint statistics** — count, error count, EMA, and P² quantile
  sketches for latency / rows / bytes / compiles. Memory is O(fingerprints
  retained), never O(queries served): a fold updates a handful of floats.
  :meth:`ProfileHistory.estimate_cost` is the learned per-fingerprint cost
  model ROADMAP item 4's SLO-aware scheduler consumes (predicted latency,
  confidence, sample count). Optional JSONL persistence appends one compact
  line per query so a restarted process (or the index advisor's what-if
  replay) can rebuild the history with :func:`load_history`.

- :class:`FlightRecorder` captures *outlier* queries whole: anything slower
  than ``hyperspace.obs.slowQueryMs`` (or ending in error/rejection) keeps
  its full span tree, profile, plan text, dispatch summary, and the conf
  deltas active at capture time, in a bounded in-memory ring mirrored to a
  bounded on-disk ring. Each entry exports its own Chrome trace for Perfetto
  triage.

The P² sketch (Jain & Chlamtac 1985) estimates a quantile online with five
markers — no sample buffer, so a million-query fingerprint costs the same 40
floats as a twenty-query one.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from hyperspace_tpu.check.locks import named_lock

__all__ = [
    "P2Quantile",
    "StreamStat",
    "CostEstimate",
    "ProfileHistory",
    "FlightEntry",
    "FlightRecorder",
    "load_history",
    "merge_history_snapshots",
]


class P2Quantile:
    """Streaming quantile estimate via the P² algorithm (five markers).

    Exact for the first five observations (it sorts them); afterwards the
    marker heights converge to the requested quantile without retaining
    samples. Not locked — callers hold the owning entry's lock.
    """

    __slots__ = ("p", "_n", "_q", "_pos", "_want")

    def __init__(self, p: float):
        self.p = float(p)
        self._n = 0
        self._q: List[float] = []  # marker heights
        self._pos: List[float] = []  # marker positions (1-based)
        self._want: List[float] = []  # desired positions

    def add(self, x: float) -> None:
        x = float(x)
        if self._n < 5:
            self._q.append(x)
            self._n += 1
            if self._n == 5:
                self._q.sort()
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                p = self.p
                self._want = [1.0, 1 + 2 * p, 1 + 4 * p, 3 + 2 * p, 5.0]
            return
        q, pos = self._q, self._pos
        # locate the cell and stretch the extreme markers
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and x >= q[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        self._n += 1
        p = self.p
        self._want = [1.0, 1 + 2 * p * (self._n - 1) / 4.0, 1 + p * (self._n - 1),
                      1 + (1 + p) * (self._n - 1) / 2.0, float(self._n)]
        # adjust interior markers toward their desired positions
        for i in range(1, 4):
            d = self._want[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (d <= -1.0 and pos[i - 1] - pos[i] < -1.0):
                d = 1.0 if d > 0 else -1.0
                qp = self._parabolic(i, d)
                if q[i - 1] < qp < q[i + 1]:
                    q[i] = qp
                else:  # parabolic estimate escaped the bracket: linear
                    q[i] = q[i] + d * (q[i + int(d)] - q[i]) / (pos[i + int(d)] - pos[i])
                pos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        q, n = self._q, self._pos
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    @property
    def value(self) -> Optional[float]:
        if self._n == 0:
            return None
        if self._n < 5:
            vals = sorted(self._q)
            idx = min(len(vals) - 1, max(0, int(round(self.p * (len(vals) - 1)))))
            return vals[idx]
        return self._q[2]


class StreamStat:
    """Bounded-memory summary of one metric stream: count, mean, EMA,
    min/max, and P² sketches for the median and tail."""

    __slots__ = ("n", "mean", "ema", "alpha", "min", "max", "_p50", "_p95")

    def __init__(self, alpha: float = 0.2):
        self.n = 0
        self.mean = 0.0
        self.ema: Optional[float] = None
        self.alpha = float(alpha)
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._p50 = P2Quantile(0.5)
        self._p95 = P2Quantile(0.95)

    def add(self, x: float) -> None:
        x = float(x)
        self.n += 1
        self.mean += (x - self.mean) / self.n
        self.ema = x if self.ema is None else self.alpha * x + (1 - self.alpha) * self.ema
        self.min = x if self.min is None else min(self.min, x)
        self.max = x if self.max is None else max(self.max, x)
        self._p50.add(x)
        self._p95.add(x)

    @property
    def p50(self) -> Optional[float]:
        return self._p50.value

    @property
    def p95(self) -> Optional[float]:
        return self._p95.value

    def to_json(self) -> Dict[str, Any]:
        return {
            "n": self.n,
            "mean": self.mean,
            "ema": self.ema,
            "min": self.min,
            "max": self.max,
            "p50": self.p50,
            "p95": self.p95,
        }


@dataclass
class CostEstimate:
    """``ProfileHistory.estimate_cost`` result: the scheduler contract.

    ``latency_s`` is the predicted wall time for the next query with this
    fingerprint; ``confidence`` in [0, 1] grows with sample count and falls
    with observed dispersion (a fingerprint whose latencies span 100x gets a
    low-confidence median, and a cost-based scheduler should treat it as
    "unknown, assume heavy")."""

    latency_s: float
    confidence: float
    samples: int

    def to_json(self) -> Dict[str, Any]:
        return {"latencySeconds": self.latency_s, "confidence": self.confidence, "samples": self.samples}


class _FingerprintStats:
    __slots__ = ("fingerprint", "query", "first_seen", "last_seen", "count",
                 "errors", "latency", "rows", "bytes", "compiles", "lock")

    def __init__(self, fingerprint: str, alpha: float):
        self.fingerprint = fingerprint
        self.query = ""
        self.first_seen = time.time()
        self.last_seen = self.first_seen
        self.count = 0
        self.errors = 0
        self.latency = StreamStat(alpha)
        self.rows = StreamStat(alpha)
        self.bytes = StreamStat(alpha)
        self.compiles = StreamStat(alpha)
        self.lock = named_lock("obs.profileHistory.entry")

    def to_json(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "fingerprint": self.fingerprint,
                "query": self.query,
                "firstSeen": self.first_seen,
                "lastSeen": self.last_seen,
                "count": self.count,
                "errors": self.errors,
                "latencySeconds": self.latency.to_json(),
                "rows": self.rows.to_json(),
                "bytes": self.bytes.to_json(),
                "compiles": self.compiles.to_json(),
            }


class ProfileHistory:
    """Thread-safe, LRU-bounded map: fingerprint -> streaming statistics.

    ``registry=`` publishes a callback gauge (``hs_profile_history_fingerprints``)
    plus a fold counter; ``persist_path=`` appends one JSON line per recorded
    query (the workload log the index advisor replays).
    """

    def __init__(
        self,
        max_fingerprints: int = 512,
        ema_alpha: float = 0.2,
        persist_path: Optional[str] = None,
        registry=None,
        server: str = "",
    ):
        self._lock = named_lock("obs.profileHistory")
        self._entries: "OrderedDict[str, _FingerprintStats]" = OrderedDict()
        self.max_fingerprints = max(1, int(max_fingerprints))
        self.ema_alpha = float(ema_alpha)
        self.evicted = 0
        self._persist_path = persist_path
        self._persist_lock = named_lock("obs.profileHistory.persist")
        self._persist_f = None
        self._recorded = None
        if persist_path:
            os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
            self._persist_f = open(persist_path, "a", buffering=1)  # line-buffered
        if registry is not None:
            labels = {"server": server} if server else {}
            registry.gauge(
                "hs_profile_history_fingerprints",
                "distinct query fingerprints with streaming statistics",
                fn=lambda: len(self._entries),
                **labels,
            )
            self._recorded = registry.counter(
                "hs_profile_history_folds_total",
                "completed queries folded into the profile history",
                **labels,
            )

    def __len__(self) -> int:
        return len(self._entries)

    def _entry(self, fingerprint: str) -> _FingerprintStats:
        with self._lock:
            e = self._entries.get(fingerprint)
            if e is None:
                e = _FingerprintStats(fingerprint, self.ema_alpha)
                self._entries[fingerprint] = e
                while len(self._entries) > self.max_fingerprints:
                    self._entries.popitem(last=False)
                    self.evicted += 1
            else:
                self._entries.move_to_end(fingerprint)
            return e

    def record(
        self,
        fingerprint: str,
        latency_s: float,
        rows: Optional[int] = None,
        bytes: Optional[int] = None,
        compiles: Optional[int] = None,
        error: bool = False,
        query: str = "",
    ) -> None:
        """Fold one completed query. O(1); errors contribute to the error
        count but NOT the latency sketch (a fast failure must not teach the
        cost model that the fingerprint is cheap)."""
        e = self._entry(fingerprint)
        with e.lock:
            e.count += 1
            e.last_seen = time.time()
            if query and not e.query:
                e.query = query[:200]
            if error:
                e.errors += 1
            else:
                e.latency.add(latency_s)
                if rows is not None:
                    e.rows.add(rows)
                if bytes is not None:
                    e.bytes.add(bytes)
                if compiles is not None:
                    e.compiles.add(compiles)
        if self._recorded is not None:
            self._recorded.inc()
        if self._persist_f is not None:
            line = json.dumps(
                {
                    "ts": round(time.time(), 3),
                    "fp": fingerprint,
                    "latencySeconds": round(float(latency_s), 6),
                    "rows": rows,
                    "bytes": bytes,
                    "compiles": compiles,
                    "error": bool(error),
                    **({"query": query[:200]} if query and e.count == 1 else {}),
                }
            )
            with self._persist_lock:
                if self._persist_f is not None:
                    self._persist_f.write(line + "\n")

    def record_profile(self, fingerprint: str, profile, latency_s: Optional[float] = None) -> None:
        """Fold a finished :class:`~hyperspace_tpu.obs.profile.QueryProfile`."""
        self.record(
            fingerprint,
            profile.duration_s if latency_s is None else latency_s,
            rows=profile.total("rows") or None,
            bytes=profile.total("bytes") or None,
            error=bool(profile.error),
            query=profile.query,
        )

    # -- the cost model ------------------------------------------------------
    def estimate_cost(self, fingerprint: str) -> Optional[CostEstimate]:
        """Predicted latency for the next query with this fingerprint.

        Prediction blends the P² median (stable under outliers) with the EMA
        (tracks drift: a fingerprint whose data doubled gets costlier);
        confidence = saturation(n/20) shrunk by relative dispersion
        (p95/p50). Returns None for an unseen fingerprint — "unknown" is the
        honest answer, not 0.0s.
        """
        with self._lock:
            e = self._entries.get(fingerprint)
        if e is None:
            return None
        with e.lock:
            n = e.latency.n
            if n == 0:
                return CostEstimate(0.0, 0.0, 0)
            p50 = e.latency.p50 or 0.0
            ema = e.latency.ema if e.latency.ema is not None else p50
            p95 = e.latency.p95 or p50
        predicted = 0.5 * p50 + 0.5 * ema
        saturation = min(1.0, n / 20.0)
        spread = (p95 / p50) if p50 > 0 else 1.0
        confidence = saturation / max(1.0, spread ** 0.5)
        return CostEstimate(predicted, min(1.0, confidence), n)

    # -- views ---------------------------------------------------------------
    def fingerprints(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def get(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._entries.get(fingerprint)
        return None if e is None else e.to_json()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able overview, most-recently-used last (the /profilez body)."""
        with self._lock:
            entries = list(self._entries.values())
        out = []
        for e in entries:
            j = e.to_json()
            est = self.estimate_cost(e.fingerprint)
            j["estimate"] = est.to_json() if est else None
            out.append(j)
        return {"fingerprints": len(out), "evicted": self.evicted, "entries": out}

    def close(self) -> None:
        with self._persist_lock:
            if self._persist_f is not None:
                try:
                    self._persist_f.close()
                finally:
                    self._persist_f = None


def load_history(path: str, **kwargs) -> ProfileHistory:
    """Rebuild a :class:`ProfileHistory` from a persisted JSONL workload log.
    Unparseable lines are skipped (a crash mid-write leaves at most one)."""
    h = ProfileHistory(**kwargs)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                h.record(
                    rec["fp"],
                    float(rec.get("latencySeconds", 0.0)),
                    rows=rec.get("rows"),
                    bytes=rec.get("bytes"),
                    compiles=rec.get("compiles"),
                    error=bool(rec.get("error")),
                    query=rec.get("query", ""),
                )
            except (ValueError, KeyError, TypeError):
                continue
    return h


# --------------------------------------------------------------------------
# Federation: approximate cross-process snapshot merging
# --------------------------------------------------------------------------

#: the StreamStat streams a snapshot entry carries, in to_json key form
_STREAM_KEYS = ("latencySeconds", "rows", "bytes", "compiles")


def _merge_stream(acc: Optional[Dict[str, Any]], add: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Combine two ``StreamStat.to_json`` dicts.

    Exact for ``n``/``min``/``max``; ``mean`` is the exact n-weighted
    combination. ``ema``/``p50``/``p95`` CANNOT be merged exactly from
    summaries (the samples are gone), so they combine as n-weighted
    averages of the per-node values — see ``merge_history_snapshots`` for
    the error model.
    """
    if not acc:
        return dict(add) if add else None
    if not add:
        return acc
    na, nb = int(acc.get("n", 0) or 0), int(add.get("n", 0) or 0)
    n = na + nb
    out: Dict[str, Any] = {"n": n}

    def _pick(key: str, fn):
        va, vb = acc.get(key), add.get(key)
        if va is None:
            return vb
        if vb is None:
            return va
        return fn(va, vb)

    def _weighted(va: float, vb: float) -> float:
        if n == 0:
            return 0.0
        return (float(va) * na + float(vb) * nb) / n

    out["mean"] = _pick("mean", _weighted)
    out["ema"] = _pick("ema", _weighted)
    out["min"] = _pick("min", min)
    out["max"] = _pick("max", max)
    out["p50"] = _pick("p50", _weighted)
    out["p95"] = _pick("p95", _weighted)
    return out


def merge_history_snapshots(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge several ``ProfileHistory.snapshot()`` bodies into one fleet
    view keyed by fingerprint — the FrontDoor's federated ``/profilez``.

    Error model (documented contract, tested): counts, error counts,
    first/last-seen, min/max are EXACT sums/extrema. Means are exact
    n-weighted combinations. Quantiles (p50/p95) and EMAs are
    **approximate**: each node contributes a P² estimate (itself an
    approximation that converges with samples), and the merge n-weights
    those point estimates. The combined quantile is exact when every node
    saw the same latency distribution; otherwise it lies within
    ``[min(node quantiles), max(node quantiles)]`` — the error is bounded
    by the cross-node spread, NOT by the true distribution's tails. Skewed
    fleets (one slow node) therefore show a merged p95 *below* the true
    fleet p95; per-worker drill-down (``/profilez`` on the worker) stays
    the exact source. Derived estimates are recomputed from the merged
    stats with the same blend ``estimate_cost`` uses.
    """
    by_fp: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    evicted = 0
    for snap in snapshots:
        if not snap:
            continue
        evicted += int(snap.get("evicted", 0) or 0)
        for entry in snap.get("entries") or []:
            fp = entry.get("fingerprint")
            if not fp:
                continue
            cur = by_fp.get(fp)
            if cur is None:
                cur = {
                    "fingerprint": fp,
                    "query": entry.get("query", ""),
                    "firstSeen": entry.get("firstSeen"),
                    "lastSeen": entry.get("lastSeen"),
                    "count": 0,
                    "errors": 0,
                }
                for key in _STREAM_KEYS:
                    cur[key] = None
                by_fp[fp] = cur
            if not cur["query"] and entry.get("query"):
                cur["query"] = entry["query"]
            fs, ls = entry.get("firstSeen"), entry.get("lastSeen")
            if fs is not None and (cur["firstSeen"] is None or fs < cur["firstSeen"]):
                cur["firstSeen"] = fs
            if ls is not None and (cur["lastSeen"] is None or ls > cur["lastSeen"]):
                cur["lastSeen"] = ls
            cur["count"] += int(entry.get("count", 0) or 0)
            cur["errors"] += int(entry.get("errors", 0) or 0)
            for key in _STREAM_KEYS:
                cur[key] = _merge_stream(cur[key], entry.get(key))
    entries = []
    for cur in by_fp.values():
        lat = cur.get("latencySeconds") or {}
        n = int(lat.get("n", 0) or 0)
        estimate = None
        if n > 0:
            p50 = float(lat.get("p50") or 0.0)
            ema = float(lat.get("ema") if lat.get("ema") is not None else p50)
            p95 = float(lat.get("p95") or p50)
            predicted = 0.5 * p50 + 0.5 * ema
            saturation = min(1.0, n / 20.0)
            spread = (p95 / p50) if p50 > 0 else 1.0
            estimate = {
                "latencySeconds": predicted,
                "confidence": min(1.0, saturation / max(1.0, spread ** 0.5)),
                "samples": n,
            }
        cur["estimate"] = estimate
        entries.append(cur)
    return {
        "fingerprints": len(entries),
        "evicted": evicted,
        "entries": entries,
        "federated": True,
    }


# --------------------------------------------------------------------------
# Slow-query flight recorder
# --------------------------------------------------------------------------


class FlightEntry:
    """One captured outlier query: profile + plan facts + environment."""

    __slots__ = ("ts", "reason", "latency_s", "fingerprint", "query", "tenant",
                 "profile", "plan_summary", "dispatch", "conf_deltas", "route",
                 "path")

    def __init__(self, reason: str, latency_s: float, fingerprint: str = "",
                 query: str = "", tenant: str = "", profile=None,
                 plan_summary: str = "", dispatch: str = "",
                 conf_deltas: Optional[Dict[str, Any]] = None,
                 route: Optional[Dict[str, Any]] = None):
        self.ts = time.time()
        self.reason = reason  # "slow" | "error" | "rejected"
        self.latency_s = float(latency_s)
        self.fingerprint = fingerprint
        self.query = query
        self.tenant = tenant
        self.profile = profile
        self.plan_summary = plan_summary
        self.dispatch = dispatch
        self.conf_deltas = dict(conf_deltas or {})
        # routed-request outcome (FrontDoor captures): failover retries,
        # whether a hedge fired, and the worker that answered
        self.route = dict(route) if route else None
        self.path: Optional[str] = None  # on-disk mirror, when enabled

    def chrome_trace(self) -> Optional[Dict[str, Any]]:
        return None if self.profile is None else self.profile.chrome_trace()

    def save_chrome_trace(self, path: str) -> Optional[str]:
        ct = self.chrome_trace()
        if ct is None:
            return None
        with open(path, "w") as f:
            json.dump(ct, f)
        return path

    def to_json(self) -> Dict[str, Any]:
        return {
            "ts": self.ts,
            "reason": self.reason,
            "latencySeconds": self.latency_s,
            "fingerprint": self.fingerprint,
            "query": self.query[:500],
            "tenant": self.tenant,
            "planSummary": self.plan_summary,
            "dispatch": self.dispatch,
            "confDeltas": {k: str(v) for k, v in self.conf_deltas.items()},
            "route": self.route,
            "profile": None if self.profile is None else self.profile.to_json(),
        }

    def __repr__(self) -> str:
        return f"FlightEntry({self.reason}, {self.latency_s * 1e3:.1f} ms, fp={self.fingerprint[:12]})"


class FlightRecorder:
    """Bounded ring of captured outlier queries, optionally mirrored to disk.

    The in-memory ring keeps live :class:`FlightEntry` objects (span trees
    included — triage without re-running). The on-disk ring, when a
    directory is configured, writes one self-contained JSON per entry
    (summary + full Chrome trace) and deletes the oldest beyond
    ``max_entries`` — a crashed process leaves its last outliers behind for
    the post-mortem.
    """

    def __init__(self, max_entries: int = 32, directory: Optional[str] = None,
                 registry=None, server: str = ""):
        self.max_entries = max(1, int(max_entries))
        self.directory = directory
        self._lock = named_lock("obs.flightRecorder")
        self._ring: "deque[FlightEntry]" = deque(maxlen=self.max_entries)
        self._seq = 0
        self._counter = None
        self._labels = {"server": server} if server else {}
        self._registry = registry
        if directory:
            os.makedirs(directory, exist_ok=True)

    def record(self, reason: str, latency_s: float, fingerprint: str = "",
               query: str = "", tenant: str = "", profile=None,
               conf_deltas: Optional[Dict[str, Any]] = None,
               route: Optional[Dict[str, Any]] = None) -> FlightEntry:
        plan_summary = ""
        dispatch = ""
        if profile is not None:
            plan_summary = profile.plan_summary
            from hyperspace_tpu.exec import trace as exec_trace

            dispatch = exec_trace.summarize_span_events(profile.root)
        entry = FlightEntry(
            reason, latency_s, fingerprint=fingerprint, query=query,
            tenant=tenant, profile=profile, plan_summary=plan_summary,
            dispatch=dispatch, conf_deltas=conf_deltas, route=route,
        )
        if self._registry is not None:
            self._registry.counter(
                "hs_slow_queries_total",
                "queries captured by the flight recorder, by reason",
                reason=reason, **self._labels,
            ).inc()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._ring.append(entry)
        if self.directory:
            self._write_disk(entry, seq)
        return entry

    def _write_disk(self, entry: FlightEntry, seq: int) -> None:
        try:
            body = entry.to_json()
            ct = entry.chrome_trace()
            if ct is not None:
                body["chromeTrace"] = ct
            path = os.path.join(self.directory, f"slow-{seq:08d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)
            entry.path = path
            # prune the on-disk ring beyond max_entries
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith("slow-") and n.endswith(".json")
            )
            for n in names[: max(0, len(names) - self.max_entries)]:
                try:
                    os.remove(os.path.join(self.directory, n))
                except OSError:
                    pass
        except OSError:
            pass  # disk mirror is best-effort; the in-memory ring is primary

    def last_slow_queries(self) -> List[FlightEntry]:
        """Captured entries, oldest first."""
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [e.to_json() for e in self.last_slow_queries()]
