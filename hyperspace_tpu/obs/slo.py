"""Latency SLO tracking: good/bad event counters + multi-window burn rates.

An SLO here is the standard serving formulation: a latency target
(``hyperspace.obs.slo.targetMs``) and an objective fraction
(``hyperspace.obs.slo.objective``, e.g. ``0.999`` = "99.9% of requests
finish under the target"). Every completed request is a *good* event
(finished under target, no error) or a *bad* event (slow, errored, or
rejected at admission).

The registry carries the cumulative truth (``hs_slo_good_total`` /
``hs_slo_bad_total``, labeled per server and tenant) — the shape Prometheus
alerting recomputes burn rates from at any window. For processes scraping
``/statusz`` (or no Prometheus at all), the tracker also maintains its own
multi-window **burn-rate gauges**: burn rate over window W = (bad fraction
in W) / (1 - objective), so 1.0 means "exactly consuming error budget at
the sustainable rate", 14.4 is the classic page-now threshold for a 1h
window on a 30d budget. Windowed state is a bounded per-tenant deque of
(monotonic time, good?) events — memory is O(window events retained), not
O(requests served).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from hyperspace_tpu.check.locks import named_lock

__all__ = ["SloTracker"]

#: per-tenant cap on retained windowed events; beyond it the oldest fall off
#: and long-window burn rates degrade gracefully toward the recent rate
_MAX_EVENTS = 8192


class _TenantState:
    __slots__ = ("good", "bad", "events", "lock", "local_good", "local_bad")

    def __init__(self):
        self.good = None  # registry counters, bound lazily
        self.bad = None
        self.events: "deque[Tuple[float, bool]]" = deque(maxlen=_MAX_EVENTS)
        self.lock = named_lock("obs.slo.tenant")
        # locally-recorded cumulative counts, excluding remote merges — the
        # ledger the fabric sidecar publishes (peers must never re-export
        # each other's events, or counts would snowball around the ring)
        self.local_good = 0
        self.local_bad = 0


class SloTracker:
    """Per-server latency-SLO accounting with per-tenant labels."""

    def __init__(
        self,
        target_ms: float,
        objective: float = 0.999,
        windows_s: Tuple[float, ...] = (300.0, 3600.0),
        registry=None,
        server: str = "",
        clock=time.monotonic,
    ):
        if not (0.0 < objective < 1.0):
            raise ValueError(f"SLO objective must be in (0, 1), got {objective}")
        self.target_s = float(target_ms) / 1000.0
        self.objective = float(objective)
        self.windows_s = tuple(float(w) for w in windows_s)
        self.registry = registry
        self.server = server
        self._clock = clock
        self._lock = named_lock("obs.slo")
        self._tenants: Dict[str, _TenantState] = {}

    def _tenant(self, tenant: str) -> _TenantState:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = _TenantState()
                self._tenants[tenant] = st
                if self.registry is not None:
                    labels = {"tenant": tenant}
                    if self.server:
                        labels["server"] = self.server
                    st.good = self.registry.counter(
                        "hs_slo_good_total", "requests meeting the latency SLO", **labels
                    )
                    st.bad = self.registry.counter(
                        "hs_slo_bad_total",
                        "requests violating the latency SLO (slow, errored, or rejected)",
                        **labels,
                    )
                    for w in self.windows_s:
                        self.registry.gauge(
                            "hs_slo_burn_rate",
                            "error-budget burn rate over the labeled window "
                            "(1.0 = budget consumed exactly at the sustainable rate)",
                            fn=(lambda t=tenant, ws=w: self.burn_rate(ws, tenant=t)),
                            window=f"{int(w)}s",
                            **labels,
                        )
            return st

    def record(self, latency_s: float, error: bool = False, tenant: str = "default") -> bool:
        """Account one completed (or rejected) request; returns whether it
        was a good event."""
        good = (not error) and (latency_s <= self.target_s)
        st = self._tenant(tenant)
        with st.lock:
            st.events.append((self._clock(), good))
            if good:
                st.local_good += 1
            else:
                st.local_bad += 1
        if st.good is not None:
            (st.good if good else st.bad).inc()
        return good

    # -- fabric coherence (hyperspace_tpu/fabric/coherence.py) ---------------
    def counts(self) -> Dict[str, Tuple[int, int]]:
        """Locally-recorded cumulative (good, bad) per tenant — the sidecar's
        publish ledger. Excludes events merged from peers."""
        with self._lock:
            tenants = dict(self._tenants)
        out: Dict[str, Tuple[int, int]] = {}
        for name, st in tenants.items():
            with st.lock:
                out[name] = (st.local_good, st.local_bad)
        return out

    def note_remote(self, tenant: str, good: int = 0, bad: int = 0) -> None:
        """Fold a peer process's good/bad event deltas into this tenant's
        burn-rate windows. Deliberately touches neither the registry
        counters (each process's ``hs_slo_*_total`` series stay its own
        cumulative truth — aggregation is the scrape layer's job) nor the
        local publish ledger (no echo)."""
        st = self._tenant(tenant)
        now = self._clock()
        with st.lock:
            st.events.extend([(now, True)] * max(0, int(good)))
            st.events.extend([(now, False)] * max(0, int(bad)))

    # -- windowed views ------------------------------------------------------
    def _window_counts(self, st: _TenantState, window_s: float) -> Tuple[int, int]:
        cutoff = self._clock() - window_s
        good = bad = 0
        with st.lock:
            for t, g in reversed(st.events):
                if t < cutoff:
                    break
                if g:
                    good += 1
                else:
                    bad += 1
        return good, bad

    def burn_rate(self, window_s: float, tenant: str = "default") -> float:
        """(bad fraction over the window) / (1 - objective); 0.0 when the
        window holds no events."""
        st = self._tenants.get(tenant)
        if st is None:
            return 0.0
        good, bad = self._window_counts(st, window_s)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - self.objective)

    def state(self) -> Dict[str, Any]:
        """JSON-able snapshot for ``/statusz``: cumulative + windowed, per
        tenant."""
        with self._lock:
            tenants = dict(self._tenants)
        out: Dict[str, Any] = {
            "targetMs": self.target_s * 1000.0,
            "objective": self.objective,
            "windowsSeconds": list(self.windows_s),
            "tenants": {},
        }
        for name, st in tenants.items():
            good = int(st.good.value) if st.good is not None else sum(1 for _, g in st.events if g)
            bad = int(st.bad.value) if st.bad is not None else sum(1 for _, g in st.events if not g)
            total = good + bad
            out["tenants"][name] = {
                "good": good,
                "bad": bad,
                "compliance": (good / total) if total else None,
                "burnRates": {
                    f"{int(w)}s": round(self.burn_rate(w, tenant=name), 4)
                    for w in self.windows_s
                },
            }
        return out
