"""QueryProfile: one query's span tree joined with plan-level facts.

A profile is the user-facing artifact of a traced query: the finished span
tree (timings, rows/bytes per operator, dispatch events), which indexes the
optimizer applied, a one-line plan summary, and — when
``hyperspace.obs.profile.whyNot`` is on — the why-not reasons for indexes
that were *not* applied. ``Session.last_query_profile()`` returns the most
recent one; ``QueryServer`` futures carry one per request.

``report()`` renders a readable indented tree; ``chrome_trace()`` /
``save_chrome_trace()`` export the Perfetto-loadable JSON.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from hyperspace_tpu.obs import spans as _spans

__all__ = ["QueryProfile", "build_profile"]

#: span attrs surfaced inline in the report, in display order
_REPORT_ATTRS = ("rows", "bytes", "files", "buckets", "index", "indexes", "rule", "error")


class QueryProfile:
    """Immutable-ish record of one executed query."""

    __slots__ = (
        "root",
        "query",
        "indexes_applied",
        "plan_summary",
        "why_not",
        "rule_timings",
        "error",
    )

    def __init__(
        self,
        root: _spans.Span,
        query: str = "",
        indexes_applied: Optional[List[str]] = None,
        plan_summary: str = "",
        why_not: Optional[str] = None,
        rule_timings: Optional[Dict[str, float]] = None,
        error: Optional[str] = None,
    ):
        self.root = root
        self.query = query
        self.indexes_applied = list(indexes_applied or [])
        self.plan_summary = plan_summary
        self.why_not = why_not
        self.rule_timings = dict(rule_timings or {})
        self.error = error

    # -- aggregates ----------------------------------------------------------
    @property
    def duration_s(self) -> float:
        return self.root.duration_s

    def stage_seconds(self) -> Dict[str, float]:
        """Total seconds per span name across the tree (a span's own time
        includes its children's — these are inclusive stage totals)."""
        out: Dict[str, float] = {}
        for sp in self.root.walk():
            out[sp.name] = out.get(sp.name, 0.0) + sp.duration_s
        return out

    def total(self, key: str) -> int:
        """Sum a numeric attr (``rows``, ``bytes``) over the whole tree."""
        acc = 0
        for sp in self.root.walk():
            v = sp.attrs.get(key)
            if isinstance(v, (int, float)):
                acc += int(v)
        return acc

    # -- renderings ----------------------------------------------------------
    def report(self, max_depth: Optional[int] = None) -> str:
        """Readable indented tree: durations in ms plus inline operator facts."""
        lines: List[str] = []
        head = f"Query profile: {self.duration_s * 1e3:.2f} ms"
        if self.error:
            head += f"  [error: {self.error}]"
        lines.append(head)
        if self.query:
            q = self.query if len(self.query) <= 200 else self.query[:197] + "..."
            lines.append(f"  query: {q}")
        if self.indexes_applied:
            lines.append(f"  indexes applied: {', '.join(self.indexes_applied)}")
        if self.plan_summary:
            lines.append(f"  plan: {self.plan_summary}")
        if self.rule_timings:
            ranked = sorted(self.rule_timings.items(), key=lambda kv: -kv[1])
            body = ", ".join(f"{r} {t * 1e3:.2f}ms" for r, t in ranked)
            lines.append(f"  rule timings: {body}")
        lines.append("  spans:")
        self._render(self.root, lines, depth=0, max_depth=max_depth)
        tr = self.root.trace
        if tr is not None and tr.dropped:
            lines.append(f"  ... {tr.dropped} span(s) dropped (budget {tr.max_spans})")
        if self.why_not:
            lines.append("  why-not:")
            for ln in self.why_not.splitlines():
                lines.append(f"    {ln}")
        return "\n".join(lines)

    def _render(self, sp: _spans.Span, lines: List[str], depth: int, max_depth: Optional[int]) -> None:
        if max_depth is not None and depth > max_depth:
            return
        pad = "    " + "  " * depth
        bits = [f"{sp.name} {sp.duration_s * 1e3:.2f} ms"]
        for k in _REPORT_ATTRS:
            if k in sp.attrs:
                bits.append(f"{k}={sp.attrs[k]}")
        for k, v in sp.attrs.items():
            if k not in _REPORT_ATTRS:
                bits.append(f"{k}={v}")
        if sp.events:
            bits.append(f"events={len(sp.events)}")
        lines.append(pad + "  ".join(str(b) for b in bits))
        for c in sp.children:
            self._render(c, lines, depth + 1, max_depth)

    def chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object (see ``obs.spans.to_chrome_trace``)."""
        return _spans.to_chrome_trace(self.root)

    def save_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def to_json(self) -> Dict[str, Any]:
        """Structured summary (not the full tree — use ``chrome_trace`` for
        that): durations per stage, totals, plan facts."""
        return {
            "durationSeconds": self.duration_s,
            "query": self.query,
            "indexesApplied": list(self.indexes_applied),
            "planSummary": self.plan_summary,
            "stageSeconds": self.stage_seconds(),
            "rows": self.total("rows"),
            "bytes": self.total("bytes"),
            "ruleTimingsSeconds": dict(self.rule_timings),
            "error": self.error,
            "spanCount": (self.root.trace.count if self.root.trace else None),
            "droppedSpans": (self.root.trace.dropped if self.root.trace else 0),
        }

    def __repr__(self) -> str:
        return (
            f"QueryProfile({self.duration_s * 1e3:.2f} ms, "
            f"indexes={self.indexes_applied!r}, spans={self.root.trace.count if self.root.trace else '?'})"
        )


def build_profile(root: _spans.Span, query: str = "", error: Optional[str] = None) -> QueryProfile:
    """Assemble a profile from a finished root span, pulling plan facts the
    instrumentation stashed as attrs (``indexes``, ``plan``, rule timings)."""
    root.finish()
    indexes: List[str] = []
    plan_summary = ""
    rule_timings: Dict[str, float] = {}
    for sp in root.walk():
        v = sp.attrs.get("indexes")
        if v:
            for name in v if isinstance(v, (list, tuple)) else [v]:
                if name not in indexes:
                    indexes.append(name)
        if not plan_summary and sp.attrs.get("plan"):
            plan_summary = str(sp.attrs["plan"])
        rt = sp.attrs.get("rule_timings")
        if isinstance(rt, dict):
            for r, t in rt.items():
                rule_timings[r] = rule_timings.get(r, 0.0) + float(t)
    return QueryProfile(
        root,
        query=query,
        indexes_applied=indexes,
        plan_summary=plan_summary,
        rule_timings=rule_timings,
        error=error,
    )
