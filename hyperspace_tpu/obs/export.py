"""HTTP telemetry endpoint: /metrics, /statusz, /profilez over stdlib http.

A tiny, dependency-free scrape surface beside :class:`QueryServer` (or any
process holding a registry):

- ``GET /metrics``  — Prometheus text exposition (format 0.0.4) of the
  bound registry, byte-identical to ``registry.prometheus_text()``;
- ``GET /statusz``  — JSON snapshot: serving stats, cache hit rates, SLO
  state, profile-history and flight-recorder summaries;
- ``GET /profilez`` — profile-history overview; ``?fingerprint=<hash>``
  drills into one fingerprint's streaming statistics + cost estimate.

Design stance: the endpoint is **read-only**, binds loopback by default, and
serves each request from a snapshot taken at request time — it holds no lock
while formatting. ``port=0`` binds an ephemeral port (the bound port is on
``.port``), which is also what keeps the tests sandbox/CI safe.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

__all__ = ["TelemetryEndpoint", "PROMETHEUS_CONTENT_TYPE"]

#: the content type Prometheus expects for text format 0.0.4
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class TelemetryEndpoint:
    """Threaded HTTP server publishing one registry + optional providers.

    ``status_fn`` returns the /statusz dict; ``history`` is a
    :class:`~hyperspace_tpu.obs.history.ProfileHistory`; ``flight`` a
    :class:`~hyperspace_tpu.obs.history.FlightRecorder`. All optional —
    absent providers make their sections/endpoints answer 404/empty rather
    than fail.
    """

    def __init__(
        self,
        registry,
        host: str = "127.0.0.1",
        port: int = 0,
        status_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        history=None,
        flight=None,
    ):
        self.registry = registry
        self.status_fn = status_fn
        self.history = history
        self.flight = flight
        self._requests = registry.counter(
            "hs_http_requests_total", "telemetry endpoint requests served", path="/metrics"
        )  # ensure the family exists before first scrape; per-path below
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            # one transient worker thread per request (ThreadingHTTPServer);
            # daemon so a live scrape never blocks interpreter exit
            daemon_threads = True

            def log_message(self, fmt, *args):  # no stderr chatter per scrape
                pass

            def do_GET(self):
                try:
                    endpoint._handle(self)
                except BrokenPipeError:
                    pass  # scraper went away mid-response
                except Exception as exc:  # defensive: never kill the server loop
                    try:
                        self.send_error(500, explain=str(exc))
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = self._server.server_address[0]
        self.port = int(self._server.server_address[1])
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TelemetryEndpoint":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"hs-telemetry-{self.port}",
                daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "TelemetryEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- request handling ----------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        self.registry.counter(
            "hs_http_requests_total", "telemetry endpoint requests served", path=path
        ).inc()
        if path == "/metrics":
            body = self.registry.prometheus_text().encode("utf-8")
            self._reply(req, 200, PROMETHEUS_CONTENT_TYPE, body)
        elif path == "/statusz":
            status = self.status_fn() if self.status_fn is not None else {}
            self._reply_json(req, 200, status)
        elif path == "/profilez":
            self._profilez(req, parse_qs(parsed.query))
        else:
            self._reply_json(
                req, 404,
                {"error": "not found", "endpoints": ["/metrics", "/statusz", "/profilez"]},
            )

    def _profilez(self, req: BaseHTTPRequestHandler, query: Dict[str, Any]) -> None:
        if self.history is None:
            self._reply_json(req, 404, {"error": "profile history disabled"})
            return
        fp = (query.get("fingerprint") or [None])[0]
        if fp is None:
            self._reply_json(req, 200, self.history.snapshot())
            return
        detail = self.history.get(fp)
        if detail is None:
            self._reply_json(req, 404, {"error": f"unknown fingerprint {fp!r}"})
            return
        est = self.history.estimate_cost(fp)
        detail["estimate"] = est.to_json() if est else None
        if self.flight is not None:
            detail["slowQueries"] = [
                e.to_json() for e in self.flight.last_slow_queries()
                if e.fingerprint == fp
            ]
        self._reply_json(req, 200, detail)

    @staticmethod
    def _reply(req: BaseHTTPRequestHandler, code: int, ctype: str, body: bytes) -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    @classmethod
    def _reply_json(cls, req: BaseHTTPRequestHandler, code: int, obj: Any) -> None:
        cls._reply(req, code, "application/json; charset=utf-8",
                   json.dumps(obj, default=str).encode("utf-8"))
