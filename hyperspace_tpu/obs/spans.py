"""Hierarchical span tracer: context-propagated timing trees per query.

The reference delegates runtime introspection to the Spark UI (SURVEY.md
§5.1); this framework owns its execution layer, so it owns the equivalent
surface too. A *trace* is one tree of :class:`Span`s covering a query's
lifecycle (parse -> resolve -> rewrite -> compile -> per-operator execute);
the *current* span is carried in a :mod:`contextvars` variable, so

- concurrent queries (``QueryServer`` workers, one request per context) get
  **disjoint** span trees — unlike ``exec/trace.py``'s process-global
  recording, which interleaves events from concurrent queries;
- helper threads (the parquet decode pool, prefetchers) join the submitting
  request's tree via :func:`wrap`/:func:`attach` instead of a global.

Overhead discipline: when no trace is active, :func:`span` performs ONE
contextvar read and returns a shared no-op context manager — no allocation,
no lock. That is what lets instrumentation points stay unconditionally in
the hot paths (bench.py ``--obs-overhead`` pins the bar).

Export: :func:`to_chrome_trace` renders a finished trace as Chrome
trace-event JSON (``{"traceEvents": [...]}``, complete ``"ph": "X"`` events)
loadable in Perfetto / ``chrome://tracing``.

Distributed traces (docs/observability.md "Distributed tracing"): a
:class:`TraceContext` is the W3C-traceparent-shaped identity that crosses
process boundaries — the FrontDoor stamps it on ``/query`` requests, the
worker binds it via :func:`bind_context` so its tree carries the router's
``trace_id``, and :func:`to_wire`/:func:`from_wire`/:func:`graft_remote`
move the worker's finished (bounded) span tree back into the router's tree
with per-process ``pid`` attribution.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "Span",
    "Trace",
    "TraceContext",
    "span",
    "trace",
    "start_trace",
    "current_span",
    "current_context",
    "bind_context",
    "parse_traceparent",
    "attach",
    "wrap",
    "add_manual",
    "to_wire",
    "from_wire",
    "graft_remote",
    "graft_span",
    "to_chrome_trace",
]

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "hs_obs_current_span", default=None
)

_context: "contextvars.ContextVar[Optional[TraceContext]]" = contextvars.ContextVar(
    "hs_obs_trace_context", default=None
)


class TraceContext:
    """W3C-traceparent-shaped trace identity that crosses process hops.

    ``trace_id`` (32 hex chars) names the end-to-end request; ``span_id``
    (16 hex chars) names the sender's active span, which the receiver
    records as its parent. ``sampled`` carries the sender's keep/drop
    decision so a worker never traces a request its router is not keeping.
    """

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    @classmethod
    def new(cls, sampled: bool = True) -> "TraceContext":
        return cls(os.urandom(16).hex(), os.urandom(8).hex(), sampled)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — what an attempt/hedge hop sends."""
        return TraceContext(self.trace_id, os.urandom(8).hex(), self.sampled)

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    def __repr__(self) -> str:
        return f"TraceContext({self.to_traceparent()})"


def parse_traceparent(header: Optional[str]) -> Optional["TraceContext"]:
    """Parse a ``traceparent`` header; None on anything malformed (an
    unparseable header must degrade to an untraced request, never a 500)."""
    if not header:
        return None
    parts = str(header).strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16 or len(version) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


def current_context() -> Optional[TraceContext]:
    """The context's active :class:`TraceContext` (None when untraced)."""
    return _context.get()


@contextlib.contextmanager
def bind_context(ctx: Optional[TraceContext]):
    """Make ``ctx`` the context's trace identity for the block; ``None`` is
    a no-op so callers can pass a maybe-absent context."""
    if ctx is None:
        yield None
        return
    token = _context.set(ctx)
    try:
        yield ctx
    finally:
        _context.reset(token)


class Trace:
    """Shared per-tree state: the span budget that bounds trace memory.

    ``count``/``dropped`` updates ride the GIL (int attribute bumps from
    worker threads may lose a tick under contention; the budget is a memory
    guard, not an invariant, and a lock here would tax every span).
    """

    __slots__ = ("max_spans", "count", "dropped")

    def __init__(self, max_spans: int):
        self.max_spans = int(max_spans)
        self.count = 1  # the root
        self.dropped = 0


class Span:
    """One timed node. ``t0``/``t1`` are ``time.perf_counter()`` readings;
    ``attrs`` carries operator facts (rows, bytes, index names); ``events``
    carries point annotations (the dispatch-trace kind/detail pairs)."""

    __slots__ = ("name", "cat", "t0", "t1", "attrs", "events", "children", "tid", "trace", "pid")

    def __init__(self, name: str, cat: str = "", trace: Optional[Trace] = None):
        self.name = name
        self.cat = cat
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.attrs: Dict[str, Any] = {}
        self.events: List = []
        self.children: List["Span"] = []
        self.tid = threading.get_ident()
        self.trace = trace
        # process attribution for stitched cross-process trees: None means
        # "this process"; grafted remote spans carry their origin's os pid
        self.pid: Optional[int] = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, kind: str, detail: str) -> None:
        """Point annotation (no duration) — the dispatch-trace shape."""
        self.events.append((kind, detail))

    def finish(self) -> "Span":
        if self.t1 is None:
            self.t1 = time.perf_counter()
        return self

    @property
    def duration_s(self) -> float:
        end = self.t1 if self.t1 is not None else time.perf_counter()
        return max(0.0, end - self.t0)

    def walk(self) -> Iterator["Span"]:
        yield self
        for c in list(self.children):
            yield from c.walk()

    def find(self, name: str) -> List["Span"]:
        return [s for s in self.walk() if s.name == name]

    def __repr__(self) -> str:
        return f"Span({self.name!r}, {self.duration_s * 1e3:.3f} ms, children={len(self.children)})"


class _NullSpan:
    """Shared do-nothing span handed out when no trace is active."""

    __slots__ = ()

    def set(self, **attrs) -> "_NullSpan":
        return self

    def event(self, kind: str, detail: str) -> None:
        pass


class _NullCM:
    __slots__ = ()

    def __enter__(self) -> _NullSpan:
        return NULL_SPAN

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()
_NULL_CM = _NullCM()


class _SpanCM:
    """Context manager creating a child of ``parent`` and making it current.

    Class-based (not a generator) so the disabled path stays allocation-free
    and the enabled path costs one object + one contextvar set/reset.
    """

    __slots__ = ("_parent", "_name", "_cat", "_attrs", "_span", "_token")

    def __init__(self, parent: Span, name: str, cat: str, attrs: Optional[dict]):
        self._parent = parent
        self._name = name
        self._cat = cat
        self._attrs = attrs
        self._span: Any = None
        self._token = None

    def __enter__(self):
        tr = self._parent.trace
        if tr is not None and tr.count >= tr.max_spans:
            # budget exhausted: keep timing the query via the existing spans,
            # just stop growing the tree (bounded memory under pathological
            # plans); droppage is visible on the trace for honesty
            tr.dropped += 1
            self._span = NULL_SPAN
            return NULL_SPAN
        if tr is not None:
            tr.count += 1
        sp = Span(self._name, self._cat, trace=tr)
        if self._attrs:
            sp.attrs.update(self._attrs)
        self._parent.children.append(sp)  # list.append: atomic under the GIL
        self._span = sp
        self._token = _current.set(sp)
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
            if exc_type is not None:
                self._span.attrs.setdefault("error", exc_type.__name__)
            self._span.finish()
        return False


def current_span() -> Optional[Span]:
    """The context's active span, or None when no trace is running here."""
    return _current.get()


def span(name: str, cat: str = "", **attrs):
    """Open a child span of the context's current span.

    When no trace is active this is the near-zero-overhead no-op path: one
    contextvar read, a shared null context manager back.
    """
    parent = _current.get()
    if parent is None:
        return _NULL_CM
    return _SpanCM(parent, name, cat, attrs or None)


_DEFAULT_MAX_SPANS = 100_000


def start_trace(name: str, cat: str = "query", max_spans: Optional[int] = None, **attrs) -> Span:
    """Create a detached root span (NOT made current) — for request objects
    whose lifecycle crosses threads (``QueryServer``): the submitting thread
    creates the root, each worker :func:`attach`-es it around its stage.
    Call ``root.finish()`` when the request completes."""
    root = Span(name, cat, trace=Trace(max_spans or _DEFAULT_MAX_SPANS))
    if attrs:
        root.attrs.update(attrs)
    return root


@contextlib.contextmanager
def trace(name: str, cat: str = "query", max_spans: Optional[int] = None, **attrs):
    """Root a new trace in this context for the duration of the block."""
    root = start_trace(name, cat, max_spans=max_spans, **attrs)
    token = _current.set(root)
    try:
        yield root
    finally:
        _current.reset(token)
        root.finish()


@contextlib.contextmanager
def attach(sp: Optional[Span]):
    """Make ``sp`` the context's current span (worker-thread propagation).
    ``attach(None)`` is a no-op, so callers can pass a maybe-absent root."""
    if sp is None:
        yield None
        return
    token = _current.set(sp)
    try:
        yield sp
    finally:
        _current.reset(token)


def wrap(fn):
    """Bind the *caller's* current span into ``fn`` so pool workers land
    their spans in the submitting request's tree. Identity when no trace is
    active (no wrapper allocation on the disabled path)."""
    parent = _current.get()
    if parent is None:
        return fn

    def inner(*args, **kwargs):
        token = _current.set(parent)
        try:
            return fn(*args, **kwargs)
        finally:
            _current.reset(token)

    return inner


def add_manual(parent: Span, name: str, cat: str, t0: float, t1: float, **attrs) -> Optional[Span]:
    """Append an already-timed child (perf_counter readings) to ``parent`` —
    for work executed once on behalf of several requests (shared-scan
    micro-batches), where each request's tree records its share after the
    fact. Returns None when the parent's span budget is exhausted."""
    tr = parent.trace
    if tr is not None:
        if tr.count >= tr.max_spans:
            tr.dropped += 1
            return None
        tr.count += 1
    sp = Span(name, cat, trace=tr)
    sp.t0, sp.t1 = t0, t1
    if attrs:
        sp.attrs.update(attrs)
    parent.children.append(sp)
    return sp


# --------------------------------------------------------------------------
# Cross-process stitching: bounded wire serialization + grafting
# --------------------------------------------------------------------------


def _span_to_dict(sp: Span, base: float, budget: List[int]) -> Optional[Dict[str, Any]]:
    """One span as a JSON-able dict with times relative to ``base`` (the
    serialized root's t0, in seconds). ``budget[0]`` is the remaining span
    allowance; a subtree past it is dropped (tree-prefix truncation keeps
    parentage valid) and counted in ``budget[1]``."""
    if budget[0] <= 0:
        budget[1] += sum(1 for _ in sp.walk())
        return None
    budget[0] -= 1
    end = sp.t1 if sp.t1 is not None else time.perf_counter()
    out: Dict[str, Any] = {
        "name": sp.name,
        "cat": sp.cat,
        "start": round(sp.t0 - base, 9),
        "dur": round(max(0.0, end - sp.t0), 9),
        "tid": sp.tid,
    }
    if sp.attrs:
        out["attrs"] = {k: _jsonable(v) for k, v in sp.attrs.items()}
    if sp.events:
        out["events"] = [[k, d] for k, d in sp.events]
    kids = []
    for c in list(sp.children):
        d = _span_to_dict(c, base, budget)
        if d is not None:
            kids.append(d)
    if kids:
        out["children"] = kids
    return out


def to_wire(
    root: Span, max_spans: int = 512, max_bytes: int = 262144
) -> Dict[str, Any]:
    """Serialize a finished span tree for the ``/query`` response.

    Doubly bounded: at most ``max_spans`` spans survive (tree-prefix
    truncation, remainder counted in ``droppedSpans``), and if the JSON
    encoding still exceeds ``max_bytes`` the payload degrades to the root
    alone with ``truncated: true`` — a worker must never inflate a response
    past the router's stated budget.
    """
    budget = [max(1, int(max_spans)), 0]
    tree = _span_to_dict(root, root.t0, budget)
    out: Dict[str, Any] = {"root": tree}
    dropped = budget[1]
    if root.trace is not None and root.trace.dropped:
        dropped += root.trace.dropped
    if dropped:
        out["droppedSpans"] = int(dropped)
    encoded = json.dumps(out, default=str)
    if len(encoded) > int(max_bytes):
        solo = dict(tree)
        solo.pop("children", None)
        out = {"root": solo, "truncated": True}
        if dropped:
            out["droppedSpans"] = int(dropped)
    return out


def _span_from_dict(
    d: Dict[str, Any], shift: float, pid: Optional[int], trace: Optional[Trace]
) -> Span:
    sp = Span.__new__(Span)
    sp.name = str(d.get("name", "?"))
    sp.cat = str(d.get("cat", ""))
    sp.t0 = float(d.get("start", 0.0)) + shift
    sp.t1 = sp.t0 + float(d.get("dur", 0.0))
    sp.attrs = dict(d.get("attrs") or {})
    sp.events = [tuple(e) for e in (d.get("events") or [])]
    sp.tid = int(d.get("tid", 0))
    sp.trace = trace
    sp.pid = pid
    sp.children = [
        _span_from_dict(c, shift, pid, trace) for c in (d.get("children") or [])
    ]
    return sp


def from_wire(
    wire: Dict[str, Any], anchor_t0: Optional[float] = None, pid: Optional[int] = None
) -> Optional[Span]:
    """Rebuild a :func:`to_wire` payload as a local Span tree.

    ``anchor_t0`` (a local ``perf_counter`` reading, normally the dispatch
    span's start) re-bases the remote tree's relative times onto this
    process's clock: remote offsets are exact *within* the remote tree, but
    the anchor inherits the network hop — cross-process alignment is
    approximate by one request latency, which is the honest best available
    without synchronized clocks.
    """
    tree = (wire or {}).get("root")
    if not isinstance(tree, dict):
        return None
    shift = time.perf_counter() if anchor_t0 is None else float(anchor_t0)
    return _span_from_dict(tree, shift, pid, None)


def graft_span(parent: Span, child_root: Optional[Span]) -> Optional[Span]:
    """Attach an existing span tree under ``parent``, charging the subtree
    against the parent's trace budget (overflow counts as dropped, and the
    subtree is kept whole — grafting never slices a remote tree)."""
    if child_root is None:
        return None
    size = sum(1 for _ in child_root.walk())
    tr = parent.trace
    if tr is not None:
        if tr.count + size > tr.max_spans:
            tr.dropped += size
            return None
        tr.count += size
        for sp in child_root.walk():
            sp.trace = tr
    parent.children.append(child_root)
    return child_root


def graft_remote(
    parent: Span,
    wire: Dict[str, Any],
    pid: Optional[int] = None,
    anchor_t0: Optional[float] = None,
) -> Optional[Span]:
    """Rebuild a worker's wire payload and graft it under ``parent`` (the
    router's dispatch span). Returns the grafted root, or None when the
    payload is empty/unparseable or the local budget rejects it."""
    remote = from_wire(
        wire, anchor_t0=parent.t0 if anchor_t0 is None else anchor_t0, pid=pid
    )
    if remote is None:
        return None
    dropped = int((wire or {}).get("droppedSpans", 0) or 0)
    if dropped:
        remote.attrs.setdefault("dropped_spans", dropped)
    if (wire or {}).get("truncated"):
        remote.attrs.setdefault("truncated", True)
    return graft_span(parent, remote)


# --------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# --------------------------------------------------------------------------


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


def to_chrome_trace(root: Span, pid: Optional[int] = None) -> Dict[str, Any]:
    """Render a finished trace as the Chrome trace-event JSON object.

    Complete events (``"ph": "X"``) with microsecond ``ts``/``dur`` relative
    to the root's start; ``tid`` is the OS thread that ran the span, so
    decode-pool work shows on its own tracks. Dispatch events attach under
    ``args.events`` as ``"kind: detail"`` strings.
    """
    if pid is None:
        pid = os.getpid()
    base = root.t0
    events: List[Dict[str, Any]] = []
    events.append(
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "hyperspace_tpu"},
        }
    )
    named_pids = {pid}
    for sp in root.walk():
        sp_pid = sp.pid if sp.pid is not None else pid
        if sp_pid not in named_pids:
            # stitched remote spans show on their own process track, named
            # by the worker that produced them when the graft recorded one
            named_pids.add(sp_pid)
            server = sp.attrs.get("server")
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": sp_pid,
                    "tid": 0,
                    "args": {
                        "name": f"hyperspace_tpu worker {server}" if server
                        else f"hyperspace_tpu pid {sp_pid}"
                    },
                }
            )
        end = sp.t1 if sp.t1 is not None else time.perf_counter()
        args = {k: _jsonable(v) for k, v in sp.attrs.items()}
        if sp.events:
            args["events"] = [f"{k}: {d}" for k, d in sp.events]
        events.append(
            {
                "name": sp.name,
                "cat": sp.cat or "span",
                "ph": "X",
                "ts": round((sp.t0 - base) * 1e6, 3),
                "dur": round(max(0.0, end - sp.t0) * 1e6, 3),
                "pid": sp_pid,
                "tid": sp.tid,
                "args": args,
            }
        )
    out: Dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
    tr = root.trace
    if tr is not None and tr.dropped:
        out["otherData"] = {"droppedSpans": tr.dropped}
    return out
