"""Process-wide metrics registry: counters, gauges, histograms.

One registry instance (module-level :data:`REGISTRY`) is the process-wide
default every subsystem publishes into — the serving runtime's completion/
latency/cache/rejection accounting, telemetry event counts, and anything a
future PR wants attributed. Instruments are *labeled* (Prometheus-style), so
several ``QueryServer``s in one process publish the same metric names under
distinct ``server=...`` labels instead of clobbering each other.

Overhead discipline: an increment is one small per-instrument lock acquire
(~no contention: each instrument has its own lock) — there is no exporter
thread, no background work; exposition (:meth:`MetricsRegistry.prometheus_text`
/ :meth:`MetricsRegistry.snapshot`) does all formatting at read time, so a
process that never exports pays only the counter bumps.

Tests that need isolation construct a private ``MetricsRegistry()``; the
serving classes all accept a ``registry=`` override for exactly that.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from hyperspace_tpu.check.locks import named_lock

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "default_registry",
]

#: seconds-oriented default histogram bounds (query latencies): 100 µs .. 60 s
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonic counter."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins instantaneous value; ``fn`` makes it a read-time
    callback gauge (queue depth, cache bytes) instead of a stored value."""

    __slots__ = ("_lock", "_v", "fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        self._v = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return self._v


class Histogram:
    """Cumulative-bucket histogram + bounded recent-value reservoir.

    Buckets give the Prometheus exposition; the reservoir (most recent
    ``window`` observations) gives *current* percentiles for stats snapshots
    — the same bounded-memory stance ``ServingMetrics`` took before it moved
    here.
    """

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count", "_window")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS, window: int = 4096):
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._count = 0
        self._window = deque(maxlen=int(window))

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for b in self.buckets:
                if v <= b:
                    break
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._window.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentiles(self, qs: Tuple[float, ...] = (50.0, 95.0, 99.0)) -> Dict[str, Optional[float]]:
        """Percentiles over the recent window (``{"p50": ..., ...}``); None
        values when nothing was observed yet."""
        with self._lock:
            vals = sorted(self._window)
        out: Dict[str, Optional[float]] = {}
        for q in qs:
            key = f"p{q:g}"
            if not vals:
                out[key] = None
                continue
            # nearest-rank on the sorted window (matches np.percentile's
            # 'lower' flavor closely enough for tail reporting)
            idx = min(len(vals) - 1, max(0, int(round((q / 100.0) * (len(vals) - 1)))))
            out[key] = float(vals[idx])
        return out

    def snapshot_buckets(self) -> List[Tuple[str, int]]:
        """Cumulative (le, count) pairs, Prometheus-style, ending at +Inf."""
        with self._lock:
            counts = list(self._counts)
        cum, out = 0, []
        for b, c in zip(self.buckets, counts[:-1]):
            cum += c
            out.append((f"{b:g}", cum))
        out.append(("+Inf", cum + counts[-1]))
        return out


def _escape_label_value(v: str) -> str:
    """Escape a label value per the Prometheus text exposition format 0.0.4:
    backslash, double-quote, and line feed must be escaped (in this order —
    escaping the backslash first keeps the other escapes unambiguous)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP lines escape backslash and line feed (quotes are legal there)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Tuple[Tuple[str, str], ...], extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
    items = list(labels) + list(extra or ())
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in items)
    return "{" + body + "}"


class MetricsRegistry:
    """Name+labels -> instrument, with get-or-create semantics.

    ``counter``/``gauge``/``histogram`` return the existing instrument when
    the (name, labels) pair is already registered; asking for the same name
    with a different instrument kind raises (one name, one type — the
    Prometheus data model).
    """

    def __init__(self):
        # registry-level lock only: per-instrument value locks stay plain —
        # they are leaf locks on the inc() hot path and never nest
        self._lock = named_lock("obs.metricsRegistry")
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._created = time.time()

    # -- instrument factories ------------------------------------------------
    def _get_or_create(self, kind: str, name: str, help_: str, labels: dict, make):
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            prev_kind = self._kinds.get(name)
            if prev_kind is not None and prev_kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {prev_kind}, not {kind}"
                )
            got = self._metrics.get(key)
            if got is None:
                got = make()
                self._metrics[key] = got
                self._kinds[name] = kind
                if help_:
                    self._help[name] = help_
            return got

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get_or_create("counter", name, help, labels, Counter)

    def gauge(self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None, **labels) -> Gauge:
        g = self._get_or_create("gauge", name, help, labels, lambda: Gauge(fn))
        if fn is not None and g.fn is not fn:
            g.fn = fn  # re-bind (a restarted server re-registers its source)
        return g

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
        window: int = 4096,
        **labels,
    ) -> Histogram:
        return self._get_or_create(
            "histogram", name, help, labels,
            lambda: Histogram(buckets or DEFAULT_BUCKETS, window=window),
        )

    def remove(self, name: str, **labels) -> None:
        """Drop one instrument (a shut-down server's callback gauge must not
        outlive its data source)."""
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            self._metrics.pop(key, None)

    # -- exposition ----------------------------------------------------------
    def _items(self):
        with self._lock:
            return sorted(self._metrics.items()), dict(self._kinds), dict(self._help)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view: ``{name: {kind, help, series: [{labels, ...}]}}``."""
        items, kinds, helps = self._items()
        out: Dict[str, Any] = {}
        for (name, labels), m in items:
            entry = out.setdefault(
                name, {"kind": kinds.get(name, ""), "help": helps.get(name, ""), "series": []}
            )
            lab = dict(labels)
            if isinstance(m, Counter) or isinstance(m, Gauge):
                entry["series"].append({"labels": lab, "value": m.value})
            else:
                entry["series"].append(
                    {
                        "labels": lab,
                        "count": m.count,
                        "sum": m.sum,
                        "percentiles": m.percentiles(),
                    }
                )
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        items, kinds, helps = self._items()
        by_name: Dict[str, List] = {}
        for (name, labels), m in items:
            by_name.setdefault(name, []).append((labels, m))
        lines: List[str] = []
        for name in sorted(by_name):
            kind = kinds.get(name, "untyped")
            h = helps.get(name, "")
            if h:
                lines.append(f"# HELP {name} {_escape_help(h)}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, m in by_name[name]:
                if isinstance(m, (Counter, Gauge)):
                    v = m.value
                    sv = f"{v:g}" if v == v else "NaN"
                    lines.append(f"{name}{_fmt_labels(labels)} {sv}")
                else:
                    for le, c in m.snapshot_buckets():
                        lines.append(f"{name}_bucket{_fmt_labels(labels, (('le', le),))} {c}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} {m.sum:g}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


#: the process-wide default registry
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY
