"""Unified query observability: spans, metrics, profiles.

Three layers on one substrate (see docs/observability.md):

- :mod:`hyperspace_tpu.obs.spans` — context-propagated hierarchical span
  traces per query, with Chrome trace-event export (Perfetto);
- :mod:`hyperspace_tpu.obs.metrics` — a process-wide, labeled metrics
  registry (counters/gauges/histograms) with Prometheus text exposition;
- :mod:`hyperspace_tpu.obs.profile` — the per-query ``QueryProfile``
  joining span timings with plan facts (indexes applied, rows/bytes,
  why-not reasons).

Import of this package is stdlib-only: no jax, no numpy (the library's
import-side-effect contract, tests/test_import_side_effects.py).
"""

from hyperspace_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from hyperspace_tpu.obs.profile import QueryProfile, build_profile
from hyperspace_tpu.obs.spans import (
    NULL_SPAN,
    Span,
    Trace,
    add_manual,
    attach,
    current_span,
    span,
    start_trace,
    to_chrome_trace,
    trace,
    wrap,
)

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "QueryProfile",
    "build_profile",
    "NULL_SPAN",
    "Span",
    "Trace",
    "add_manual",
    "attach",
    "current_span",
    "span",
    "start_trace",
    "to_chrome_trace",
    "trace",
    "wrap",
]
