"""Unified query observability: spans, metrics, profiles.

Three layers on one substrate (see docs/observability.md):

- :mod:`hyperspace_tpu.obs.spans` — context-propagated hierarchical span
  traces per query, with Chrome trace-event export (Perfetto);
- :mod:`hyperspace_tpu.obs.metrics` — a process-wide, labeled metrics
  registry (counters/gauges/histograms) with Prometheus text exposition;
- :mod:`hyperspace_tpu.obs.profile` — the per-query ``QueryProfile``
  joining span timings with plan facts (indexes applied, rows/bytes,
  why-not reasons);
- :mod:`hyperspace_tpu.obs.history` — fingerprint-keyed streaming profile
  statistics + cost estimates (``ProfileHistory``) and the slow-query
  flight recorder (``FlightRecorder``);
- :mod:`hyperspace_tpu.obs.slo` — per-tenant latency-SLO accounting with
  multi-window burn-rate gauges;
- :mod:`hyperspace_tpu.obs.export` — the stdlib HTTP telemetry endpoint
  (``/metrics``, ``/statusz``, ``/profilez``).

Import of this package is stdlib-only: no jax, no numpy (the library's
import-side-effect contract, tests/test_import_side_effects.py).
"""

from hyperspace_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from hyperspace_tpu.obs.export import TelemetryEndpoint
from hyperspace_tpu.obs.history import (
    CostEstimate,
    FlightEntry,
    FlightRecorder,
    ProfileHistory,
    load_history,
    merge_history_snapshots,
)
from hyperspace_tpu.obs.profile import QueryProfile, build_profile
from hyperspace_tpu.obs.slo import SloTracker
from hyperspace_tpu.obs.spans import (
    NULL_SPAN,
    Span,
    Trace,
    TraceContext,
    add_manual,
    attach,
    bind_context,
    current_context,
    current_span,
    from_wire,
    graft_remote,
    parse_traceparent,
    span,
    start_trace,
    to_chrome_trace,
    to_wire,
    trace,
    wrap,
)

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "QueryProfile",
    "build_profile",
    "CostEstimate",
    "FlightEntry",
    "FlightRecorder",
    "ProfileHistory",
    "load_history",
    "merge_history_snapshots",
    "SloTracker",
    "TelemetryEndpoint",
    "NULL_SPAN",
    "Span",
    "Trace",
    "TraceContext",
    "add_manual",
    "attach",
    "bind_context",
    "current_context",
    "current_span",
    "from_wire",
    "graft_remote",
    "parse_traceparent",
    "span",
    "start_trace",
    "to_chrome_trace",
    "to_wire",
    "trace",
    "wrap",
]
