"""Snapshot pinning: freeze the data version a request resolves against.

A ``SnapshotHandle`` is an immutable capture of the stable index-log roster
(every index's latest stable ``IndexLogEntry``) plus the lifecycle commit
sequence observed at capture time. The serving front-end captures one per
request at admission and enters :func:`snapshot_scope` around resolution and
execution; ``IndexCollectionManager.get_indexes``/``get_index`` consult
:func:`current_snapshot` first, so *every* log-version resolution downstream
of a pinned request — ``session_token``, ``version_brand``,
``ApplyHyperspace`` candidate collection, hybrid-scan appended/deleted
diffs — reads the pinned roster, never the live log.

The invariant this buys (docs/lifecycle.md): a refresh committing version
N+1 while a request is in flight cannot change that request's answer — the
request was admitted against version N and serves exactly version N's rows.
Conversely a request admitted *after* commit k captures a roster with the
new entry, giving linearizable version visibility.

The pin is a ``contextvars.ContextVar``, so concurrent worker threads (and
micro-batched groups) each carry their own pin without cross-talk — the same
mechanism ``Session.hyperspace_scope`` uses for the enabled flag.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, List, Optional, Tuple

_pin: contextvars.ContextVar = contextvars.ContextVar("hs_snapshot_pin", default=None)


def current_snapshot() -> Optional["SnapshotHandle"]:
    """The SnapshotHandle pinned on this thread/context, or None."""
    return _pin.get()


@contextlib.contextmanager
def snapshot_scope(handle: Optional["SnapshotHandle"]) -> Iterator[Optional["SnapshotHandle"]]:
    """Pin ``handle`` for the dynamic extent of the block (no-op for None,
    so call sites don't need to branch on whether pinning is enabled)."""
    if handle is None:
        yield None
        return
    token = _pin.set(handle)
    try:
        yield handle
    finally:
        _pin.reset(token)


def _count_pin() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_snapshot_pins_total",
        "SnapshotHandles captured (one per admitted request when pinning is on)",
    ).inc()


class SnapshotHandle:
    """Immutable capture of the stable log version of every index at one
    instant, plus the commit sequence number it was taken at.

    ``entries`` holds the latest stable ``IndexLogEntry`` per index (all
    stable states, matching what the caching manager caches); ``roster`` is
    the sorted ``(name, log id)`` tuple — the part of the identity that
    folds into session tokens and version brands.
    """

    __slots__ = ("entries", "roster", "commit_seq", "created_at")

    def __init__(self, entries, commit_seq: int = 0, created_at: Optional[float] = None):
        self.entries: Tuple = tuple(entries)
        self.roster: Tuple = tuple(sorted((e.name, e.id) for e in self.entries))
        self.commit_seq = int(commit_seq)
        self.created_at = time.monotonic() if created_at is None else created_at

    @classmethod
    def capture(cls, session) -> "SnapshotHandle":
        """Capture the current stable roster through the session's (caching)
        index manager. Under an existing pin this returns the *pinned* roster
        — capture is idempotent, a nested capture can't time-travel forward.

        The commit sequence is read BEFORE the roster: if a commit lands
        between the two reads, the handle under-reports its sequence, which
        is the safe direction (a request claiming seq k must see >= k).

        An unreadable roster (no ``hyperspace.system.path`` configured, log
        directory gone) pins an *empty* snapshot instead of failing the
        request: queries then resolve no indexes and fall back to plain
        scans — correct answers, minus the speedup.
        """
        from hyperspace_tpu.models import states

        bus = session.lifecycle_bus
        seq = bus.commit_seq
        try:
            entries = session.index_manager.get_indexes(list(states.STABLE_STATES))
        except Exception:
            entries = ()
        _count_pin()
        return cls(entries, commit_seq=seq)

    def get_indexes(self, accepted_states: Optional[List[str]] = None) -> List:
        from hyperspace_tpu.models import states

        accepted = set(accepted_states or states.STABLE_STATES)
        return [e for e in self.entries if e.state in accepted]

    def get_index(self, name: str):
        for e in self.entries:
            if e.name == name:
                return e
        return None

    def index_version(self, name: str) -> Optional[int]:
        """The pinned log id of ``name``, or None when the index is not in
        the snapshot."""
        e = self.get_index(name)
        return None if e is None else e.id

    def __repr__(self) -> str:
        return f"SnapshotHandle(seq={self.commit_seq}, roster={self.roster!r})"
