"""Background refresh controller: keep indexes fresh while serving.

``RefreshManager`` watches every ACTIVE index's appended/deleted drift — the
same ``FileInfo`` set-diff and byte ratios the candidate gate uses
(``rules/candidate._signature_filter``) — and schedules an
incremental/quick refresh when drift crosses the hybrid-scan thresholds:

- hybrid scan absorbs *small* drift at query time for free, so below the
  thresholds the manager commits a **quick** (metadata-only) refresh that
  records appended/deleted in the log entry;
- past either threshold the candidate gate would start rejecting the index,
  so the manager runs an **incremental** refresh that folds the drift into
  the index data proper.

Concurrency stance:

- the build runs on the manager's own thread, never under any serving lock —
  serving keeps resolving the prior stable version throughout;
- **single-writer per index**: a non-blocking per-index mutex makes a second
  scheduler (or an operator-issued manual refresh racing the manager) skip
  rather than double-build; with ``hyperspace.fabric.lease.enabled`` the
  same guarantee extends across *processes* via a lake-persisted lease with
  heartbeat renewal and a fencing token verified at the commit point
  (``fabric/lease.py``) — a holder killed mid-refresh is taken over by a
  peer after lease expiry, and its late commit is fenced off;
- **crash-safe / retry-idempotent** by construction: refresh goes through
  the Action FSM (CREATING->ACTIVE via the log manager), so a failure at any
  point leaves the prior ACTIVE entry untouched and a retry re-runs the same
  diff; once a refresh commits, the retry sees no drift and raises
  ``NoChangesException`` — surfaced here as the ``no-changes`` outcome;
- commits publish on the session's :class:`InvalidationBus` (via the caching
  manager), which is what makes the new version visible to serving.

Every attempt lands in ``hs_lifecycle_refresh_total{mode,outcome}``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from hyperspace_tpu.check.locks import named_lock


class DriftStats:
    """Appended/deleted drift of one index vs its current source files."""

    __slots__ = (
        "index_name",
        "appended_files",
        "deleted_files",
        "appended_bytes",
        "deleted_bytes",
        "appended_ratio",
        "deleted_ratio",
    )

    def __init__(self, index_name, appended_files, deleted_files,
                 appended_bytes, deleted_bytes, appended_ratio, deleted_ratio):
        self.index_name = index_name
        self.appended_files = appended_files
        self.deleted_files = deleted_files
        self.appended_bytes = appended_bytes
        self.deleted_bytes = deleted_bytes
        self.appended_ratio = appended_ratio
        self.deleted_ratio = deleted_ratio

    @property
    def has_drift(self) -> bool:
        return bool(self.appended_files or self.deleted_files)

    def __repr__(self) -> str:
        return (
            f"DriftStats({self.index_name!r}, +{self.appended_files}f/"
            f"{self.appended_bytes}B ({self.appended_ratio:.3f}), "
            f"-{self.deleted_files}f/{self.deleted_bytes}B ({self.deleted_ratio:.3f}))"
        )


def _count_refresh(mode: str, outcome: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_lifecycle_refresh_total",
        "refresh attempts by the lifecycle refresh manager",
        mode=mode,
        outcome=outcome,
    ).inc()


class RefreshManager:
    """Poll-loop controller; ``poll_once()`` is the deterministic unit tests
    drive directly, ``start()``/``stop()`` wrap it in a daemon thread."""

    def __init__(self, session, interval_seconds: Optional[float] = None):
        self._session = session
        self._interval = interval_seconds
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._index_locks: Dict[str, threading.Lock] = {}
        self._guard = named_lock("lifecycle.refreshManager")

    # -- lifecycle -----------------------------------------------------------
    @property
    def interval_seconds(self) -> float:
        if self._interval is not None:
            return float(self._interval)
        return self._session.conf.lifecycle_refresh_interval_seconds

    def start(self) -> None:
        with self._guard:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop_event.clear()
            self._thread = threading.Thread(
                target=self._run, name="hs-refresh-manager", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        with self._guard:
            thread = self._thread
            self._thread = None
        self._stop_event.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout)

    def _run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.poll_once()
            except Exception:  # pragma: no cover - the loop must survive
                pass
            self._stop_event.wait(self.interval_seconds)

    # -- drift + decision ----------------------------------------------------
    def drift(self, entry) -> Optional[DriftStats]:
        """Re-list the index's source and diff against what it indexed —
        the refresh-action preamble, run read-only. None when the source
        cannot be re-listed (dropped table, unreadable path)."""
        try:
            metadata = self._session.provider_manager.create_relation_metadata(entry.relation)
            relation = metadata.to_relation_object()
            current = {fi.key: fi for fi in relation.all_file_infos()}
        except Exception:
            return None
        indexed = {fi.key: fi for fi in entry.source_file_infos()}
        appended = [current[k] for k in current.keys() - indexed.keys()]
        deleted = [indexed[k] for k in indexed.keys() - current.keys()]
        appended_bytes = sum(fi.size for fi in appended)
        deleted_bytes = sum(fi.size for fi in deleted)
        # ratio denominators match rules/candidate._signature_filter so the
        # manager's incremental trigger fires exactly when the candidate gate
        # would start rejecting hybrid scan
        total_bytes = sum(fi.size for fi in current.values())
        return DriftStats(
            index_name=entry.name,
            appended_files=len(appended),
            deleted_files=len(deleted),
            appended_bytes=appended_bytes,
            deleted_bytes=deleted_bytes,
            appended_ratio=appended_bytes / max(1, total_bytes),
            deleted_ratio=deleted_bytes / max(1, entry.source_files_size()),
        )

    def decide(self, drift: Optional[DriftStats]) -> Optional[str]:
        """Refresh mode for this drift, or None for no action.

        ``hyperspace.lifecycle.refresh.mode`` pins the mode; the default
        ``auto`` picks incremental when drift exceeds either hybrid-scan
        threshold (the candidate gate is about to reject the index) and a
        metadata-only quick refresh otherwise.
        """
        from hyperspace_tpu import config as C

        if drift is None or not drift.has_drift:
            return None
        conf = self._session.conf
        mode = conf.lifecycle_refresh_mode
        if mode != "auto":
            return mode if mode in C.REFRESH_MODES else None
        over = (
            drift.appended_ratio > conf.hybrid_scan_appended_ratio_threshold
            or drift.deleted_ratio > conf.hybrid_scan_deleted_ratio_threshold
        )
        return C.REFRESH_MODE_INCREMENTAL if over else C.REFRESH_MODE_QUICK

    # -- execution -----------------------------------------------------------
    def _lock_for(self, name: str) -> threading.Lock:
        with self._guard:
            lock = self._index_locks.get(name)
            if lock is None:
                lock = self._index_locks[name] = threading.Lock()
            return lock

    def _acquire_lake_lease(self, name: str):
        """The cross-process half of single-writer: a lake-persisted lease
        per index (``fabric/lease.py``) when the fabric + lease conf is on.
        Returns ``(lease, acquired)`` — ``(None, True)`` means leases are
        off and the in-process lock alone governs, as before."""
        conf = self._session.conf
        if not (conf.fabric_enabled and conf.fabric_lease_enabled and conf.system_path):
            return None, True
        from hyperspace_tpu.fabric import lease as lease_mod
        from hyperspace_tpu.fabric.records import local_node_id

        lease = lease_mod.acquire(
            conf.system_path,
            f"refresh/{name}",
            holder=local_node_id(conf),
            ttl_s=conf.fabric_lease_ttl_seconds,
        )
        if lease is None:
            return None, False
        lease.start_heartbeat(conf.fabric_lease_renew_interval_seconds)
        return lease, True

    def refresh_index(self, name: str, mode: str) -> str:
        """Run one refresh under the per-index single-writer lock (plus the
        lake lease when ``hyperspace.fabric.lease.enabled``); returns the
        outcome: committed | no-changes | busy | fenced | error."""
        from hyperspace_tpu.actions.base import NoChangesException

        lock = self._lock_for(name)
        if not lock.acquire(blocking=False):
            _count_refresh(mode, "busy")
            return "busy"
        lease = None
        try:
            lease, acquired = self._acquire_lake_lease(name)
            if not acquired:
                # a peer process holds the lease: same convergence story as
                # the in-process lock — skip, the next poll re-checks drift
                outcome = "busy"
            else:
                try:
                    if lease is not None:
                        from hyperspace_tpu.fabric.lease import fence_scope

                        with fence_scope(lease):
                            self._session.index_manager.refresh(name, mode)
                    else:
                        self._session.index_manager.refresh(name, mode)
                    outcome = "committed"
                except NoChangesException:
                    # the drift we saw was committed by someone else (or a
                    # retried refresh already landed) — converged
                    outcome = "no-changes"
                except Exception as exc:
                    from hyperspace_tpu.fabric.lease import LeaseLostError

                    # the Action FSM guarantees the prior ACTIVE entry still
                    # serves; the next poll retries the same diff. A fenced
                    # commit means a peer took over — also converged, but
                    # surfaced distinctly (the zombie-writer signature).
                    outcome = "fenced" if isinstance(exc, LeaseLostError) else "error"
        finally:
            if lease is not None:
                lease.release()
            lock.release()
        _count_refresh(mode, outcome)
        return outcome

    def poll_once(self) -> List[dict]:
        """One scheduling pass over every ACTIVE index; returns what was
        decided/done per index (tests assert on this)."""
        from hyperspace_tpu.models import states

        results: List[dict] = []
        try:
            entries = self._session.index_manager.get_indexes([states.ACTIVE])
        except Exception:
            return results
        for entry in entries:
            drift = self.drift(entry)
            mode = self.decide(drift)
            if mode is None:
                results.append({"index": entry.name, "mode": None, "outcome": "fresh"})
                continue
            outcome = self.refresh_index(entry.name, mode)
            results.append({"index": entry.name, "mode": mode, "outcome": outcome})
        return results
