"""Live-data lifecycle subsystem: zero-downtime refresh for a serving runtime.

Three cooperating pieces (docs/lifecycle.md has the walkthrough):

- :mod:`hyperspace_tpu.lifecycle.snapshot` — immutable per-request
  ``SnapshotHandle`` pinning the index-log roster observed at admission, so
  a refresh committing version N+1 mid-flight never changes a running
  query's answer;
- :mod:`hyperspace_tpu.lifecycle.refresh_manager` — the background
  controller that watches per-index appended/deleted drift against the
  hybrid-scan thresholds and schedules incremental/quick refreshes
  concurrently with serving;
- :mod:`hyperspace_tpu.lifecycle.invalidation` — the commit bus: every
  index mutation publishes exactly one commit event, and freshness
  propagation (roster cache, bucket/IO/device caches, brand rotation)
  happens in one place instead of per-cache ad-hoc discipline.
"""

from hyperspace_tpu.lifecycle.invalidation import CommitEvent, InvalidationBus
from hyperspace_tpu.lifecycle.refresh_manager import RefreshManager
from hyperspace_tpu.lifecycle.snapshot import (
    SnapshotHandle,
    current_snapshot,
    snapshot_scope,
)

__all__ = [
    "CommitEvent",
    "InvalidationBus",
    "RefreshManager",
    "SnapshotHandle",
    "current_snapshot",
    "snapshot_scope",
]
