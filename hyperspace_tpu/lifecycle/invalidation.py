"""Commit/invalidation bus: one place where freshness propagates.

Every successful index mutation (refresh, optimize, create, delete, restore,
vacuum) publishes exactly one :class:`CommitEvent` to the session's
:class:`InvalidationBus`. The bus then does the freshness work that PRs 1-9
left to ad-hoc per-cache discipline:

- bumps the monotonic **commit sequence** — the number snapshot pins record
  so the soak test can assert linearizable visibility (a request admitted
  after commit k pins seq >= k);
- clears the **roster TTL cache** (``CachingIndexCollectionManager``) so the
  next admitted request pins the new log version immediately instead of up
  to ``cache_expiry_seconds`` later;
- **targeted-purges** the bucket-prefetch, IO batch, and device column
  caches for the files the commit touched (old index data files + deleted
  source files), counted per cache in
  ``hs_lifecycle_invalidations_total{cache=...}``;
- notifies subscribers (the refresh manager, tests).

The result cache and join-build cache are *brand-rotated* rather than
purged here: their keys fold in ``data_version_brand`` / the roster brand,
which changes as soon as the roster cache is cleared, and both caches purge
stale brands on first observation of a new one (counted in their own
``hs_*_cache_invalidations_total`` counters). The bus's job for those two is
simply making the new brand visible immediately.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from hyperspace_tpu.check.locks import named_lock


class CommitEvent:
    """One committed index mutation.

    ``affected_files`` carries every file path whose cached derivatives are
    stale after this commit: the *previous* entry's index data files (their
    content was rewritten or superseded) plus any source files the commit
    deleted from coverage.

    ``origin`` is the fabric node id of the publishing process (None for a
    plain single-process publish): the commit record persists it so a
    CommitWatcher in the publishing process can recognize — and skip — its
    own commits instead of re-purging caches it already purged.
    """

    __slots__ = ("index_name", "log_id", "kind", "affected_files", "origin")

    def __init__(
        self,
        index_name: str,
        log_id: Optional[int],
        kind: str,
        affected_files: Sequence[str] = (),
        origin: Optional[str] = None,
    ):
        self.index_name = str(index_name)
        self.log_id = log_id
        self.kind = str(kind)  # refresh-incremental | refresh-quick | create | ...
        self.affected_files: Tuple[str, ...] = tuple(affected_files)
        self.origin = origin

    def __repr__(self) -> str:
        return (
            f"CommitEvent({self.index_name!r}, id={self.log_id}, kind={self.kind!r}, "
            f"files={len(self.affected_files)})"
        )


def _count_commit() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_lifecycle_commits_total",
        "index mutations published on the lifecycle commit bus",
    ).inc()


def _count_replay(kind: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_records_replayed_total",
        "remote commit records replayed onto the local invalidation bus",
        kind=kind,
    ).inc()


def _count_invalidations(cache: str, n: int) -> None:
    if n <= 0:
        return
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_lifecycle_invalidations_total",
        "cache entries purged by commit-driven invalidation",
        cache=cache,
    ).inc(n)


class InvalidationBus:
    """Session-scoped commit fan-out (see module docstring).

    ``publish`` is safe to call with serving traffic in flight: in-flight
    requests hold a snapshot pin and keep resolving the old version; the
    purges only remove *cached bytes*, never data, so a pinned request that
    raced a purge simply re-reads from disk.
    """

    def __init__(self, session):
        self._session = session
        self._lock = named_lock("lifecycle.invalidationBus")
        self._seq = 0
        self._subscribers: List[Callable[[CommitEvent], None]] = []

    @property
    def commit_seq(self) -> int:
        """Monotonic count of commits published on this bus."""
        with self._lock:
            return self._seq

    # -- subscriptions -------------------------------------------------------
    def subscribe(self, fn: Callable[[CommitEvent], None]) -> None:
        with self._lock:
            if fn not in self._subscribers:
                self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[CommitEvent], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    # -- publication ---------------------------------------------------------
    def publish(self, event: CommitEvent) -> dict:
        """Publish one commit; returns per-cache purge counts (observability
        and test assertions — the same numbers land in
        ``hs_lifecycle_invalidations_total{cache}``).

        With the fabric on, the commit is also persisted as a numbered
        record under the index's log directory, stamped with this process's
        node id and the post-bump commit sequence, so peer processes replay
        it (see :meth:`replay` and ``hyperspace_tpu/fabric/watcher.py``).
        """
        with self._lock:
            self._seq += 1
            seq = self._seq
            subscribers = list(self._subscribers)
        _count_commit()
        self._persist_record(event, seq)
        return self._apply(event, subscribers)

    def replay(self, event: CommitEvent, seq: Optional[int] = None) -> dict:
        """Apply a commit observed in the lake (published by *another*
        process) to this process's caches. Advances the local commit
        sequence to at least the record's persisted sequence — a Lamport
        merge, so all processes agree that event ordering never runs
        backwards — and never re-persists a record (no echo)."""
        with self._lock:
            if seq is not None and int(seq) > self._seq:
                self._seq = int(seq)
            else:
                # a record without a sequence still invalidates pins/tokens
                self._seq += 1
            subscribers = list(self._subscribers)
        _count_replay(event.kind)
        return self._apply(event, subscribers)

    def _persist_record(self, event: CommitEvent, seq: int) -> None:
        conf = getattr(self._session, "conf", None)
        if conf is None or not conf.fabric_enabled:
            return
        from hyperspace_tpu.fabric import records

        if event.origin is None:
            event.origin = records.local_node_id(conf)
        records.append_commit_record(conf.system_path, event, seq)

    def _apply(self, event: CommitEvent, subscribers) -> dict:
        counts = {"roster": 0, "bucket": 0, "io": 0, "device": 0}

        # 1) roster freshness: without this, a post-commit request would pin
        #    a TTL-stale roster for up to cache_expiry_seconds — breaking the
        #    "admitted after commit k sees >= k" invariant outright.
        mgr = getattr(self._session, "_index_manager", None)
        if mgr is not None and hasattr(mgr, "clear_cache"):
            mgr.clear_cache()
            counts["roster"] = 1
        _count_invalidations("roster", counts["roster"])

        # 2) targeted purges of byte caches keyed (partly) by file path
        files = event.affected_files
        if files:
            bucket = getattr(self._session, "bucket_cache", None)
            if bucket is not None and hasattr(bucket, "purge_files"):
                counts["bucket"] = bucket.purge_files(files)
            _count_invalidations("bucket", counts["bucket"])

            from hyperspace_tpu.exec.io import purge_io_cache

            counts["io"] = purge_io_cache(files)
            _count_invalidations("io", counts["io"])

            from hyperspace_tpu.exec.device import purge_device_cache_files

            counts["device"] = purge_device_cache_files(files)
            _count_invalidations("device", counts["device"])

        # 3) fan out; a broken subscriber must not block the commit path
        for fn in subscribers:
            try:
                fn(event)
            except Exception:  # pragma: no cover - defensive
                pass
        return counts
