"""Deterministic fault injection at the lake IO seams.

A :class:`FaultRule` targets one *site* — a named IO seam the production
code consults via :func:`check` / :func:`mangle_bytes` — and fires either
on the nth matching operation, with a seeded probability, or on every
match. Sites wired through the codebase:

========================  ====================================================
``log.read``              operation-log entry read (models/log_manager.py)
``log.write``             operation-log entry write
``io.footer``             parquet footer/metadata/schema read (exec/io.py)
``io.decode``             parquet decode — fires on the per-file path
                          (exec/io.py read_one) AND before/after the native
                          row-group fast path's C calls (_native_rg_scan)
``pipeline.task``         prefetch-pipeline chunk task (exec/pipeline.py)
``join.task``             streamed-join side decode task (exec/join_stream.py)
``device.transfer``       host→device staging (exec/device.py)
``lease.renew``           fabric lease heartbeat renewal (fabric/lease.py)
``fabric.http``           FrontDoor→worker HTTP dispatch (fabric/frontdoor.py)
``record.compact``        fsck garbage-collection removal (fabric/fsck.py)
========================  ====================================================

Fault kinds: ``transient`` raises :class:`InjectedTransientIOError`,
``corrupt`` raises :class:`InjectedCorruptDataError`, ``latency`` sleeps
``delay_s`` then proceeds, and ``truncate`` / ``magic`` mangle the bytes at
byte-level seams (the log reader) — truncation tears the tail off, magic
flips the leading bytes.

Default-off discipline: the registry holds a single ``active`` flag that is
False unless rules are installed; every production seam checks that one
attribute before anything else, so the disabled path is one attribute read
(the ≤1% hook budget). Tests install rules with :func:`fault_scope` — no
monkeypatching — and sessions can install from conf via
``hyperspace.reliability.faults.spec``:

    "io.decode:transient:p=0.01;log.read:corrupt:glob=*_hyperspace_log*:nth=3"

Everything is deterministic under a fixed seed: one ``random.Random(seed)``
drives probability draws in installation order, and nth-operation counters
are per-rule.
"""

from __future__ import annotations

import fnmatch
import random
import threading
import time
from typing import List, Optional

from hyperspace_tpu.reliability.errors import (
    InjectedCorruptDataError,
    InjectedTransientIOError,
)

KINDS = ("transient", "corrupt", "latency", "truncate", "magic")


class FaultRule:
    """One injection rule; see module docstring for targeting semantics."""

    __slots__ = ("site", "kind", "path_glob", "nth", "probability", "delay_s",
                 "max_fires", "_ops", "_fires")

    def __init__(
        self,
        site: str,
        kind: str,
        *,
        path_glob: Optional[str] = None,
        nth: Optional[int] = None,
        probability: Optional[float] = None,
        delay_s: float = 0.0,
        max_fires: Optional[int] = None,
    ):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
        self.site = str(site)
        self.kind = kind
        self.path_glob = path_glob
        self.nth = nth
        self.probability = probability
        self.delay_s = float(delay_s)
        self.max_fires = max_fires
        self._ops = 0    # matching operations observed
        self._fires = 0  # faults actually delivered

    def matches_target(self, site: str, path: Optional[str]) -> bool:
        if self.site != "*" and self.site != site:
            return False
        if self.path_glob is not None:
            if path is None or not fnmatch.fnmatch(str(path), self.path_glob):
                return False
        return True

    def should_fire(self, rng: random.Random) -> bool:
        """Called under the registry lock for a target match; advances the
        per-rule op counter and decides deterministically."""
        if self.max_fires is not None and self._fires >= self.max_fires:
            return False
        self._ops += 1
        if self.nth is not None:
            fire = self._ops == self.nth
        elif self.probability is not None:
            fire = rng.random() < self.probability
        else:
            fire = True
        if fire:
            self._fires += 1
        return fire

    @property
    def fires(self) -> int:
        return self._fires

    def __repr__(self) -> str:
        return (
            f"FaultRule({self.site}:{self.kind}, glob={self.path_glob!r}, "
            f"nth={self.nth}, p={self.probability}, fires={self._fires})"
        )


def _count_injection(site: str, kind: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_faults_injected_total",
        "faults delivered by the reliability fault-injection harness",
        site=site,
        kind=kind,
    ).inc()


class FaultRegistry:
    """Process-global rule set; ``active`` is the one-attribute fast path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self._rng = random.Random(0)
        self.active = False

    # -- installation --------------------------------------------------------
    def install(self, *rules: FaultRule) -> None:
        with self._lock:
            self._rules.extend(rules)
            self.active = bool(self._rules)

    def clear(self) -> None:
        with self._lock:
            self._rules = []
            self.active = False

    def seed(self, seed: int) -> None:
        with self._lock:
            self._rng = random.Random(int(seed))

    def rules(self) -> List[FaultRule]:
        with self._lock:
            return list(self._rules)

    # -- the seams -----------------------------------------------------------
    def check(self, site: str, path: Optional[str] = None) -> None:
        """Raise/delay per the first matching rule that fires. The inactive
        path is the caller's ``if FAULTS.active`` — this method assumes at
        least the possibility of rules."""
        if not self.active:
            return
        fired: Optional[FaultRule] = None
        with self._lock:
            for r in self._rules:
                if r.matches_target(site, path) and r.should_fire(self._rng):
                    fired = r
                    break
        if fired is None:
            return
        _count_injection(site, fired.kind)
        if fired.kind == "latency":
            time.sleep(fired.delay_s)
            return
        if fired.kind == "transient":
            raise InjectedTransientIOError(
                f"injected transient fault at {site} ({path or '?'})"
            )
        # corrupt / truncate / magic at a non-byte seam all surface as a
        # corrupt-data error: the seam has no bytes to mangle
        raise InjectedCorruptDataError(
            f"injected corrupt-data fault at {site}", path=path or ""
        )

    def mangle_bytes(self, site: str, path: Optional[str], data: bytes) -> bytes:
        """Byte-level seams (the log reader holds raw bytes): ``truncate``
        tears off the tail, ``magic`` flips the head, other kinds delegate
        to :meth:`check` semantics (raise/delay)."""
        if not self.active:
            return data
        fired: Optional[FaultRule] = None
        with self._lock:
            for r in self._rules:
                if r.matches_target(site, path) and r.should_fire(self._rng):
                    fired = r
                    break
        if fired is None:
            return data
        _count_injection(site, fired.kind)
        if fired.kind == "truncate":
            return data[: max(0, len(data) // 2 - 1)]
        if fired.kind == "magic":
            return (b"XXXX" + data[4:]) if len(data) >= 4 else b"X"
        if fired.kind == "latency":
            time.sleep(fired.delay_s)
            return data
        if fired.kind == "transient":
            raise InjectedTransientIOError(
                f"injected transient fault at {site} ({path or '?'})"
            )
        raise InjectedCorruptDataError(
            f"injected corrupt-data fault at {site}", path=path or ""
        )


#: the process-global registry every seam consults (fast path: one attr read);
#: intentionally process-local — fault injection is a per-process chaos harness
FAULTS = FaultRegistry()  # hscheck: disable=process-local-state


class fault_scope:
    """Install rules for a ``with`` block and restore the prior set after —
    the no-monkeypatching test API. Re-seeds on entry for determinism."""

    def __init__(self, *rules: FaultRule, seed: int = 0):
        self._rules = rules
        self._seed = seed

    def __enter__(self):
        self._prior = FAULTS.rules()
        FAULTS.clear()
        FAULTS.seed(self._seed)
        FAULTS.install(*self._rules)
        return FAULTS

    def __exit__(self, *exc) -> None:
        FAULTS.clear()
        FAULTS.install(*self._prior)


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse the conf-string rule syntax (see module docstring):
    ``site:kind[:glob=PAT][:nth=N][:p=F][:delay=S][:max=N]`` joined by ``;``."""
    rules: List[FaultRule] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"fault spec {part!r}: need site:kind")
        site, kind = fields[0].strip(), fields[1].strip()
        kw: dict = {}
        for opt in fields[2:]:
            k, _, v = opt.partition("=")
            k = k.strip()
            if k == "glob":
                kw["path_glob"] = v
            elif k == "nth":
                kw["nth"] = int(v)
            elif k == "p":
                kw["probability"] = float(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "max":
                kw["max_fires"] = int(v)
            else:
                raise ValueError(f"fault spec {part!r}: unknown option {k!r}")
        rules.append(FaultRule(site, kind, **kw))
    return rules


_CONF_INSTALLED = False


def configure(conf) -> None:
    """Apply a session's ``hyperspace.reliability.faults.*`` conf (called
    from Session construction; most recent session wins, like the decode
    pool). A disabled conf clears only conf-installed rules — a test's
    ``fault_scope`` rules survive a session constructed inside the scope."""
    global _CONF_INSTALLED
    if not conf.reliability_faults_enabled:
        if _CONF_INSTALLED:
            FAULTS.clear()
            _CONF_INSTALLED = False
        return
    FAULTS.clear()
    FAULTS.seed(conf.reliability_faults_seed)
    FAULTS.install(*parse_spec(conf.reliability_faults_spec))
    _CONF_INSTALLED = True
