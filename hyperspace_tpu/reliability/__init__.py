"""Reliability subsystem: fault injection, deadline-aware retries, and
index quarantine with fallback-to-source (docs/reliability.md).

Three layers, all default-off behind ``hyperspace.reliability.*``:

- :mod:`hyperspace_tpu.reliability.faults` — seeded deterministic fault
  injection at the lake IO seams (the chaos harness);
- :mod:`hyperspace_tpu.reliability.retry` — decorrelated-jitter backoff for
  transient IO errors, bounded by the serving request's admission deadline;
- :mod:`hyperspace_tpu.reliability.degrade` — a per-index circuit breaker
  that quarantines an index after repeated corrupt reads, re-planning its
  queries against source until a half-open probe reads clean.

The typed error taxonomy (:class:`TransientIOError` /
:class:`CorruptDataError` / :class:`FaultInjected`) classifies every
lake-IO failure path; its classification counters are always-on (a counter
bump per *error*, nothing on the success path).

:func:`configure` applies a session's conf to the process-global registries
(most recent session wins — the same stance as the decode pool and the HLO
verifier) and is called from ``Session.__init__``.
"""

from __future__ import annotations

from hyperspace_tpu.reliability.errors import (
    CorruptDataError,
    FaultInjected,
    ReliabilityError,
    TransientIOError,
    classify,
    count_io_error,
)
from hyperspace_tpu.reliability.faults import FAULTS, FaultRule, fault_scope
from hyperspace_tpu.reliability.retry import (
    RetryPolicy,
    current_deadline,
    deadline_scope,
    with_retry,
)
from hyperspace_tpu.reliability.degrade import QUARANTINE

__all__ = [
    "CorruptDataError",
    "FAULTS",
    "FaultInjected",
    "FaultRule",
    "QUARANTINE",
    "ReliabilityError",
    "RetryPolicy",
    "TransientIOError",
    "classify",
    "configure",
    "count_io_error",
    "current_deadline",
    "deadline_scope",
    "fault_scope",
    "with_retry",
]


def configure(session) -> None:
    """Apply ``hyperspace.reliability.*`` conf to the process-global fault,
    retry, and quarantine registries."""
    from hyperspace_tpu.reliability import degrade as _degrade
    from hyperspace_tpu.reliability import faults as _faults
    from hyperspace_tpu.reliability import retry as _retry

    _faults.configure(session.conf)
    _retry.configure(session.conf)
    _degrade.configure(session)
