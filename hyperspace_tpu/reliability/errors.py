"""Typed error taxonomy for lake IO failures.

Every failure a lake-touching path can observe classifies into exactly one
of two operational families:

- :class:`TransientIOError` — the read *might* succeed if repeated (network
  blip, NFS hiccup, a racing writer's rename window). Subclasses ``OSError``
  so existing ``except OSError`` fallbacks keep catching injected/classified
  transients without a second handler arm. Retryable under the retry policy.
- :class:`CorruptDataError` — the bytes are wrong (torn write, flipped
  parquet magic, truncated footer). Retrying re-reads the same bad bytes;
  the correct responses are skip-to-prior-version (operation log), index
  quarantine (degrade.py), or a typed query failure (source files).

:class:`FaultInjected` is a marker mixin: errors raised by the
fault-injection harness (faults.py) carry it so the chaos soak can assert
"every failure I saw was one I injected" while the production classifiers
never produce it.

:func:`classify` maps raw third-party exceptions into the taxonomy — the
single routing table every swallow site consults, so transient-vs-corrupt
is decided in one place.
"""

from __future__ import annotations

import json


class ReliabilityError(Exception):
    """Base of the typed lake-IO failure taxonomy."""


class TransientIOError(ReliabilityError, OSError):
    """Possibly-recoverable IO failure; retry may succeed."""


class CorruptDataError(ReliabilityError):
    """The bytes read are not the bytes written; retry cannot help."""

    def __init__(self, message: str = "", path: str = ""):
        super().__init__(message or f"corrupt data: {path}")
        self.path = path


class FaultInjected:
    """Marker mixin for errors raised by the fault-injection harness."""


class InjectedTransientIOError(FaultInjected, TransientIOError):
    pass


class InjectedCorruptDataError(FaultInjected, CorruptDataError):
    pass


#: exception types whose meaning is "the stored bytes are wrong" — decode
#: and parse failures, never connectivity (lazy pa import keeps this module
#: importable without pyarrow)
def _corrupt_types() -> tuple:
    out = [json.JSONDecodeError, KeyError, ValueError]
    try:
        import pyarrow as pa

        out += [pa.ArrowInvalid, pa.ArrowTypeError]
    except Exception:  # pragma: no cover - pyarrow is a baked-in dep
        pass
    return tuple(out)


def classify(exc: BaseException, path: str = "") -> ReliabilityError:
    """Wrap a raw exception as its taxonomy type (already-typed errors pass
    through unchanged). ``OSError`` → transient; parse/decode errors →
    corrupt; anything else stays transient-leaning corrupt-free so an
    unknown failure is never mistaken for bad bytes."""
    if isinstance(exc, ReliabilityError):
        return exc
    if isinstance(exc, _corrupt_types()):
        err = CorruptDataError(f"{type(exc).__name__}: {exc}", path=path)
        err.__cause__ = exc
        return err
    if isinstance(exc, OSError):
        err2 = TransientIOError(str(exc) or type(exc).__name__)
        err2.__cause__ = exc
        return err2
    err3 = TransientIOError(f"{type(exc).__name__}: {exc}")
    err3.__cause__ = exc
    return err3


def is_corrupt(exc: BaseException) -> bool:
    return isinstance(exc, CorruptDataError) or isinstance(exc, _corrupt_types())


def count_io_error(op: str, exc: BaseException, *, swallowed: bool = False) -> None:
    """Classification counter every audit point bumps — even sites that go
    on to a fallback (``swallowed=True``) leave a metric trail instead of
    vanishing. Cheap: one counter inc, no conf lookup."""
    from hyperspace_tpu.obs.metrics import REGISTRY

    kind = "corrupt" if is_corrupt(exc) else (
        "transient" if isinstance(exc, OSError) else "other"
    )
    REGISTRY.counter(
        "hs_io_errors_total",
        "lake IO errors observed, classified by the reliability taxonomy "
        "(handled=fallback-taken vs raised=surfaced to the caller)",
        op=op,
        kind=kind,
        outcome="handled" if swallowed else "raised",
    ).inc()
