"""Deadline-aware retry with decorrelated-jitter backoff.

Policy shape (the AWS architecture-blog "decorrelated jitter" variant):
``sleep_k = min(cap, uniform(base, 3 * sleep_{k-1}))`` — retries spread out
instead of thundering in lockstep, and the cap bounds tail latency. Clock,
sleep, and RNG are injectable so tests run the full policy in zero wall
time and byte-deterministically.

Deadline discipline: a serving worker enters :func:`deadline_scope` with the
request's admission deadline (``serving/server.py`` tracks it from submit).
:meth:`RetryPolicy.call` never sleeps past :func:`current_deadline` — a
retry that cannot complete in budget gives up immediately with the original
typed error (``hs_io_giveups_total{op,reason="deadline"}``), and the request
sheds through the server's existing timeout/shed accounting rather than
burning worker seconds on a doomed read.

Only :class:`TransientIOError` retries. :class:`CorruptDataError` re-reads
the same wrong bytes — it fails fast into degrade.py's quarantine path.

Default-off: ``hyperspace.reliability.retry.enabled`` gates whether
Session-configured call sites wrap reads at all; the disabled path never
constructs a policy.
"""

from __future__ import annotations

import contextvars
import random
import time
from typing import Callable, Optional, TypeVar

from hyperspace_tpu.reliability.errors import (  # noqa: F401  (re-export: the taxonomy lives with retry in the issue's API)
    CorruptDataError,
    FaultInjected,
    ReliabilityError,
    TransientIOError,
    classify,
)

T = TypeVar("T")

#: the active request's absolute monotonic deadline (None = no deadline)
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "hs_reliability_deadline", default=None
)


class deadline_scope:
    """Pin the current request's monotonic deadline for this thread/context.
    Serving workers enter it around plan resolution + execution; nested
    scopes restore the outer deadline on exit."""

    def __init__(self, deadline: Optional[float]):
        self._deadline = deadline

    def __enter__(self):
        self._token = _DEADLINE.set(self._deadline)
        return self

    def __exit__(self, *exc) -> None:
        _DEADLINE.reset(self._token)


def current_deadline() -> Optional[float]:
    return _DEADLINE.get()


def _retry_counter(op: str, reason: str):
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_io_retries_total",
        "transient lake-IO failures retried by the reliability retry policy",
        op=op,
        reason=reason,
    )


def _giveup_counter(op: str, reason: str):
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY.counter(
        "hs_io_giveups_total",
        "retry sequences abandoned (attempts exhausted, or the request "
        "deadline left no budget for another attempt)",
        op=op,
        reason=reason,
    )


class RetryPolicy:
    """Decorrelated-jitter exponential backoff over a callable.

    ``clock``/``sleep``/``rng`` default to the real ones; tests inject a
    fake clock and a seeded RNG for wall-time-free determinism.
    """

    def __init__(
        self,
        max_attempts: int = 4,
        base_s: float = 0.005,
        cap_s: float = 0.1,
        *,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()

    def call(self, fn: Callable[[], T], *, op: str) -> T:
        """Run ``fn``, retrying transient failures within the deadline.
        Corrupt-data errors and non-IO exceptions propagate immediately."""
        prev_sleep = self.base_s
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except CorruptDataError:
                raise
            except FileNotFoundError:
                raise  # ENOENT is deterministic: re-reading cannot help
            except OSError as exc:
                # includes TransientIOError (subclass) and raw transients
                if attempt >= self.max_attempts:
                    _giveup_counter(op, "attempts").inc()
                    raise
                delay = min(self.cap_s, self._rng.uniform(self.base_s, prev_sleep * 3))
                prev_sleep = max(delay, self.base_s)
                deadline = current_deadline()
                if deadline is not None and self._clock() + delay > deadline:
                    _giveup_counter(op, "deadline").inc()
                    raise
                reason = "injected" if isinstance(exc, FaultInjected) else "oserror"
                _retry_counter(op, reason).inc()
                self._sleep(delay)


#: process-global policy serving/session call sites use when retry is
#: enabled; None while disabled (the default) so the gated path costs one
#: "is None" check.
_POLICY: Optional[RetryPolicy] = None


def configure(conf) -> None:
    """Build (or drop) the process-global policy from a session's
    ``hyperspace.reliability.retry.*`` conf. Most recent session wins."""
    global _POLICY
    if not conf.reliability_retry_enabled:
        _POLICY = None
        return
    _POLICY = RetryPolicy(
        max_attempts=conf.reliability_retry_max_attempts,
        base_s=conf.reliability_retry_base_ms / 1000.0,
        cap_s=conf.reliability_retry_cap_ms / 1000.0,
    )


def active_policy() -> Optional[RetryPolicy]:
    return _POLICY


def with_retry(fn: Callable[[], T], *, op: str) -> T:
    """Run ``fn`` under the configured policy, or directly when retry is
    off — the one-liner IO seams call."""
    policy = _POLICY
    if policy is None:
        return fn()
    return policy.call(fn, op=op)
