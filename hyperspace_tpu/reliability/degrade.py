"""Graceful degradation: per-index quarantine circuit breaker.

The paper's core contract is that an index is always *optional* — source
data stays the ground truth. This module enforces that operationally:
repeated :class:`CorruptDataError`\\ s on one index's files trip a breaker
that **quarantines the index**:

- a ``CommitEvent(kind="quarantine")`` publishes on the session's lifecycle
  invalidation bus, so the roster TTL cache and the bucket/IO/device byte
  caches purge any derivative of the bad files;
- the candidate collector stops proposing the index (why-not reason
  ``INDEX_QUARANTINED``), so queries transparently re-plan against source —
  correct answers, just slower;
- after ``cooldownSeconds`` the breaker goes **half-open**: the next
  eligibility check admits the index once as a probe. A clean read of its
  files closes the breaker (un-quarantines); another corrupt read re-trips
  it for a fresh cooldown.

Corruption on *source* files never quarantines anything — there is no
fallback below the ground truth — the query fails with the typed error,
surfaced through ``QueryServer._seal`` into SLO/error metrics.

Default-off: ``hyperspace.reliability.quarantine.enabled`` gates the whole
registry; disabled, every hook is one attribute read.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, List, Optional

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half-open"


def _count_quarantine(index: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_index_quarantined_total",
        "circuit-breaker trips quarantining an index after repeated "
        "corrupt-data errors on its files",
        index=index,
    ).inc()


class _Breaker:
    __slots__ = ("state", "strikes", "tripped_at")

    def __init__(self):
        self.state = _CLOSED
        self.strikes = 0
        self.tripped_at = 0.0


class QuarantineRegistry:
    """Process-global breaker map, configured per session (most recent
    session wins, like the decode pool); holds only a weakref to the
    session so a dropped session never leaks through reliability state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._breakers: Dict[str, _Breaker] = {}
        # strikes merged from fabric peers (coherence sidecar); they count
        # toward the local threshold but are never re-published
        self._remote_strikes: Dict[str, int] = {}
        self._indexes_root: Optional[str] = None
        self._session_ref = lambda: None
        self._threshold = 3
        self._cooldown_s = 30.0
        self._clock = time.monotonic
        self.enabled = False

    def configure(
        self,
        session,
        *,
        enabled: bool,
        threshold: int,
        cooldown_s: float,
        clock=time.monotonic,
    ) -> None:
        with self._lock:
            self.enabled = bool(enabled)
            self._threshold = max(1, int(threshold))
            self._cooldown_s = float(cooldown_s)
            self._clock = clock
            self._session_ref = weakref.ref(session)
            # index layout: <system.path>/<indexName>/... (models/path_resolver.py)
            sys_path = session.conf.system_path
            self._indexes_root = os.path.abspath(str(sys_path)) if sys_path else None
            self._breakers = {}
            self._remote_strikes = {}

    # -- path → index attribution -------------------------------------------
    def index_of_path(self, path: Optional[str]) -> Optional[str]:
        """The index name owning ``path``, or None for source/other files."""
        root = self._indexes_root
        if root is None or not path:
            return None
        p = os.path.abspath(str(path))
        if not p.startswith(root + os.sep):
            return None
        rest = p[len(root) + 1 :]
        name = rest.split(os.sep, 1)[0]
        return name or None

    def _index_files(self, name: str) -> List[str]:
        root = self._indexes_root
        if root is None:
            return []
        out: List[str] = []
        for dirpath, _dirs, files in os.walk(os.path.join(root, name)):
            out.extend(os.path.join(dirpath, f) for f in files)
        return out

    # -- the hooks -----------------------------------------------------------
    def note_corrupt(self, path: Optional[str]) -> Optional[str]:
        """Record a corrupt read of ``path``. Returns the index name if this
        strike tripped (or re-tripped) its quarantine, else None."""
        if not self.enabled:
            return None
        name = self.index_of_path(path)
        if name is None:
            return None
        tripped = False
        with self._lock:
            b = self._breakers.setdefault(name, _Breaker())
            if b.state == _HALF_OPEN:
                # the probe read was corrupt too: straight back to open
                b.state = _OPEN
                b.tripped_at = self._clock()
                tripped = True
            else:
                b.strikes += 1
                effective = b.strikes + self._remote_strikes.get(name, 0)
                if b.state == _CLOSED and effective >= self._threshold:
                    b.state = _OPEN
                    b.tripped_at = self._clock()
                    tripped = True
        if tripped:
            _count_quarantine(name)
            self._publish_quarantine(name)
        return name if tripped else None

    def note_ok(self, path: Optional[str]) -> None:
        """A clean read of ``path``: closes a half-open breaker (the probe
        succeeded) and clears accumulated strikes on a closed one."""
        if not self.enabled:
            return
        name = self.index_of_path(path)
        if name is None:
            return
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                return
            if b.state == _HALF_OPEN:
                b.state = _CLOSED
                b.strikes = 0
            elif b.state == _CLOSED:
                b.strikes = 0

    def is_quarantined(self, name: str) -> bool:
        """Planner eligibility check. An open breaker past its cooldown
        flips to half-open and admits the index once as a probe."""
        if not self.enabled:
            return False
        with self._lock:
            b = self._breakers.get(str(name))
            if b is None or b.state == _CLOSED:
                return False
            if b.state == _HALF_OPEN:
                # one probe is already in flight; stay out of new plans
                return True
            if self._clock() - b.tripped_at >= self._cooldown_s:
                b.state = _HALF_OPEN
                return False
            return True

    def state_of(self, name: str) -> str:
        with self._lock:
            b = self._breakers.get(str(name))
            return b.state if b is not None else _CLOSED

    # -- fabric coherence (hyperspace_tpu/fabric/coherence.py) ---------------
    def local_strikes(self) -> Dict[str, int]:
        """This process's own accumulated strikes per index — what the
        coherence sidecar publishes (remote strikes are excluded so peers
        never echo each other's counts back and forth)."""
        with self._lock:
            return {n: b.strikes for n, b in self._breakers.items() if b.strikes}

    def merge_remote_strikes(self, strikes: Dict[str, int]) -> List[str]:
        """Replace the remote-strike view with the peers' current totals and
        trip any closed breaker whose local+remote count now crosses the
        threshold. Returns the names tripped by this merge. Merged trips are
        deliberately NOT re-published on the bus — the originating process
        already persisted the strikes, and an echo would ping-pong."""
        if not self.enabled:
            return []
        tripped: List[str] = []
        with self._lock:
            self._remote_strikes = {str(k): int(v) for k, v in strikes.items() if int(v) > 0}
            for name, remote in self._remote_strikes.items():
                b = self._breakers.setdefault(name, _Breaker())
                if b.state == _CLOSED and b.strikes + remote >= self._threshold:
                    b.state = _OPEN
                    b.tripped_at = self._clock()
                    tripped.append(name)
        for name in tripped:
            _count_quarantine(name)
        return tripped

    def merge_remote_trip(self, name: str) -> bool:
        """A peer's breaker tripped (its quarantine commit record replayed
        here): open ours too so this process stops planning the index
        immediately. Returns False when it was already open."""
        if not self.enabled:
            return False
        with self._lock:
            b = self._breakers.setdefault(str(name), _Breaker())
            if b.state == _OPEN:
                return False
            b.state = _OPEN
            b.tripped_at = self._clock()
        _count_quarantine(str(name))
        return True

    # -- bus publication -----------------------------------------------------
    def _publish_quarantine(self, name: str) -> None:
        session = self._session_ref()
        if session is None:
            return
        from hyperspace_tpu.lifecycle.invalidation import CommitEvent

        try:
            session.lifecycle_bus.publish(
                CommitEvent(name, None, "quarantine", self._index_files(name))
            )
        except Exception:  # pragma: no cover — a broken bus must not mask the read error
            pass


#: the process-global registry (one-attr fast path while disabled); its
#: strikes/trips are shared across fabric processes by the coherence sidecar
QUARANTINE = QuarantineRegistry()

#: module-level registries whose state the fabric publishes to peers — the
#: process-local-state lint rule exempts these by name
__fabric_published__ = ("QUARANTINE",)


def configure(session) -> None:
    conf = session.conf
    QUARANTINE.configure(
        session,
        enabled=conf.reliability_quarantine_enabled,
        threshold=conf.reliability_quarantine_threshold,
        cooldown_s=conf.reliability_quarantine_cooldown_seconds,
    )
