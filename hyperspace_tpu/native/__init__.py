"""ctypes binding for libhs_native — the native Parquet→buffer decode path.

The TPU framework's ground-up native component (SURVEY.md §7: "C++ Parquet
column-chunk decode path into device-feedable buffers"; the reference is 100%
JVM — SURVEY.md §0 — so this has no reference counterpart). Columns decode
from an mmap'd file directly into numpy arrays that ``jax.device_put`` can
ship to HBM with no intermediate pyarrow tables or row pivoting.

The shared library is compiled on demand with g++ (``native/Makefile``); when
the toolchain or the file's encoding is outside the native dialect
(compressed/nested/v2-specific shapes), callers fall back to pyarrow via
``NativeUnsupported``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, "libhs_native.so")
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "native")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed: Optional[str] = None


class NativeUnsupported(Exception):
    """The native decoder cannot handle this file; fall back to pyarrow."""


def _build() -> None:
    src = os.path.join(_SRC_DIR, "hs_native.cc")
    if not os.path.exists(src):
        raise NativeUnsupported("native sources not present")
    base = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-shared",
        src,
    ]
    # gzip/zstd decode link the system zlib/libzstd; a host missing either
    # dev package must not lose the whole native path — rebuild without that
    # codec instead. Only a genuinely missing library justifies dropping it;
    # any other failure (transient OOM, bad flag) must surface.
    def _missing(stderr: str, lib: str, header: str) -> bool:
        # GNU ld, lld, ld64 and gcc/clang all word this differently. The lib
        # name must match as a whole word: 'cannot find -lz' is a substring
        # of 'cannot find -lzstd', and matching it would drop zlib on hosts
        # that are only missing libzstd.
        import re

        pats = (
            rf"cannot find -l{lib}\b",  # GNU ld
            rf"unable to find library -l{lib}\b",  # lld
            rf"library '{lib}' not found",  # ld64 (macOS)
            rf"library not found for -l{lib}\b",  # older ld64
            rf"-l{lib}\b: not found",
        )
        if any(re.search(p, stderr) for p in pats):
            return True
        return header in stderr and ("No such file" in stderr or "not found" in stderr)

    flags: List[str] = ["-lz", "-lzstd"]
    dropped: List[str] = []
    res = subprocess.run(
        base + flags + ["-o", _SO_PATH], capture_output=True, text=True, cwd=_SRC_DIR
    )
    if res.returncode != 0 and _missing(res.stderr, "zstd", "zstd.h"):
        # the dev package (zstd.h + libzstd.so symlink) is absent but the
        # runtime library often still is: declare ZSTD's stable ABI by hand
        # (-DHS_ZSTD_COMPAT) and link the versioned soname before dropping
        # the codec outright
        compat = [f if f != "-lzstd" else "-l:libzstd.so.1" for f in flags]
        res2 = subprocess.run(
            base + ["-DHS_ZSTD_COMPAT"] + compat + ["-o", _SO_PATH],
            capture_output=True,
            text=True,
            cwd=_SRC_DIR,
        )
        if res2.returncode == 0:
            res = res2
            flags = compat
    for lib, header, define in (("z", "zlib.h", "-DHS_NO_ZLIB"),
                                ("zstd", "zstd.h", "-DHS_NO_ZSTD")):
        if res.returncode == 0:
            break
        if not _missing(res.stderr, lib, header):
            continue
        flags = [f for f in flags if f != f"-l{lib}"] + [define]
        dropped.append(lib)
        res = subprocess.run(
            base + flags + ["-o", _SO_PATH], capture_output=True, text=True, cwd=_SRC_DIR
        )
    if res.returncode == 0 and dropped:
        logging.getLogger(__name__).warning(
            "hs_native built without %s support (missing on this host)",
            "/".join(dropped),
        )
    if res.returncode != 0:
        raise NativeUnsupported(f"native build failed: {res.stderr[-2000:]}")


def _load() -> ctypes.CDLL:
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed is not None:
            raise NativeUnsupported(_load_failed)
        try:
            srcs = [
                os.path.join(_SRC_DIR, "hs_native.cc"),
                os.path.join(_SRC_DIR, "thrift_compact.h"),
            ]
            if not os.path.exists(_SO_PATH) or any(
                os.path.exists(s) and os.path.getmtime(s) > os.path.getmtime(_SO_PATH)
                for s in srcs
            ):
                _build()
            lib = ctypes.CDLL(_SO_PATH)
        except NativeUnsupported as e:
            _load_failed = str(e)
            raise
        except OSError as e:
            _load_failed = f"cannot load libhs_native: {e}"
            raise NativeUnsupported(_load_failed)
        try:
            _wire_symbols(lib)
        except AttributeError:
            # stale prebuilt .so missing newer symbols: rebuild once, then
            # give up via NativeUnsupported (callers fall back) rather than
            # leaking AttributeError through every native call site
            try:
                _build()
                lib = ctypes.CDLL(_SO_PATH)
                _wire_symbols(lib)
            except (NativeUnsupported, OSError, AttributeError) as e:
                _load_failed = f"libhs_native is stale and rebuild failed: {e}"
                raise NativeUnsupported(_load_failed)
        _lib = lib
        return lib


def _wire_symbols(lib: ctypes.CDLL) -> None:
        lib.hsn_open.restype = ctypes.c_void_p
        lib.hsn_open.argtypes = [ctypes.c_char_p]
        lib.hsn_close.argtypes = [ctypes.c_void_p]
        lib.hsn_error.restype = ctypes.c_char_p
        lib.hsn_error.argtypes = [ctypes.c_void_p]
        lib.hsn_num_rows.restype = ctypes.c_int64
        lib.hsn_num_rows.argtypes = [ctypes.c_void_p]
        lib.hsn_num_columns.restype = ctypes.c_int32
        lib.hsn_num_columns.argtypes = [ctypes.c_void_p]
        lib.hsn_column_name.restype = ctypes.c_char_p
        lib.hsn_column_name.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.hsn_column_type.restype = ctypes.c_int32
        lib.hsn_column_type.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.hsn_column_optional.restype = ctypes.c_int32
        lib.hsn_column_optional.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.hsn_read_fixed.restype = ctypes.c_int64
        lib.hsn_read_fixed.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.hsn_read_binary.restype = ctypes.c_int64
        lib.hsn_read_binary.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        # row-group-granular ABI (parallel decode; errors via per-call buffer)
        lib.hsn_num_row_groups.restype = ctypes.c_int32
        lib.hsn_num_row_groups.argtypes = [ctypes.c_void_p]
        lib.hsn_rg_num_rows.restype = ctypes.c_int64
        lib.hsn_rg_num_rows.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.hsn_rg_codec.restype = ctypes.c_int32
        lib.hsn_rg_codec.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        lib.hsn_read_fixed_rg.restype = ctypes.c_int64
        lib.hsn_read_fixed_rg.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.hsn_read_binary_rg.restype = ctypes.c_int64
        lib.hsn_read_binary_rg.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.hsn_read_codes_rg.restype = ctypes.c_int64
        lib.hsn_read_codes_rg.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.hsn_rg_dict_count.restype = ctypes.c_int64
        lib.hsn_rg_dict_count.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.hsn_read_dict_binary_rg.restype = ctypes.c_int64
        lib.hsn_read_dict_binary_rg.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
        ]
        lib.hsn_merge_spans.restype = None
        lib.hsn_merge_spans.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.hsn_expand_pairs.restype = ctypes.c_int64
        lib.hsn_expand_pairs.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.hsn_snappy_decompress.restype = ctypes.c_int32
        lib.hsn_snappy_decompress.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.hsn_snappy_uncompressed_length.restype = ctypes.c_int64
        lib.hsn_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, ctypes.c_int64]


def snappy_decompress(blob: bytes) -> bytes:
    """Raw-snappy decompression via the native library; raises
    NativeUnsupported when the library is unavailable (callers fall back to
    the pure-Python decoder in utils/avro.py)."""
    lib = _load()
    n = lib.hsn_snappy_uncompressed_length(blob, len(blob))
    if n < 0:
        raise ValueError("snappy: bad length header")
    # the varint comes from untrusted input: a corrupt header must not drive
    # a multi-GB allocation (snappy can expand at most ~255x per the format's
    # max copy/literal ratios; 1 GiB also caps any legitimate Avro block)
    if n > max(len(blob) * 256, 1 << 30):
        raise ValueError(f"snappy: implausible uncompressed length {n}")
    out = ctypes.create_string_buffer(n)
    if lib.hsn_snappy_decompress(blob, len(blob), out, n) != 0:
        raise ValueError("snappy: malformed input")
    return out.raw


# parquet physical types
_T_BOOLEAN, _T_INT32, _T_INT64 = 0, 1, 2
_T_FLOAT, _T_DOUBLE, _T_BYTE_ARRAY = 4, 5, 6

_FIXED_DTYPES = {
    _T_BOOLEAN: np.dtype(np.bool_),
    _T_INT32: np.dtype(np.int32),
    _T_INT64: np.dtype(np.int64),
    _T_FLOAT: np.dtype(np.float32),
    _T_DOUBLE: np.dtype(np.float64),
}

#: per-call error buffer size for the row-group ABI (the C side truncates)
_ERR_CAP = 256

#: parquet CompressionCodec ids the dialect decodes, as metric-label names
CODEC_NAMES = {0: "uncompressed", 1: "snappy", 2: "gzip", 6: "zstd"}


class NativeParquetFile:
    """One open parquet file. Use as a context manager."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._h = lib.hsn_open(path.encode())
        if not self._h:
            raise NativeUnsupported(f"cannot open {path!r} natively")
        err = lib.hsn_error(self._h)
        if err:
            msg = err.decode()
            lib.hsn_close(self._h)
            self._h = None
            raise NativeUnsupported(msg)
        self.num_rows = lib.hsn_num_rows(self._h)
        self.columns: List[str] = []
        self._types: List[int] = []
        for i in range(lib.hsn_num_columns(self._h)):
            self.columns.append(lib.hsn_column_name(self._h, i).decode())
            self._types.append(lib.hsn_column_type(self._h, i))
        self.num_row_groups = int(lib.hsn_num_row_groups(self._h))
        #: rows per row group, in file order (row-group g starts at
        #: sum(rg_rows[:g]) within the file)
        self.rg_rows: List[int] = [
            int(lib.hsn_rg_num_rows(self._h, g)) for g in range(self.num_row_groups)
        ]

    def __enter__(self) -> "NativeParquetFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._h:
            self._lib.hsn_close(self._h)
            self._h = None

    def _err(self) -> str:
        e = self._lib.hsn_error(self._h)
        return e.decode() if e else "unknown native error"

    def read_column(self, name: str) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Decode one column. Returns (values, validity-or-None). Fixed-width
        columns come back as their numpy dtype; BYTE_ARRAY as an object array
        of ``str``. Rows that were null have zero/empty values and validity 0."""
        if name not in self.columns:
            raise NativeUnsupported(f"column {name!r} not in file")
        col = self.columns.index(name)
        t = self._types[col]
        n = self.num_rows
        optional = self._lib.hsn_column_optional(self._h, col) == 1
        validity = np.ones(n, dtype=np.uint8) if optional else None
        vptr = validity.ctypes.data_as(ctypes.c_void_p) if validity is not None else None

        if t in _FIXED_DTYPES:
            out = np.empty(n, dtype=_FIXED_DTYPES[t])
            rc = self._lib.hsn_read_fixed(self._h, col, out.ctypes.data_as(ctypes.c_void_p), vptr)
            if rc != n:
                raise NativeUnsupported(self._err())
            return out, validity
        if t == _T_BYTE_ARRAY:
            offsets = np.empty(n + 1, dtype=np.int64)
            rc = self._lib.hsn_read_binary(
                self._h, col, offsets.ctypes.data_as(ctypes.c_void_p), None, vptr
            )
            if rc != n:
                raise NativeUnsupported(self._err())
            data = np.empty(int(offsets[n]), dtype=np.uint8)
            rc = self._lib.hsn_read_binary(
                self._h,
                col,
                offsets.ctypes.data_as(ctypes.c_void_p),
                data.ctypes.data_as(ctypes.c_void_p),
                vptr,
            )
            if rc != n:
                raise NativeUnsupported(self._err())
            # zero-copy arrow view over (offsets, data); arrow's C++ loop then
            # materializes the python strings — ~5x faster than a python loop
            import pyarrow as pa

            arr = pa.Array.from_buffers(
                pa.large_utf8(), n, [None, pa.py_buffer(offsets), pa.py_buffer(data)]
            )
            out = arr.to_numpy(zero_copy_only=False)
            return out, validity
        raise NativeUnsupported(f"unsupported physical type {t}")

    # -- row-group-granular decode (parallel fan-out) -------------------------

    def _col_index(self, name: str) -> int:
        if name not in self.columns:
            raise NativeUnsupported(f"column {name!r} not in file")
        return self.columns.index(name)

    def column_optional(self, name: str) -> bool:
        return self._lib.hsn_column_optional(self._h, self._col_index(name)) == 1

    def column_numpy_dtype(self, name: str) -> Optional[np.dtype]:
        """Decoded numpy dtype for a column, or None for BYTE_ARRAY (strings
        materialize as object arrays, which have no flat buffer to decode
        into). Raises NativeUnsupported for physical types outside the dialect."""
        t = self._types[self._col_index(name)]
        if t in _FIXED_DTYPES:
            return _FIXED_DTYPES[t]
        if t == _T_BYTE_ARRAY:
            return None
        raise NativeUnsupported(f"unsupported physical type {t}")

    def rg_codec(self, rg: int, name: str) -> str:
        """Codec name of one chunk ("uncompressed"/"snappy"/"gzip"/"zstd"),
        or "other" for ids outside the dialect."""
        c = self._lib.hsn_rg_codec(self._h, rg, self._col_index(name))
        return CODEC_NAMES.get(int(c), "other")

    def read_fixed_rg_into(
        self, rg: int, name: str, out: np.ndarray, validity: Optional[np.ndarray] = None
    ) -> None:
        """Decode one (row group × column) chunk into ``out`` — typically a
        slice of a larger per-column buffer; the C side writes through the
        slice's data pointer, so the caller controls the row offset and
        parallel workers fill disjoint slots of one shared array."""
        col = self._col_index(name)
        t = self._types[col]
        if t not in _FIXED_DTYPES:
            raise NativeUnsupported(f"not a fixed-width column: {name!r}")
        n = self.rg_rows[rg]
        if out.shape[0] != n or out.dtype.itemsize != _FIXED_DTYPES[t].itemsize:
            raise ValueError(
                f"read_fixed_rg_into: buffer shape {out.shape}/{out.dtype} does "
                f"not match row group ({n} rows of {_FIXED_DTYPES[t]})"
            )
        if not out.flags["C_CONTIGUOUS"] or not out.flags["WRITEABLE"]:
            raise ValueError("read_fixed_rg_into: need a contiguous writable buffer")
        vptr = validity.ctypes.data_as(ctypes.c_void_p) if validity is not None else None
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self._lib.hsn_read_fixed_rg(
            self._h, rg, col, out.ctypes.data_as(ctypes.c_void_p), vptr, err, _ERR_CAP
        )
        if rc != n:
            raise NativeUnsupported(err.value.decode() or "native row-group decode failed")

    def read_binary_rg(
        self, rg: int, name: str
    ) -> Tuple[np.ndarray, Optional[np.ndarray], int]:
        """Decode one BYTE_ARRAY chunk to (object array of str, validity,
        utf8 payload bytes)."""
        col = self._col_index(name)
        if self._types[col] != _T_BYTE_ARRAY:
            raise NativeUnsupported(f"not a BYTE_ARRAY column: {name!r}")
        n = self.rg_rows[rg]
        optional = self._lib.hsn_column_optional(self._h, col) == 1
        validity = np.ones(n, dtype=np.uint8) if optional else None
        vptr = validity.ctypes.data_as(ctypes.c_void_p) if validity is not None else None
        offsets = np.empty(n + 1, dtype=np.int64)
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self._lib.hsn_read_binary_rg(
            self._h, rg, col, offsets.ctypes.data_as(ctypes.c_void_p), None, vptr,
            err, _ERR_CAP,
        )
        if rc != n:
            raise NativeUnsupported(err.value.decode() or "native row-group decode failed")
        data = np.empty(int(offsets[n]), dtype=np.uint8)
        rc = self._lib.hsn_read_binary_rg(
            self._h,
            rg,
            col,
            offsets.ctypes.data_as(ctypes.c_void_p),
            data.ctypes.data_as(ctypes.c_void_p),
            vptr,
            err,
            _ERR_CAP,
        )
        if rc != n:
            raise NativeUnsupported(err.value.decode() or "native row-group decode failed")
        import pyarrow as pa

        arr = pa.Array.from_buffers(
            pa.large_utf8(), n, [None, pa.py_buffer(offsets), pa.py_buffer(data)]
        )
        return arr.to_numpy(zero_copy_only=False), validity, int(offsets[n])

    def read_codes_rg(self, rg: int, name: str) -> np.ndarray:
        """Dictionary codes (int32; -1 = null) for a fully dictionary-encoded
        chunk. Raises NativeUnsupported when any page fell back to PLAIN —
        callers retry with value decode."""
        col = self._col_index(name)
        n = self.rg_rows[rg]
        codes = np.empty(n, dtype=np.int32)
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self._lib.hsn_read_codes_rg(
            self._h, rg, col, codes.ctypes.data_as(ctypes.c_void_p), err, _ERR_CAP
        )
        if rc != n:
            raise NativeUnsupported(err.value.decode() or "native codes decode failed")
        return codes

    def rg_dict_count(self, rg: int, name: str) -> int:
        """Dictionary entry count for a chunk (0 = no dictionary page)."""
        col = self._col_index(name)
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self._lib.hsn_rg_dict_count(self._h, rg, col, err, _ERR_CAP)
        if rc < 0:
            raise NativeUnsupported(err.value.decode() or "native dict probe failed")
        return int(rc)

    def read_dict_rg(self, rg: int, name: str) -> np.ndarray:
        """The BYTE_ARRAY dictionary payload of one chunk as an object array
        of str (entry i is the value behind code i)."""
        return self.read_dict_rg_arrow(rg, name).to_numpy(zero_copy_only=False)

    def read_dict_rg_arrow(self, rg: int, name: str):
        """The BYTE_ARRAY dictionary payload of one chunk as an arrow
        large_utf8 Array over the decoder's buffers — no per-entry Python
        string is materialized, so dictionary merges across many chunks stay
        in C (callers concat + dictionary_encode arrow-side)."""
        col = self._col_index(name)
        if self._types[col] != _T_BYTE_ARRAY:
            raise NativeUnsupported(f"not a BYTE_ARRAY column: {name!r}")
        err = ctypes.create_string_buffer(_ERR_CAP)
        count = self._lib.hsn_rg_dict_count(self._h, rg, col, err, _ERR_CAP)
        if count < 0:
            raise NativeUnsupported(err.value.decode() or "native dict probe failed")
        offsets = np.empty(int(count) + 1, dtype=np.int64)
        rc = self._lib.hsn_read_dict_binary_rg(
            self._h, rg, col, offsets.ctypes.data_as(ctypes.c_void_p), None, err, _ERR_CAP
        )
        if rc != count:
            raise NativeUnsupported(err.value.decode() or "native dict decode failed")
        data = np.empty(int(offsets[count]), dtype=np.uint8)
        rc = self._lib.hsn_read_dict_binary_rg(
            self._h,
            rg,
            col,
            offsets.ctypes.data_as(ctypes.c_void_p),
            data.ctypes.data_as(ctypes.c_void_p),
            err,
            _ERR_CAP,
        )
        if rc != count:
            raise NativeUnsupported(err.value.decode() or "native dict decode failed")
        import pyarrow as pa

        return pa.Array.from_buffers(
            pa.large_utf8(), int(count), [None, pa.py_buffer(offsets), pa.py_buffer(data)]
        )


def read_columns(path: str, columns: List[str], dtype_hints: Optional[Dict[str, np.dtype]] = None) -> Dict[str, np.ndarray]:
    """Decode ``columns`` of ``path`` into a host batch (dict of numpy arrays).

    ``dtype_hints`` maps column name -> desired numpy dtype (e.g. datetime64
    views of INT64 timestamps); the raw decoded int64 array is reinterpreted
    via ``.view`` when widths match.
    """
    hints = dtype_hints or {}
    out: Dict[str, np.ndarray] = {}
    with NativeParquetFile(path) as f:
        for c in columns:
            values, validity = f.read_column(c)
            hint = hints.get(c)
            if hint is not None and values.dtype.kind in ("i", "u"):
                if hint.itemsize == values.dtype.itemsize:
                    values = values.view(hint)
                elif hint.kind == "M":
                    # int32-backed date32 widens to datetime64[D] (astype
                    # treats ints as counts of the target unit since epoch)
                    values = values.astype(hint)
            if validity is not None and not validity.all():
                if values.dtype.kind == "f":
                    values = values.copy()
                    values[validity == 0] = np.nan
                elif values.dtype == object:
                    values[validity == 0] = None
                elif values.dtype.kind == "M":
                    values = values.copy()
                    values[validity == 0] = np.datetime64("NaT")
                elif values.dtype.kind == "b":
                    # match pyarrow's to_numpy: nullable bools surface as
                    # object arrays of True/False/None
                    values = values.astype(object)
                    values[validity == 0] = None
                elif values.dtype.kind in ("i", "u"):
                    # match pyarrow's to_numpy: nullable ints surface as
                    # float64 with NaN holes
                    values = values.astype(np.float64)
                    values[validity == 0] = np.nan
            out[c] = values
    return out


def is_available() -> bool:
    try:
        _load()
        return True
    except NativeUnsupported:
        return False


def merge_spans(left_keys: np.ndarray, right_keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per left row, the [lo, hi) span of equal keys in ``right_keys``.

    Both arrays must be ascending int64 (the index dialect's per-bucket
    sortedness). One O(n+m) merge walk in C, replacing two binary-search
    passes. Raises NativeUnsupported when the library is unavailable or a
    side exceeds int32 indexing."""
    lib = _load()
    lk = np.ascontiguousarray(left_keys, dtype=np.int64)
    rk = np.ascontiguousarray(right_keys, dtype=np.int64)
    if rk.shape[0] >= 2**31 or lk.shape[0] >= 2**31:
        raise NativeUnsupported("bucket exceeds int32 indexing")
    lo = np.empty(lk.shape[0], dtype=np.int32)
    hi = np.empty(lk.shape[0], dtype=np.int32)
    lib.hsn_merge_spans(
        lk.ctypes.data_as(ctypes.c_void_p),
        lk.shape[0],
        rk.ctypes.data_as(ctypes.c_void_p),
        rk.shape[0],
        lo.ctypes.data_as(ctypes.c_void_p),
        hi.ctypes.data_as(ctypes.c_void_p),
    )
    return lo, hi


def expand_pairs(lo: np.ndarray, hi: np.ndarray, total: int) -> Tuple[np.ndarray, np.ndarray]:
    """Expand per-left-row spans into (left, right) gather index arrays of
    length ``total`` (= sum(hi - lo)). Raises NativeUnsupported past int32
    range (callers fall back to the int64 numpy expansion)."""
    lib = _load()
    n = int(np.shape(lo)[0])
    if (
        n >= 2**31
        or total >= 2**31
        or (n and int(np.max(hi)) >= 2**31)
    ):
        raise NativeUnsupported("join bucket exceeds int32 indexing")
    lo32 = np.ascontiguousarray(lo, dtype=np.int32)
    hi32 = np.ascontiguousarray(hi, dtype=np.int32)
    lidx = np.empty(total, dtype=np.int32)
    ridx = np.empty(total, dtype=np.int32)
    written = lib.hsn_expand_pairs(
        lo32.ctypes.data_as(ctypes.c_void_p),
        hi32.ctypes.data_as(ctypes.c_void_p),
        lo32.shape[0],
        lidx.ctypes.data_as(ctypes.c_void_p),
        ridx.ctypes.data_as(ctypes.c_void_p),
    )
    if written != total:
        raise NativeUnsupported(f"expand_pairs wrote {written} of {total} pairs")
    return lidx, ridx
