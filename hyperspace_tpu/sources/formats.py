"""Dataset opening for every supported file format.

The reference's default source accepts the formats listed in
``spark.hyperspace.index.sources.fileBasedBuilders``'s default provider —
avro, csv, json, orc, parquet, text (ref: HS/util/HyperspaceConf.scala:94-99).
pyarrow's dataset layer natively covers parquet/csv/json/orc; Avro object
container files are decoded with the framework's own codec
(``utils/avro.py``, shared with the Iceberg manifest reader) and ``text``
reads each line into a single ``value`` string column (Spark text-source
semantics), both materialized as in-memory arrow datasets.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.dataset as pads

#: formats pyarrow.dataset handles directly from file bytes
ARROW_NATIVE_FORMATS = ("parquet", "csv", "json", "orc")
#: formats decoded by this module into in-memory tables
MATERIALIZED_FORMATS = ("avro", "text")
SUPPORTED_FORMATS = ARROW_NATIVE_FORMATS + MATERIALIZED_FORMATS

TEXT_COLUMN = "value"


def _avro_primitive_to_arrow(schema: Any) -> Optional[pa.DataType]:
    if isinstance(schema, str):
        return {
            "null": pa.null(),
            "boolean": pa.bool_(),
            "int": pa.int32(),
            "long": pa.int64(),
            "float": pa.float32(),
            "double": pa.float64(),
            "bytes": pa.binary(),
            "string": pa.string(),
        }.get(schema)
    return None


def _avro_to_arrow_type(schema: Any) -> pa.DataType:
    prim = _avro_primitive_to_arrow(schema)
    if prim is not None:
        return prim
    if isinstance(schema, list):  # union: use the first non-null branch
        branches = [b for b in schema if b != "null"]
        return _avro_to_arrow_type(branches[0]) if branches else pa.null()
    if isinstance(schema, dict):
        t = schema.get("type")
        if t == "record":
            return pa.struct(
                [pa.field(f["name"], _avro_to_arrow_type(f["type"])) for f in schema.get("fields", [])]
            )
        if t == "array":
            return pa.list_(_avro_to_arrow_type(schema["items"]))
        if t == "map":
            return pa.map_(pa.string(), _avro_to_arrow_type(schema["values"]))
        if t == "enum":
            return pa.string()
        if t == "fixed":
            return pa.binary(int(schema["size"]))
        prim = _avro_primitive_to_arrow(t)
        if prim is not None:
            return prim
    raise ValueError(f"Unsupported Avro schema for arrow conversion: {schema!r}")


def _avro_arrow_schema(avro_schema: Dict[str, Any]) -> pa.Schema:
    if avro_schema.get("type") != "record":
        raise ValueError("Avro data files must have a record top-level schema")
    return pa.schema(
        [pa.field(f["name"], _avro_to_arrow_type(f["type"])) for f in avro_schema.get("fields", [])]
    )


def read_avro_table(path: str, columns: Optional[List[str]] = None) -> pa.Table:
    from hyperspace_tpu.utils.avro import read_container

    schema, records = read_container(path)
    t = pa.Table.from_pylist(records, schema=_avro_arrow_schema(schema))
    if columns is not None:
        # a requested column absent from this file (schema evolution) is
        # null-filled, matching the native formats' dataset-level behavior
        arrays, fields = [], []
        for c in columns:
            if c in t.schema.names:
                arrays.append(t.column(c))
                fields.append(t.schema.field(c))
            else:
                arrays.append(pa.nulls(t.num_rows))
                fields.append(pa.field(c, pa.null()))
        t = pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    return t


def read_text_table(path: str, columns: Optional[List[str]] = None) -> pa.Table:
    with io.open(path, "r", encoding="utf-8", newline="") as f:
        data = f.read()
    lines = data.split("\n")
    if lines and lines[-1] == "":  # trailing newline does not create a row
        lines.pop()
    lines = [ln[:-1] if ln.endswith("\r") else ln for ln in lines]
    t = pa.table({TEXT_COLUMN: pa.array(lines, type=pa.string())})
    if columns is not None:
        t = t.select(columns)
    return t


def write_text(path: str, lines: List[str]) -> None:
    with io.open(path, "w", encoding="utf-8", newline="") as f:
        for ln in lines:
            f.write(ln)
            f.write("\n")


def arrow_format(file_format: str, options: Optional[Dict[str, Any]] = None):
    """The pyarrow dataset ``format`` argument honoring reader options.

    CSV supports ``delimiter``/``sep`` and ``header`` (default true; false
    autogenerates ``f0..fN`` column names). Unknown options are ignored, as
    are options on formats that take none here."""
    if file_format == "csv" and options:
        from pyarrow import csv as pacsv

        parse = pacsv.ParseOptions(delimiter=str(options.get("delimiter", options.get("sep", ","))))
        header = options.get("header", True)
        if isinstance(header, str):
            header = header.strip().lower() in ("true", "1", "yes")
        read = pacsv.ReadOptions(autogenerate_column_names=not header)
        return pads.CsvFileFormat(parse_options=parse, read_options=read)
    return file_format


def read_table(
    path: str,
    file_format: str,
    columns: Optional[List[str]] = None,
    options: Optional[Dict[str, Any]] = None,
) -> pa.Table:
    """One file -> arrow table (column-pruned at decode when the format allows)."""
    if file_format == "avro":
        return read_avro_table(path, columns)
    if file_format == "text":
        return read_text_table(path, columns)
    return pads.dataset([path], format=arrow_format(file_format, options)).to_table(columns=columns)


def _align_to_schema(t: pa.Table, schema: pa.Schema) -> pa.Table:
    """Project ``t`` onto ``schema``: cast common columns, null-fill absent
    ones (schema evolution across files)."""
    arrays = []
    for field in schema:
        if field.name in t.schema.names:
            arrays.append(t.column(field.name).cast(field.type))
        else:
            arrays.append(pa.nulls(t.num_rows, type=field.type))
    return pa.Table.from_arrays(arrays, schema=schema)


def tables_to_dataset(tables: List[pa.Table]) -> pads.Dataset:
    """In-memory dataset over per-file tables with one unified schema."""
    if not tables:
        empty = pa.schema([])
        return pads.dataset([pa.Table.from_arrays([], schema=empty)], schema=empty)
    schema = pa.unify_schemas([t.schema for t in tables])
    return pads.dataset([_align_to_schema(t, schema) for t in tables], schema=schema)


def open_dataset(
    files: List[str], file_format: str, options: Optional[Dict[str, Any]] = None
) -> pads.Dataset:
    """``files`` -> a pyarrow Dataset regardless of format.

    Native formats stream from file bytes; materialized formats (avro/text)
    are decoded up front into an in-memory dataset with a unified schema.
    """
    if file_format in ARROW_NATIVE_FORMATS:
        return pads.dataset(files, format=arrow_format(file_format, options))
    if file_format not in MATERIALIZED_FORMATS:
        raise ValueError(f"Unsupported file format: {file_format!r}")
    return tables_to_dataset([read_table(f, file_format) for f in files])


def count_rows(path: str, file_format: str, options: Optional[Dict[str, Any]] = None) -> int:
    if file_format in ARROW_NATIVE_FORMATS:
        return pads.dataset([path], format=arrow_format(file_format, options)).count_rows()
    if file_format == "avro":
        # block headers carry record counts; no payload is decompressed
        from hyperspace_tpu.utils.avro import count_records

        return count_records(path)
    if file_format == "text":
        n = 0
        last = b""
        with open(path, "rb") as f:
            while True:  # stream: bounded memory on arbitrarily large files
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                n += chunk.count(b"\n")
                last = chunk[-1:]
        if last and last != b"\n":
            n += 1  # last line without trailing newline is still a row
        return n
    raise ValueError(f"Unsupported file format: {file_format!r}")


def read_format_schema(files: List[str], file_format: str) -> pa.Schema:
    """Unified schema of a materialized-format dataset WITHOUT decoding any
    record data: avro from container headers, text is constant."""
    if file_format == "text":
        return pa.schema([pa.field(TEXT_COLUMN, pa.string())])
    if file_format == "avro":
        from hyperspace_tpu.utils.avro import read_schema

        return pa.unify_schemas([_avro_arrow_schema(read_schema(f)) for f in files])
    raise ValueError(f"read_format_schema only covers {MATERIALIZED_FORMATS}, got {file_format!r}")
