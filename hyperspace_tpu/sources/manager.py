"""Source-provider manager.

Loads comma-separated builder classes from conf and dispatches each SPI call,
enforcing that exactly one provider answers
(ref: HS/index/sources/FileBasedSourceProviderManager.scala:38-174).
"""

from __future__ import annotations

import importlib
from typing import List, Optional

from hyperspace_tpu.models.log_entry import Relation
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedRelationMetadata,
    FileBasedSourceProvider,
)


class HyperspaceException(Exception):
    pass


def _load_class(dotted: str):
    module_name, _, cls_name = dotted.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)


class FileBasedSourceProviderManager:
    def __init__(self, session):
        self._session = session
        self._providers: Optional[List[FileBasedSourceProvider]] = None
        self._built_from: Optional[str] = None

    def providers(self) -> List[FileBasedSourceProvider]:
        raw = self._session.conf.source_builders
        if self._providers is None or raw != self._built_from:
            self._providers = [
                _load_class(name.strip())().build(self._session)
                for name in raw.split(",")
                if name.strip()
            ]
            self._built_from = raw
        return self._providers

    def _run_single(self, fn_name: str, *args):
        answers = []
        for p in self.providers():
            result = getattr(p, fn_name)(*args, self._session)
            if result is not None:
                answers.append(result)
        if len(answers) != 1:
            raise HyperspaceException(
                f"Expected exactly one source provider to handle {fn_name}; got {len(answers)}."
            )
        return answers[0]

    def create_relation(self, path_or_plan) -> FileBasedRelation:
        return self._run_single("create_relation", path_or_plan)

    def create_relation_metadata(self, relation: Relation) -> FileBasedRelationMetadata:
        return self._run_single("create_relation_metadata", relation)
