"""Default file-based source provider: Parquet (and CSV/JSON via pyarrow)
datasets on local/fuse-mounted lake storage
(ref: HS/index/sources/default/DefaultFileBasedSource.scala:37-124,
DefaultFileBasedRelation.scala:38).
"""

from __future__ import annotations

import glob as globlib
import os
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads

from hyperspace_tpu.models.log_entry import Content, FileInfo, Relation, Storage
from hyperspace_tpu.sources import partitions
from hyperspace_tpu.sources import schema as schema_codec
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedRelationMetadata,
    FileBasedSourceProvider,
)
from hyperspace_tpu.sources.signatures import file_based_signature
from hyperspace_tpu.sources import formats
from hyperspace_tpu.sources.formats import (
    MATERIALIZED_FORMATS,
    SUPPORTED_FORMATS,
    read_format_schema,
    read_table,
    tables_to_dataset,
)


def _list_data_files(root: str) -> List[str]:
    from hyperspace_tpu.utils.file_utils import walk_data_files

    return sorted(walk_data_files(root))


class DefaultFileBasedRelation(FileBasedRelation):
    def __init__(self, root_paths: List[str], file_format: str, options: Optional[Dict[str, str]] = None,
                 files: Optional[List[str]] = None):
        self._root_paths = [os.path.abspath(p) for p in root_paths]
        self._file_format = file_format
        self._options = dict(options or {})
        if files is not None:
            self._files = sorted(os.path.abspath(f) for f in files)
        else:
            self._files = []
            for p in self._root_paths:
                if os.path.isdir(p):
                    self._files.extend(_list_data_files(p))
                elif globlib.has_magic(p):
                    for m in sorted(globlib.glob(p)):
                        if os.path.isdir(m):
                            self._files.extend(_list_data_files(m))
                        else:
                            self._files.append(os.path.abspath(m))
                else:
                    self._files.append(p)
        if not self._files:
            raise FileNotFoundError(f"No data files under {root_paths!r}")
        self._schema: Optional[pa.Schema] = None
        # hive-style partition discovery (.../col=value/... segments); single
        # root only, so arrow_dataset() can serve the same partition columns
        # (multi-root layouts are treated as unpartitioned, like Spark
        # without an explicit basePath)
        if len(self._root_paths) == 1 and os.path.isdir(self._root_paths[0]):
            self._part_cols, self._part_raw = partitions.discover(self._files, self._root_paths)
        else:
            self._part_cols, self._part_raw = [], {}
        self._part_dtypes = partitions.infer_dtypes(self._part_cols, self._part_raw)

    @property
    def name(self) -> str:
        return ",".join(self._root_paths)

    def _partition_arrow_fields(self) -> List[pa.Field]:
        out = []
        for c in self._part_cols:
            dt = self._part_dtypes[c]
            if dt == np.dtype(np.int64):
                out.append(pa.field(c, pa.int64()))
            elif dt == np.dtype(np.float64):
                out.append(pa.field(c, pa.float64()))
            else:
                out.append(pa.field(c, pa.string()))
        return out

    @property
    def schema(self) -> pa.Schema:
        # arrow_dataset() carries the hive partitioning, so its schema
        # already includes the partition fields (the path-derived value
        # shadows any same-named column in the file bytes); avro/text resolve
        # from file headers alone — no record data is decoded for the schema
        if self._schema is None:
            if self._file_format in MATERIALIZED_FORMATS:
                s = read_format_schema(self._files, self._file_format)
                for field in self._partition_arrow_fields():
                    if field.name not in s.names:
                        s = s.append(field)
                self._schema = s
            else:
                self._schema = self.arrow_dataset().schema
        return self._schema

    @property
    def partition_columns(self) -> List[str]:
        return list(self._part_cols)

    def partition_values_for(self, file_path: str) -> Dict[str, object]:
        """Typed partition-column values of one file's rows."""
        raw = self._part_raw.get(os.path.abspath(file_path), {})
        return {
            c: partitions.typed_value(raw.get(c), self._part_dtypes[c])
            for c in self._part_cols
        }

    @property
    def partition_dtypes(self) -> Dict[str, "np.dtype"]:
        return dict(self._part_dtypes)

    @property
    def root_paths(self) -> List[str]:
        return list(self._root_paths)

    @property
    def file_format(self) -> str:
        return self._file_format

    @property
    def options(self) -> Dict[str, str]:
        return dict(self._options)

    def arrow_dataset(self, files: Optional[List[str]] = None) -> pads.Dataset:
        target = files if files is not None else self._files
        if self._file_format in MATERIALIZED_FORMATS:
            return self._materialized_dataset(target)
        fmt = formats.arrow_format(self._file_format, self._options)
        if self._part_cols:
            part = pads.partitioning(pa.schema(self._partition_arrow_fields()), flavor="hive")
            return pads.dataset(
                target,
                format=fmt,
                partitioning=part,
                partition_base_dir=self._root_paths[0],
            )
        return pads.dataset(target, format=fmt)

    def _materialized_dataset(self, target: List[str]) -> pads.Dataset:
        """Avro/text: decode to in-memory tables, attaching hive-partition
        columns (constant per file, absent from the file bytes) so the schema
        matches what the native path's hive partitioning would expose."""
        tables = []
        for f in target:
            t = read_table(f, self._file_format)
            if self._part_cols:
                vals = self.partition_values_for(f)
                for field in self._partition_arrow_fields():
                    t = t.append_column(
                        field, pa.array([vals.get(field.name)] * t.num_rows, type=field.type)
                    )
            tables.append(t)
        return tables_to_dataset(tables)

    def all_file_infos(self) -> List[FileInfo]:
        return [FileInfo.from_path(f) for f in self._files]

    def signature(self) -> str:
        return file_based_signature(self.all_file_infos())

    def create_relation_metadata(self, file_id_tracker) -> Relation:
        infos = self.all_file_infos()
        if file_id_tracker is not None:
            file_id_tracker.add_files(infos)
        return Relation(
            root_paths=self.root_paths,
            data=Storage(Content.from_leaf_files(infos)),
            schema_json=schema_codec.schema_to_json(self.schema),
            file_format=self._file_format,
            options=self.options,
        )


class DefaultFileBasedRelationMetadata(FileBasedRelationMetadata):
    """(ref: HS/index/sources/default/DefaultFileBasedRelationMetadata.scala:25)"""

    def refresh(self) -> Relation:
        fresh = DefaultFileBasedRelation(
            self.relation.root_paths, self.relation.file_format, self.relation.options
        )
        return fresh.create_relation_metadata(None)

    def to_relation_object(self) -> DefaultFileBasedRelation:
        return DefaultFileBasedRelation(
            self.relation.root_paths, self.relation.file_format, self.relation.options
        )


class DefaultFileBasedSource(FileBasedSourceProvider):
    def create_relation(self, path_or_plan, session) -> Optional[FileBasedRelation]:
        if isinstance(path_or_plan, DefaultFileBasedRelation):
            return path_or_plan
        if isinstance(path_or_plan, tuple):
            paths, fmt, options = path_or_plan
            if fmt not in SUPPORTED_FORMATS:
                return None
            return DefaultFileBasedRelation(list(paths), fmt, options)
        return None

    def create_relation_metadata(self, relation: Relation, session) -> Optional[FileBasedRelationMetadata]:
        if relation.file_format in SUPPORTED_FORMATS:
            return DefaultFileBasedRelationMetadata(relation)
        return None


class DefaultFileBasedSourceBuilder:
    """Builder loaded from conf ``hyperspace.index.sources.fileBasedBuilders``
    (ref: HS/index/sources/FileBasedSourceProviderManager.scala:38-174)."""

    def build(self, session) -> FileBasedSourceProvider:
        return DefaultFileBasedSource()
