"""Hive-style partition discovery (``.../col=value/...`` path segments).

Plays the role of Spark's ``PartitioningAwareFileIndex`` partition inference
for the default source (ref: HS/index/sources/default/DefaultFileBasedRelation.scala:38
exposes partition schema/basePaths; the reference's E2E suites index and
hybrid-scan partitioned data). Inference follows Spark's default: int64 →
float64 → string (date inference is opt-in in Spark and omitted here).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple
from urllib.parse import unquote

import numpy as np

HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _segments_between(file_path: str, roots: List[str]) -> Optional[List[str]]:
    """Directory segments of ``file_path`` below its root, or None if the
    file is under no root."""
    fdir = os.path.dirname(os.path.abspath(file_path))
    for root in roots:
        root = os.path.abspath(root)
        if fdir == root:
            return []
        if fdir.startswith(root + os.sep):
            rel = fdir[len(root) + 1 :]
            return rel.split(os.sep)
    return None


def _parse_kv(segment: str) -> Optional[Tuple[str, str]]:
    if "=" not in segment:
        return None
    k, _, v = segment.partition("=")
    if not k:
        return None
    return unquote(k), unquote(v)


def discover(files: List[str], roots: List[str]) -> Tuple[List[str], Dict[str, Dict[str, Optional[str]]]]:
    """Infer partition columns from file paths.

    Returns (ordered partition column names, {file -> {col -> raw value or
    None for the hive null partition}}). An inconsistent layout (files with
    differing partition columns, or any non-``k=v`` directory segment)
    yields ([], {}) — the dataset is treated as unpartitioned, like Spark
    when basePath inference fails.
    """
    cols: Optional[List[str]] = None
    raw: Dict[str, Dict[str, Optional[str]]] = {}
    for f in files:
        segs = _segments_between(f, roots)
        if segs is None:
            return [], {}
        kvs = []
        for s in segs:
            kv = _parse_kv(s)
            if kv is None:
                return [], {}
            kvs.append(kv)
        names = [k for k, _ in kvs]
        if cols is None:
            cols = names
        elif names != cols:
            return [], {}
        raw[f] = {k: (None if v == HIVE_NULL else v) for k, v in kvs}
    if not cols:
        return [], {}
    return cols, raw


def _all_parse(values, caster) -> bool:
    for v in values:
        if v is None:
            continue
        try:
            caster(v)
        except (TypeError, ValueError):
            return False
    return True


def infer_dtypes(cols: List[str], raw: Dict[str, Dict[str, Optional[str]]]) -> Dict[str, np.dtype]:
    """Per-column numpy dtype: int64 if every value parses as int, else
    float64 if every value parses as float, else object (string)."""
    out: Dict[str, np.dtype] = {}
    for c in cols:
        values = [per_file.get(c) for per_file in raw.values()]
        has_null = any(v is None for v in values)
        if _all_parse(values, int) and not has_null:
            out[c] = np.dtype(np.int64)
        elif _all_parse(values, float):
            # int columns containing a hive-null partition also land here:
            # NaN needs a float column
            out[c] = np.dtype(np.float64)
        else:
            out[c] = np.dtype(object)
    return out


def typed_value(value: Optional[str], dtype: np.dtype):
    """Raw partition string -> typed scalar (None stays None for strings,
    NaN for floats; int columns with nulls are promoted to float by
    ``infer_dtypes`` callers only when parsing fails, so null here means the
    hive null partition)."""
    if value is None:
        if dtype == np.dtype(np.float64):
            return float("nan")
        return None
    if dtype == np.dtype(np.int64):
        return int(value)
    if dtype == np.dtype(np.float64):
        return float(value)
    return value


def column_array(value, dtype: np.dtype, n: int) -> np.ndarray:
    """Constant partition column for one file's rows."""
    if dtype == np.dtype(object):
        arr = np.empty(n, dtype=object)
        arr[:] = value
        return arr
    if value is None:
        # int64 with a hive-null partition: no integer NaN — promote to float
        return np.full(n, np.nan, dtype=np.float64)
    return np.full(n, value, dtype=dtype)
