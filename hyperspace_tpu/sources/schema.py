"""Arrow-schema <-> JSON codec.

The reference stores the source relation's Spark ``StructType`` JSON in the
log entry (ref: HS/index/IndexLogEntry.scala:379-385, util/JsonUtils.scala).
Here schemas are ``pyarrow.Schema`` serialized to a small JSON structure.
"""

from __future__ import annotations

import json
from typing import Dict, List

import pyarrow as pa

_STR_TO_TYPE = {
    "int8": pa.int8(),
    "int16": pa.int16(),
    "int32": pa.int32(),
    "int64": pa.int64(),
    "uint8": pa.uint8(),
    "uint16": pa.uint16(),
    "uint32": pa.uint32(),
    "uint64": pa.uint64(),
    "float": pa.float32(),
    "float32": pa.float32(),
    "double": pa.float64(),
    "float64": pa.float64(),
    "bool": pa.bool_(),
    "string": pa.string(),
    "large_string": pa.large_string(),
    "binary": pa.binary(),
    "date32[day]": pa.date32(),
    "date64[ms]": pa.date64(),
    "timestamp[us]": pa.timestamp("us"),
    "timestamp[ns]": pa.timestamp("ns"),
    "timestamp[ms]": pa.timestamp("ms"),
    "timestamp[s]": pa.timestamp("s"),
}


def _type_to_dict(t: pa.DataType) -> Dict:
    if pa.types.is_struct(t):
        return {"type": "struct", "fields": [{"name": t.field(i).name, **_type_to_dict(t.field(i).type)} for i in range(t.num_fields)]}
    if pa.types.is_list(t):
        return {"type": "list", "item": _type_to_dict(t.value_type)}
    if pa.types.is_decimal(t):
        return {"type": "decimal", "precision": t.precision, "scale": t.scale}
    return {"type": str(t)}


def _type_from_dict(d: Dict) -> pa.DataType:
    t = d["type"]
    if t == "struct":
        return pa.struct([pa.field(f["name"], _type_from_dict(f)) for f in d["fields"]])
    if t == "list":
        return pa.list_(_type_from_dict(d["item"]))
    if t == "decimal":
        return pa.decimal128(d["precision"], d["scale"])
    if t in _STR_TO_TYPE:
        return _STR_TO_TYPE[t]
    raise ValueError(f"Unsupported type string {t!r}")


def arrow_to_numpy_dtype(t: pa.DataType):
    """Best-effort numpy dtype for an arrow type (object for strings/nested)."""
    import numpy as np

    if pa.types.is_integer(t):
        return np.dtype(np.int64)
    if pa.types.is_floating(t):
        return np.dtype(np.float64)
    if pa.types.is_boolean(t):
        return np.dtype(bool)
    if pa.types.is_timestamp(t):
        return np.dtype(f"datetime64[{t.unit}]")
    if pa.types.is_date(t):
        return np.dtype("datetime64[D]")
    return np.dtype(object)


def schema_to_json(schema: pa.Schema) -> str:
    fields: List[Dict] = [{"name": f.name, **_type_to_dict(f.type)} for f in schema]
    return json.dumps({"fields": fields})


def schema_from_json(text: str) -> pa.Schema:
    d = json.loads(text)
    return pa.schema([pa.field(f["name"], _type_from_dict(f)) for f in d["fields"]])
