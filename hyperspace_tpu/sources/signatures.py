"""Signature providers.

``FileBasedSignatureProvider`` fingerprints a relation by folding each file's
(mtime, length, path) and hashing (ref: HS/index/FileBasedSignatureProvider.scala:30-62).
``IndexSignatureProvider`` adds a fingerprint of the plan structure on top
(ref: HS/index/IndexSignatureProvider.scala).
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.utils.hashing import md5_hex

FILE_BASED_SIGNATURE_PROVIDER = "FileBasedSignatureProvider"
# /v2: the plan-structure token canonicalizes Scan by format instead of by
# root-path spelling (glob/dir/file-list addressing of the same files now
# signature-equal). Entries recorded under an older provider are disqualified
# with an explicit provider-mismatch reason until refreshed.
INDEX_SIGNATURE_PROVIDER = "IndexSignatureProvider/v2"


def file_based_signature(file_infos) -> str:
    parts = sorted(f"{fi.modified_time}:{fi.size}:{fi.name}" for fi in file_infos)
    return md5_hex("\n".join(parts))


def plan_structure_string(plan) -> str:
    """A canonical string of the plan's node kinds + shapes (stands in for
    Catalyst canonicalization; ref: HS/index/PlanSignatureProvider.scala)."""
    from hyperspace_tpu.plan import logical as L

    def walk(p) -> str:
        if isinstance(p, L.Scan):
            # canonicalize by format, not path spelling: the same file set is
            # addressable as a directory, a glob, or an explicit list, and
            # data identity is already carried by the file-based signature
            # (the reference needs a globbingPattern conf for this,
            # HS/index/IndexConstants + DataPathFilter; resolved-file identity
            # subsumes it)
            return f"Scan({p.relation.file_format})"
        name = type(p).__name__
        inner = ",".join(walk(c) for c in p.children())
        if isinstance(p, L.Project):
            name += f"[{','.join(c.lower() for c in p.columns)}]"
        return f"{name}({inner})"

    return walk(plan)


def index_signature(plan) -> Optional[str]:
    """Signature of the full source plan: plan structure + every relation's
    file-based signature (ref: HS/index/IndexSignatureProvider.scala)."""
    from hyperspace_tpu.plan import logical as L

    scans = L.collect(plan, lambda p: isinstance(p, L.Scan))
    if not scans:
        return None
    rel_sigs = sorted(s.relation.signature() for s in scans)
    return md5_hex(plan_structure_string(plan) + "|" + "|".join(rel_sigs))
