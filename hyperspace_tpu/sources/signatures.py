"""Signature providers.

``FileBasedSignatureProvider`` fingerprints a relation by folding each file's
(mtime, length, path) and hashing (ref: HS/index/FileBasedSignatureProvider.scala:30-62).
``IndexSignatureProvider`` adds a fingerprint of the plan structure on top
(ref: HS/index/IndexSignatureProvider.scala).
"""

from __future__ import annotations

from typing import Optional

from hyperspace_tpu.utils.hashing import md5_hex

FILE_BASED_SIGNATURE_PROVIDER = "FileBasedSignatureProvider"
INDEX_SIGNATURE_PROVIDER = "IndexSignatureProvider"


def file_based_signature(file_infos) -> str:
    parts = sorted(f"{fi.modified_time}:{fi.size}:{fi.name}" for fi in file_infos)
    return md5_hex("\n".join(parts))


def plan_structure_string(plan) -> str:
    """A canonical string of the plan's node kinds + shapes (stands in for
    Catalyst canonicalization; ref: HS/index/PlanSignatureProvider.scala)."""
    from hyperspace_tpu.plan import logical as L

    def walk(p) -> str:
        if isinstance(p, L.Scan):
            return f"Scan({','.join(sorted(p.relation.root_paths))})"
        name = type(p).__name__
        inner = ",".join(walk(c) for c in p.children())
        if isinstance(p, L.Project):
            name += f"[{','.join(c.lower() for c in p.columns)}]"
        return f"{name}({inner})"

    return walk(plan)


def index_signature(plan) -> Optional[str]:
    """Signature of the full source plan: plan structure + every relation's
    file-based signature (ref: HS/index/IndexSignatureProvider.scala)."""
    from hyperspace_tpu.plan import logical as L

    scans = L.collect(plan, lambda p: isinstance(p, L.Scan))
    if not scans:
        return None
    rel_sigs = sorted(s.relation.signature() for s in scans)
    return md5_hex(plan_structure_string(plan) + "|" + "|".join(rel_sigs))
