"""Delta Lake source provider.

Reads the Delta transaction log (``_delta_log/NNN...N.json``) natively — no
Spark — replaying add/remove actions to materialize the file list at any
table version, enabling time travel
(ref: HS/index/sources/delta/DeltaLakeFileBasedSource.scala:31,
DeltaLakeRelation.scala:40-44 signature = tableVersion + path;
DeltaLakeRelationMetadata.scala:39-53 deltaVersions history property).

Also ships a minimal writer (``write_delta_table``) so tests and local
pipelines can produce Delta tables without Spark.

Checkpoint parquet files are supported read-only (``_last_checkpoint``).
"""

from __future__ import annotations

import json
import os
import re
import time
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.models.log_entry import Content, FileInfo, IndexLogEntry, Relation, Storage
from hyperspace_tpu.sources import schema as schema_codec
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedRelationMetadata,
    FileBasedSourceProvider,
)
from hyperspace_tpu.utils.hashing import md5_hex

DELTA_LOG_DIR = "_delta_log"
_VERSION_FILE_RE = re.compile(r"^(\d{20})\.json$")
DELTA_VERSIONS_PROPERTY = "deltaVersions"


def _log_dir(root: str) -> str:
    return os.path.join(root, DELTA_LOG_DIR)


def list_versions(root: str) -> List[int]:
    try:
        names = os.listdir(_log_dir(root))
    except OSError:
        return []
    out = []
    for n in names:
        m = _VERSION_FILE_RE.match(n)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _replay(root: str, version: int) -> Dict[str, Dict]:
    """Replay the log up to ``version`` inclusive; returns path -> add action."""
    files: Dict[str, Dict] = {}
    checkpoint_version = -1
    cp_path = os.path.join(_log_dir(root), "_last_checkpoint")
    if os.path.exists(cp_path):
        with open(cp_path) as f:
            cp = json.load(f)
        if cp.get("version", -1) <= version:
            checkpoint_version = int(cp["version"])
            cp_file = os.path.join(_log_dir(root), f"{checkpoint_version:020d}.checkpoint.parquet")
            t = pq.read_table(cp_file)
            for row in t.to_pylist():
                add = row.get("add")
                if add and add.get("path"):
                    files[add["path"]] = add
    for v in list_versions(root):
        if v <= checkpoint_version or v > version:
            continue
        with open(os.path.join(_log_dir(root), f"{v:020d}.json")) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                action = json.loads(line)
                if "add" in action:
                    files[action["add"]["path"]] = action["add"]
                elif "remove" in action:
                    files.pop(action["remove"]["path"], None)
    return files


class DeltaLakeRelation(FileBasedRelation):
    def __init__(self, root: str, version: Optional[int] = None):
        self._root = os.path.abspath(root)
        versions = list_versions(self._root)
        if not versions:
            raise FileNotFoundError(f"No Delta table found at {root!r} (missing {DELTA_LOG_DIR})")
        self._version = versions[-1] if version is None else int(version)
        if self._version not in versions and version is not None:
            # allow any version <= latest present in the log range
            if self._version > versions[-1] or self._version < 0:
                raise ValueError(f"Version {version} not available; latest is {versions[-1]}")
        self._adds = _replay(self._root, self._version)
        if not self._adds:
            raise FileNotFoundError(f"Delta table at {root!r} has no data files at version {self._version}")
        self._schema: Optional[pa.Schema] = None

    @property
    def name(self) -> str:
        return self._root

    @property
    def version(self) -> int:
        return self._version

    @property
    def schema(self) -> pa.Schema:
        if self._schema is None:
            self._schema = self.arrow_dataset().schema
        return self._schema

    @property
    def root_paths(self) -> List[str]:
        return [self._root]

    @property
    def file_format(self) -> str:
        return "delta"

    @property
    def options(self) -> Dict[str, str]:
        return {"versionAsOf": str(self._version)}

    def _abs_files(self) -> List[str]:
        return sorted(os.path.join(self._root, p) for p in self._adds)

    def arrow_dataset(self, files: Optional[List[str]] = None) -> pads.Dataset:
        return pads.dataset(files if files is not None else self._abs_files(), format="parquet")

    def all_file_infos(self) -> List[FileInfo]:
        out = []
        for rel_path, add in sorted(self._adds.items()):
            out.append(
                FileInfo(
                    os.path.join(self._root, rel_path),
                    int(add.get("size", 0)),
                    int(add.get("modificationTime", 0)),
                )
            )
        return out

    def signature(self) -> str:
        """Delta signature = table version + path
        (ref: DeltaLakeRelation.scala:40-44)."""
        return md5_hex(f"delta:{self._root}:{self._version}")

    def has_parquet_as_source_format(self) -> bool:
        return True

    def create_relation_metadata(self, file_id_tracker) -> Relation:
        infos = self.all_file_infos()
        if file_id_tracker is not None:
            file_id_tracker.add_files(infos)
        return Relation(
            root_paths=self.root_paths,
            data=Storage(Content.from_leaf_files(infos)),
            schema_json=schema_codec.schema_to_json(self.schema),
            file_format="delta",
            options=self.options,
        )

    def closest_index(self, entry: IndexLogEntry) -> IndexLogEntry:
        """Time-travel-aware index-version selection: when querying an older
        table version, use the index log version whose recorded delta version
        is closest to (and at most) the queried version
        (ref: DeltaLakeRelation.scala:179-251)."""
        history = entry.properties.get(DELTA_VERSIONS_PROPERTY)
        if not history:
            return entry
        # history: {index_log_id(str): delta_version(int)}; among versions at
        # most the queried one, prefer the highest version and, on ties, the
        # LATEST log id (earlier ids for the same version are superseded)
        best_log_id, best_delta = None, None
        for log_id_str, delta_v in history.items():
            dv, lid = int(delta_v), int(log_id_str)
            if dv <= self._version and (best_delta is None or (dv, lid) > (best_delta, best_log_id)):
                best_log_id, best_delta = lid, dv
        if best_log_id is None or best_log_id == entry.id:
            return entry
        # the LATEST entry covers the newest recorded snapshot even when its
        # own id isn't in the history (optimize/restore entries supersede the
        # recording refresh without changing source coverage) — only reach
        # back for a strictly older snapshot
        latest_recorded = max(int(v) for v in history.values())
        if best_delta >= latest_recorded:
            return entry
        from hyperspace_tpu.models.log_manager import IndexLogManager
        from hyperspace_tpu.models.path_resolver import PathResolver

        # re-read that log version of the same index
        index_root = os.path.dirname(os.path.dirname(entry.content.files[0])) if entry.content.files else None
        if index_root is None:
            return entry
        older = IndexLogManager(index_root).get_log(best_log_id)
        return older if older is not None and older.state == entry.state else entry


class DeltaLakeRelationMetadata(FileBasedRelationMetadata):
    """(ref: HS/index/sources/delta/DeltaLakeRelationMetadata.scala:39-53)"""

    def refresh(self) -> Relation:
        return self.to_relation_object().create_relation_metadata(None)

    def to_relation_object(self) -> DeltaLakeRelation:
        return DeltaLakeRelation(self.relation.root_paths[0])  # latest version

    def enrich_index_properties(
        self,
        properties: Dict[str, Any],
        log_id: Optional[int] = None,
        previous_properties: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Maintain the index-log-version -> delta-table-version history that
        time-travel queries consult via ``closest_index``
        (ref: DeltaLakeRelationMetadata.scala:39-53 deltaVersions).

        ``log_id=None`` means carry the history forward without recording
        (actions whose entries copy their predecessor)."""
        history = dict((previous_properties or {}).get(DELTA_VERSIONS_PROPERTY) or {})
        if log_id is not None:
            version = self.relation.options.get("versionAsOf")
            if version is not None:
                history[str(log_id)] = int(version)
        if not history:
            return properties
        out = dict(properties)
        out[DELTA_VERSIONS_PROPERTY] = history
        return out


class DeltaLakeFileBasedSource(FileBasedSourceProvider):
    def create_relation(self, path_or_plan, session) -> Optional[FileBasedRelation]:
        if isinstance(path_or_plan, DeltaLakeRelation):
            return path_or_plan
        if isinstance(path_or_plan, tuple):
            paths, fmt, options = path_or_plan
            if fmt == "delta":
                version = options.get("versionAsOf")
                return DeltaLakeRelation(list(paths)[0], None if version is None else int(version))
        return None

    def create_relation_metadata(self, relation: Relation, session) -> Optional[FileBasedRelationMetadata]:
        if relation.file_format == "delta":
            return DeltaLakeRelationMetadata(relation)
        return None


class DeltaLakeSourceBuilder:
    def build(self, session) -> FileBasedSourceProvider:
        return DeltaLakeFileBasedSource()


# --- minimal writer (tests / local pipelines; no Spark needed) --------------

def write_delta_table(table: pa.Table, root: str, mode: str = "append") -> int:
    """Write ``table`` as one parquet part + one Delta commit. Returns the new
    table version. ``mode='overwrite'`` removes all previous files."""
    root = os.path.abspath(root)
    os.makedirs(_log_dir(root), exist_ok=True)
    versions = list_versions(root)
    new_version = (versions[-1] + 1) if versions else 0

    part = f"part-{new_version:05d}-{uuid.uuid4().hex[:12]}.parquet"
    pq.write_table(table, os.path.join(root, part))
    st = os.stat(os.path.join(root, part))

    actions = []
    if new_version == 0:
        actions.append({"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}})
        actions.append(
            {
                "metaData": {
                    "id": uuid.uuid4().hex,
                    "format": {"provider": "parquet", "options": {}},
                    "partitionColumns": [],
                    "configuration": {},
                }
            }
        )
    if mode == "overwrite" and new_version > 0:
        for rel_path in _replay(root, versions[-1]):
            actions.append({"remove": {"path": rel_path, "dataChange": True}})
    actions.append(
        {
            "add": {
                "path": part,
                "size": st.st_size,
                "modificationTime": int(st.st_mtime * 1000),
                "dataChange": True,
                "partitionValues": {},
            }
        }
    )
    actions.append({"commitInfo": {"timestamp": int(time.time() * 1000), "operation": "WRITE"}})
    with open(os.path.join(_log_dir(root), f"{new_version:020d}.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")
    return new_version


def delete_delta_files(root: str, rel_paths: List[str]) -> int:
    """Commit a remove-only transaction (logical delete of whole files)."""
    root = os.path.abspath(root)
    versions = list_versions(root)
    if not versions:
        raise FileNotFoundError(f"No Delta table at {root!r}")
    new_version = versions[-1] + 1
    with open(os.path.join(_log_dir(root), f"{new_version:020d}.json"), "w") as f:
        for p in rel_paths:
            f.write(json.dumps({"remove": {"path": p, "dataChange": True}}) + "\n")
        f.write(json.dumps({"commitInfo": {"timestamp": int(time.time() * 1000), "operation": "DELETE"}}) + "\n")
    return new_version
