"""Apache Iceberg source provider.

Reads the Iceberg table format natively — no Spark, no iceberg-core:
``metadata/v<N>.metadata.json`` (+ ``version-hint.text``) → snapshots →
manifest-list Avro → manifest Avro → live data files. The Avro codec is the
framework's own (utils/avro.py), schema-driven, so manifests written by real
engines parse.

Parity with the reference Iceberg source
(ref: HS/index/sources/iceberg/IcebergRelation.scala:65-67 signature =
snapshotId + location; :72-74 files via table.newScan().planFiles();
IcebergFileBasedSource.scala derived hasParquetAsSourceFormat=true), plus
snapshot time travel via the ``snapshotId`` option.

Also ships a minimal writer (``write_iceberg_table``) so tests and local
pipelines can produce real Iceberg tables (v1 layout, Avro manifests).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from hyperspace_tpu.models.log_entry import Content, FileInfo, IndexLogEntry, Relation, Storage
from hyperspace_tpu.sources import schema as schema_codec
from hyperspace_tpu.sources.interfaces import (
    FileBasedRelation,
    FileBasedRelationMetadata,
    FileBasedSourceProvider,
)
from hyperspace_tpu.utils import avro
from hyperspace_tpu.utils.hashing import md5_hex

METADATA_DIR = "metadata"
VERSION_HINT = "version-hint.text"


def _metadata_dir(root: str) -> str:
    return os.path.join(root, METADATA_DIR)


def _resolve_path(root: str, path: str) -> str:
    """Manifest/data paths may be absolute, file:// URIs, or table-relative."""
    if path.startswith("file://"):
        return path[len("file://"):]
    if os.path.isabs(path):
        return path
    return os.path.join(root, path)


def current_metadata_path(root: str) -> Optional[str]:
    md = _metadata_dir(root)
    hint = os.path.join(md, VERSION_HINT)
    if os.path.exists(hint):
        with open(hint) as f:
            v = f.read().strip()
        cand = os.path.join(md, f"v{v}.metadata.json")
        if os.path.exists(cand):
            return cand
    try:
        versions = sorted(
            (n for n in os.listdir(md) if n.endswith(".metadata.json")),
            key=lambda n: os.path.getmtime(os.path.join(md, n)),
        )
    except OSError:
        return None
    return os.path.join(md, versions[-1]) if versions else None


def load_table_metadata(root: str) -> Dict[str, Any]:
    path = current_metadata_path(root)
    if path is None:
        raise FileNotFoundError(f"No Iceberg table found at {root!r} (missing {METADATA_DIR}/)")
    with open(path) as f:
        return json.load(f)


def _snapshot(meta: Dict[str, Any], snapshot_id: Optional[int]) -> Dict[str, Any]:
    snaps = meta.get("snapshots", [])
    if not snaps:
        raise FileNotFoundError("Iceberg table has no snapshots")
    if snapshot_id is None:
        current = meta.get("current-snapshot-id")
        for s in snaps:
            if s["snapshot-id"] == current:
                return s
        return snaps[-1]
    for s in snaps:
        if s["snapshot-id"] == snapshot_id:
            return s
    raise ValueError(f"Snapshot {snapshot_id} not found")


def plan_files(root: str, snapshot: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Live data files of a snapshot: manifest-list → manifests → entries with
    status != DELETED (2) (the reference delegates this walk to
    table.newScan().planFiles(); ref: IcebergRelation.scala:72-74)."""
    out: List[Dict[str, Any]] = []
    manifest_list = _resolve_path(root, snapshot["manifest-list"])
    _, manifests = avro.read_container(manifest_list)
    for m in manifests:
        manifest_path = _resolve_path(root, m["manifest_path"])
        _, entries = avro.read_container(manifest_path)
        for e in entries:
            if e.get("status") == 2:  # DELETED
                continue
            df = e.get("data_file") or {}
            if df.get("file_path"):
                out.append(df)
    return out


class IcebergRelation(FileBasedRelation):
    def __init__(self, root: str, snapshot_id: Optional[int] = None):
        self._root = os.path.abspath(root)
        self._meta = load_table_metadata(self._root)
        self._snap = _snapshot(self._meta, snapshot_id)
        self._data_files = plan_files(self._root, self._snap)
        if not self._data_files:
            raise FileNotFoundError(f"Iceberg table at {root!r} has no data files in snapshot {self._snap['snapshot-id']}")
        self._schema: Optional[pa.Schema] = None

    @property
    def name(self) -> str:
        return self._root

    @property
    def snapshot_id(self) -> int:
        return int(self._snap["snapshot-id"])

    @property
    def schema(self) -> pa.Schema:
        if self._schema is None:
            self._schema = self.arrow_dataset().schema
        return self._schema

    @property
    def root_paths(self) -> List[str]:
        return [self._root]

    @property
    def file_format(self) -> str:
        return "iceberg"

    @property
    def options(self) -> Dict[str, str]:
        return {"snapshotId": str(self.snapshot_id)}

    def _abs_files(self) -> List[str]:
        return sorted(_resolve_path(self._root, df["file_path"]) for df in self._data_files)

    def arrow_dataset(self, files: Optional[List[str]] = None) -> pads.Dataset:
        return pads.dataset(files if files is not None else self._abs_files(), format="parquet")

    def all_file_infos(self) -> List[FileInfo]:
        out = []
        for df in sorted(self._data_files, key=lambda d: d["file_path"]):
            path = _resolve_path(self._root, df["file_path"])
            size = int(df.get("file_size_in_bytes") or 0)
            if size == 0 and os.path.exists(path):
                size = os.stat(path).st_size
            mtime = int(os.stat(path).st_mtime_ns) if os.path.exists(path) else 0
            out.append(FileInfo(path, size, mtime))
        return out

    def signature(self) -> str:
        """Iceberg signature = snapshot id + table location
        (ref: IcebergRelation.scala:65-67)."""
        return md5_hex(f"iceberg:{self._root}:{self.snapshot_id}")

    def has_parquet_as_source_format(self) -> bool:
        return True  # (ref: IcebergFileBasedSource derived property)

    def create_relation_metadata(self, file_id_tracker) -> Relation:
        infos = self.all_file_infos()
        if file_id_tracker is not None:
            file_id_tracker.add_files(infos)
        return Relation(
            root_paths=self.root_paths,
            data=Storage(Content.from_leaf_files(infos)),
            schema_json=schema_codec.schema_to_json(self.schema),
            file_format="iceberg",
            options=self.options,
        )


class IcebergRelationMetadata(FileBasedRelationMetadata):
    def refresh(self) -> Relation:
        return self.to_relation_object().create_relation_metadata(None)

    def to_relation_object(self) -> IcebergRelation:
        return IcebergRelation(self.relation.root_paths[0])  # current snapshot

    def internal_file_format_name(self) -> str:
        return "parquet"

    def enrich_index_properties(self, properties, log_id=None, previous_properties=None):
        return properties


class IcebergFileBasedSource(FileBasedSourceProvider):
    def create_relation(self, path_or_plan, session) -> Optional[FileBasedRelation]:
        if isinstance(path_or_plan, IcebergRelation):
            return path_or_plan
        if isinstance(path_or_plan, tuple):
            paths, fmt, options = path_or_plan
            if fmt == "iceberg":
                sid = options.get("snapshotId")
                return IcebergRelation(list(paths)[0], None if sid is None else int(sid))
        return None

    def create_relation_metadata(self, relation: Relation, session) -> Optional[FileBasedRelationMetadata]:
        if relation.file_format == "iceberg":
            return IcebergRelationMetadata(relation)
        return None


class IcebergSourceBuilder:
    def build(self, session) -> FileBasedSourceProvider:
        return IcebergFileBasedSource()


# --------------------------------------------------------------------------
# minimal writer (tests / local pipelines) — v1 table layout, Avro manifests
# --------------------------------------------------------------------------

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {
            "name": "data_file",
            "type": {
                "type": "record",
                "name": "r2",
                "fields": [
                    {"name": "file_path", "type": "string"},
                    {"name": "file_format", "type": "string"},
                    {"name": "record_count", "type": "long"},
                    {"name": "file_size_in_bytes", "type": "long"},
                ],
            },
        },
    ],
}

_MANIFEST_FILE_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"], "default": None},
    ],
}


def write_iceberg_table(table: pa.Table, root: str, mode: str = "append") -> int:
    """Write one parquet data file + manifest + manifest list + a new
    metadata.json snapshot. Returns the new snapshot id."""
    root = os.path.abspath(root)
    data_dir = os.path.join(root, "data")
    md = _metadata_dir(root)
    os.makedirs(data_dir, exist_ok=True)
    os.makedirs(md, exist_ok=True)

    prior_meta: Optional[Dict[str, Any]] = None
    if current_metadata_path(root):
        prior_meta = load_table_metadata(root)

    snapshot_id = int(time.time() * 1000) * 1000 + len((prior_meta or {}).get("snapshots", []))
    part = f"data/part-{uuid.uuid4().hex[:12]}.parquet"
    abs_part = os.path.join(root, part)
    pq.write_table(table, abs_part)
    st = os.stat(abs_part)

    manifest_name = f"manifest-{uuid.uuid4().hex[:12]}.avro"
    manifest_path = os.path.join(md, manifest_name)
    avro.write_container(
        manifest_path,
        _MANIFEST_ENTRY_SCHEMA,
        [
            {
                "status": 1,  # ADDED
                "snapshot_id": snapshot_id,
                "data_file": {
                    "file_path": part,
                    "file_format": "PARQUET",
                    "record_count": table.num_rows,
                    "file_size_in_bytes": st.st_size,
                },
            }
        ],
    )

    manifests = [
        {
            "manifest_path": os.path.join(METADATA_DIR, manifest_name),
            "manifest_length": os.stat(manifest_path).st_size,
            "partition_spec_id": 0,
            "added_snapshot_id": snapshot_id,
        }
    ]
    if mode == "append" and prior_meta is not None and prior_meta.get("snapshots"):
        prev_snap = _snapshot(prior_meta, None)
        prev_list = _resolve_path(root, prev_snap["manifest-list"])
        _, prev_manifests = avro.read_container(prev_list)
        manifests = prev_manifests + manifests

    list_name = f"snap-{snapshot_id}-{uuid.uuid4().hex[:8]}.avro"
    list_path = os.path.join(md, list_name)
    avro.write_container(list_path, _MANIFEST_FILE_SCHEMA, manifests)

    version = 1 if prior_meta is None else int(prior_meta.get("_version", 0)) + 1
    snapshots = list((prior_meta or {}).get("snapshots", []))
    snapshots.append(
        {
            "snapshot-id": snapshot_id,
            "timestamp-ms": int(time.time() * 1000),
            "manifest-list": os.path.join(METADATA_DIR, list_name),
            "summary": {"operation": "append" if mode == "append" else "overwrite"},
        }
    )
    meta = {
        "format-version": 1,
        "table-uuid": (prior_meta or {}).get("table-uuid", str(uuid.uuid4())),
        "location": root,
        "last-updated-ms": int(time.time() * 1000),
        "current-snapshot-id": snapshot_id,
        "snapshots": snapshots,
        "_version": version,
    }
    with open(os.path.join(md, f"v{version}.metadata.json"), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(md, VERSION_HINT), "w") as f:
        f.write(str(version))
    return snapshot_id
