"""Source-provider SPI.

Mirrors the reference's pluggable source layer
(ref: HS/index/sources/interfaces.scala:43-272):

  - ``FileBasedRelation``          — wraps one concrete source relation
  - ``FileBasedRelationMetadata``  — operations on the *logged* relation
  - ``FileBasedSourceProvider``    — answers "is this relation supported?"
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import pyarrow as pa

from hyperspace_tpu.models.log_entry import FileInfo, IndexLogEntry, Relation


class FileBasedRelation:
    """One source relation: files + schema + format + options
    (ref: HS/index/sources/interfaces.scala:43-158)."""

    @property
    def name(self) -> str:
        raise NotImplementedError

    @property
    def schema(self) -> pa.Schema:
        raise NotImplementedError

    @property
    def root_paths(self) -> List[str]:
        raise NotImplementedError

    @property
    def file_format(self) -> str:
        raise NotImplementedError

    @property
    def physical_format(self) -> str:
        """Format of the underlying data files (e.g. a Delta relation's files
        are parquet; ref: internalFileFormatName, interfaces.scala:249-272)."""
        return "parquet" if self.has_parquet_as_source_format() else self.file_format

    @property
    def options(self) -> Dict[str, str]:
        return {}

    @property
    def partition_columns(self) -> List[str]:
        return []

    def all_file_infos(self) -> List[FileInfo]:
        raise NotImplementedError

    def signature(self) -> str:
        """Content fingerprint of this relation at this moment
        (ref: DefaultFileBasedRelation signature,
        HS/index/sources/default/DefaultFileBasedSource.scala:37-124)."""
        raise NotImplementedError

    def create_relation_metadata(self, file_id_tracker) -> Relation:
        """Snapshot into log-entry form (ref: interfaces.scala createRelationMetadata)."""
        raise NotImplementedError

    def has_parquet_as_source_format(self) -> bool:
        return self.file_format == "parquet"

    def closest_index(self, entry: IndexLogEntry) -> IndexLogEntry:
        """Hook for source-specific index-version selection, e.g. Delta time
        travel (ref: interfaces.scala:155-158, DeltaLakeRelation.scala:179-251).
        Default: identity."""
        return entry


class FileBasedRelationMetadata:
    """Operations over a relation *as recorded in a log entry*
    (ref: HS/index/sources/interfaces.scala:249-272)."""

    def __init__(self, relation: Relation):
        self.relation = relation

    def refresh(self) -> Relation:
        """Reconstruct a current snapshot of the logged relation (drop any
        recorded update, re-list files)."""
        raise NotImplementedError

    def to_relation_object(self) -> "FileBasedRelation":
        """Revive a live FileBasedRelation over the logged source's current
        state (used by refresh actions)."""
        raise NotImplementedError

    def internal_file_format_name(self) -> str:
        return self.relation.file_format

    def enrich_index_properties(
        self,
        properties: Dict[str, Any],
        log_id: Optional[int] = None,
        previous_properties: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Provider hook run when an action commits its final log entry
        (ref: FileBasedRelationMetadata.enrichIndexProperties,
        HS/index/sources/interfaces.scala:249-272): ``log_id`` is the entry's
        id and ``previous_properties`` the preceding entry's properties, so a
        provider can maintain per-log-version history (Delta's
        ``deltaVersions`` time-travel map)."""
        return properties


class FileBasedSourceProvider:
    """Answers SPI calls for relations it supports; returns None otherwise
    (ref: HS/index/sources/interfaces.scala:196-232)."""

    def create_relation(self, path_or_plan, session) -> Optional[FileBasedRelation]:
        raise NotImplementedError

    def create_relation_metadata(self, relation: Relation, session) -> Optional[FileBasedRelationMetadata]:
        raise NotImplementedError
