"""Coherence sidecar: share quarantine strikes and SLO accounting via the lake.

Each fabric node periodically overwrites one sidecar file
(``<system.path>/_fabric/nodes/<node>.json``) with its **cumulative**
coherence ledger:

- per-index quarantine strike counts (``reliability/degrade.py`` breakers),
- per-tenant SLO good/bad event counts (``obs/slo.py``),
- per-tenant token-bucket drain totals (``serving/scheduler.py``),

and merges every peer's ledger back in. Merging is delta-based: the sidecar
remembers the last cumulative value it folded in per (peer, key) and applies
only the increase, so re-reading an unchanged file is a no-op and a
restarted peer (counters reset to zero) simply contributes nothing until it
grows again. The effect:

- remote strikes count toward the local quarantine threshold, so one
  process's corrupt reads protect the others *before* they trip locally
  (trip events themselves also propagate instantly via commit records);
- remote good/bad events fold into local burn-rate windows, so the
  scheduler's burn-boost reacts to the *global* SLO, not one process's
  slice of it;
- remote bucket drains debit local token buckets, so a per-tenant rate
  limit of R holds at ~R across the fleet instead of R × processes.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, Optional

from hyperspace_tpu.fabric import records

__all__ = ["CoherenceSidecar"]


def _registry():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY


class CoherenceSidecar:
    """One publish/merge loop per fabric node (see module docstring).

    ``run_once`` (publish then merge) is the deterministic unit tests call
    directly; ``start`` runs it on a daemon thread every ``interval``
    seconds. QueryServers attach themselves while serving (their scheduler
    and SLO tracker are the accounting sources and merge sinks).
    """

    def __init__(
        self,
        session,
        node_id: Optional[str] = None,
        interval: Optional[float] = None,
    ):
        import time as _time

        conf = session.conf
        self._session_ref = weakref.ref(session)
        self._started_at = _time.time()
        self.node_id = node_id or records.local_node_id(conf)
        self.interval = float(
            conf.fabric_slo_publish_interval_seconds if interval is None else interval
        )
        self.share_quarantine = bool(conf.fabric_quarantine_shared)
        self.share_slo = bool(conf.fabric_slo_shared)
        self._lock = threading.Lock()
        self._servers: "weakref.WeakSet" = weakref.WeakSet()
        # last cumulative value folded in, per peer: {"slo": {(origin, tenant):
        # (good, bad)}, "drained": {(origin, tenant): tokens}}
        self._merged_slo: Dict[tuple, tuple] = {}
        self._merged_drained: Dict[tuple, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- server attachment ---------------------------------------------------
    def attach_server(self, server) -> None:
        with self._lock:
            self._servers.add(server)

    def detach_server(self, server) -> None:
        with self._lock:
            self._servers.discard(server)

    def _live_servers(self):
        with self._lock:
            return list(self._servers)

    # -- publish -------------------------------------------------------------
    def publish_once(self) -> bool:
        session = self._session_ref()
        if session is None:
            return False
        import time as _time

        # the node file's updatedAt is the fleet heartbeat; this payload
        # adds what liveness checks want alongside it (FrontDoor.check_beats
        # reads updatedAt age, /healthz consumers read commitSeq lag)
        state: dict = {
            "heartbeat": {
                "commitSeq": int(getattr(session.lifecycle_bus, "commit_seq", 0)),
                "uptimeSeconds": max(0.0, _time.time() - self._started_at),
            }
        }
        if self.share_quarantine:
            from hyperspace_tpu.reliability.degrade import QUARANTINE

            state["strikes"] = QUARANTINE.local_strikes()
        if self.share_slo:
            slo: Dict[str, Dict[str, int]] = {}
            drained: Dict[str, float] = {}
            for server in self._live_servers():
                tracker = getattr(server, "slo", None)
                if tracker is not None:
                    for tenant, (good, bad) in tracker.counts().items():
                        cur = slo.setdefault(tenant, {"good": 0, "bad": 0})
                        cur["good"] += good
                        cur["bad"] += bad
                sched = getattr(server, "admission", None)
                if hasattr(sched, "drained_tokens"):
                    for tenant, tokens in sched.drained_tokens().items():
                        drained[tenant] = drained.get(tenant, 0.0) + tokens
            state["slo"] = slo
            state["drained"] = drained
        ok = records.write_node_file(
            session.conf.system_path, self.node_id, state
        )
        if ok:
            reg = _registry()
            reg.counter(
                "hs_fabric_sidecar_publishes_total",
                "sidecar node-file publishes",
            ).inc()
        return ok

    # -- merge ---------------------------------------------------------------
    def merge_once(self) -> int:
        """Fold every peer's ledger deltas into local state; returns the
        number of peers merged."""
        session = self._session_ref()
        if session is None:
            return 0
        peers = records.read_peer_node_files(session.conf.system_path, self.node_id)
        if not peers:
            return 0
        if self.share_quarantine:
            self._merge_strikes(peers)
        if self.share_slo:
            self._merge_slo(peers)
        reg = _registry()
        reg.counter(
            "hs_fabric_sidecar_merges_total",
            "sidecar merge rounds that observed at least one peer",
        ).inc()
        return len(peers)

    def _merge_strikes(self, peers: Dict[str, dict]) -> None:
        totals: Dict[str, int] = {}
        for state in peers.values():
            for index, n in (state.get("strikes") or {}).items():
                totals[index] = totals.get(index, 0) + int(n)
        from hyperspace_tpu.reliability.degrade import QUARANTINE

        reg = _registry()
        for index in QUARANTINE.merge_remote_strikes(totals):
            reg.counter(
                "hs_fabric_quarantine_merged_total",
                "quarantine trips caused or propagated by remote strikes",
                index=index,
            ).inc()

    def _merge_slo(self, peers: Dict[str, dict]) -> None:
        servers = self._live_servers()
        for origin, state in peers.items():
            for tenant, counts in (state.get("slo") or {}).items():
                good = int(counts.get("good", 0))
                bad = int(counts.get("bad", 0))
                pg, pb = self._merged_slo.get((origin, tenant), (0, 0))
                dg, db = max(0, good - pg), max(0, bad - pb)
                self._merged_slo[(origin, tenant)] = (good, bad)
                if dg or db:
                    for server in servers:
                        tracker = getattr(server, "slo", None)
                        if tracker is not None:
                            tracker.note_remote(tenant, good=dg, bad=db)
            for tenant, tokens in (state.get("drained") or {}).items():
                tokens = float(tokens)
                prev = self._merged_drained.get((origin, tenant), 0.0)
                delta = max(0.0, tokens - prev)
                self._merged_drained[(origin, tenant)] = tokens
                if delta > 0:
                    for server in servers:
                        sched = getattr(server, "admission", None)
                        if hasattr(sched, "external_drain"):
                            sched.external_drain(tenant, delta)

    def run_once(self) -> int:
        self.publish_once()
        return self.merge_once()

    # -- thread lifecycle ----------------------------------------------------
    def start(self) -> "CoherenceSidecar":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="hs-fabric-sidecar", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if self._session_ref() is None:
                return
            try:
                self.run_once()
            except Exception:  # pragma: no cover — a bad round must not kill the loop
                pass
