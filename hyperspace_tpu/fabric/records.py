"""Lake-persisted fabric records: commit records + sidecar node files.

Two record families, both plain JSON under paths source listing can never
see (``walk_data_files`` skips dot/underscore-prefixed entries at any
depth):

- **Commit records** — ``<index>/_hyperspace_log/_commits/<NNNNNNNNNN>``,
  one immutable numbered file per published :class:`CommitEvent`, claimed
  with the same create-exclusive protocol the operation log itself uses
  (``write_atomic_exclusive``), so concurrent publishers on one index
  serialize into a total per-index order with no coordinator. Each record
  carries the publisher's post-bump ``commit_seq`` (the Lamport timestamp
  peers merge via ``InvalidationBus.replay``) and its ``origin`` node id
  (self-commit dedupe).
- **Sidecar node files** — ``<system.path>/_fabric/nodes/<node>.json``,
  one mutable per-node file overwritten atomically (temp + rename) each
  publish round, carrying the node's cumulative quarantine strikes and
  per-tenant SLO / token-bucket accounting. Peers merge *deltas* between
  successive reads, so a node file is a cumulative ledger, never a queue.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu import config as C
from hyperspace_tpu.utils.file_utils import write_atomic, write_atomic_exclusive

#: commit records live under <index>/_hyperspace_log/<COMMITS_DIR>/
COMMITS_DIR = "_commits"
#: sidecar node files live under <system.path>/<FABRIC_DIR>/nodes/
FABRIC_DIR = "_fabric"

#: zero-padded record ids keep lexicographic == numeric ordering in listings
_RECORD_WIDTH = 10


def local_node_id(conf) -> str:
    """The configured node id, or the per-process default."""
    return conf.fabric_node_id or f"{socket.gethostname()}:{os.getpid()}"


def _count_commit_record() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_commit_records_total",
        "commit records persisted to the lake for peer replay",
    ).inc()


def _count_record_error(op: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_record_errors_total",
        "fabric record reads/writes that failed and were skipped",
        op=op,
    ).inc()


def commits_dir(system_path: str, index_name: str) -> str:
    return os.path.join(
        str(system_path), str(index_name), C.HYPERSPACE_LOG_DIR, COMMITS_DIR
    )


def nodes_dir(system_path: str) -> str:
    return os.path.join(str(system_path), FABRIC_DIR, "nodes")


# -- commit records ----------------------------------------------------------


def append_commit_record(system_path: Optional[str], event, seq: int) -> Optional[int]:
    """Persist one published commit as the next numbered record under its
    index's log directory. Returns the claimed record id, or None when the
    record could not be written (a fabric record failure must never fail
    the commit it describes — peers simply stay TTL-fresh instead)."""
    if not system_path:
        return None
    payload = json.dumps(
        {
            "seq": int(seq),
            "origin": event.origin,
            "index": event.index_name,
            "logId": event.log_id,
            "kind": event.kind,
            "affectedFiles": list(event.affected_files),
            "ts": time.time(),
        },
        sort_keys=True,
    ).encode("utf-8")
    try:
        d = commits_dir(system_path, event.index_name)
        rid = _next_record_id(d)
        while not write_atomic_exclusive(
            os.path.join(d, f"{rid:0{_RECORD_WIDTH}d}"), payload
        ):
            rid += 1  # another publisher claimed this slot; take the next
        _count_commit_record()
        return rid
    except Exception:
        _count_record_error("commit-write")
        return None


def _next_record_id(dirpath: str) -> int:
    try:
        ids = [int(n) for n in os.listdir(dirpath) if n.isdigit()]
    except OSError:
        return 0
    return max(ids) + 1 if ids else 0


def read_commit_records(
    dirpath: str, after_id: int = -1
) -> List[Tuple[int, dict]]:
    """All parseable commit records in ``dirpath`` with id > ``after_id``,
    ordered by id. Unreadable/corrupt records are counted and skipped — a
    half-written record (impossible under the rename protocol, possible
    under lake-level corruption) must not wedge the watcher."""
    try:
        names = sorted(n for n in os.listdir(dirpath) if n.isdigit())
    except OSError:
        return []
    out: List[Tuple[int, dict]] = []
    for name in names:
        rid = int(name)
        if rid <= after_id:
            continue
        try:
            with open(os.path.join(dirpath, name), "rb") as f:
                out.append((rid, json.loads(f.read().decode("utf-8"))))
        except Exception:
            _count_record_error("commit-read")
    return out


# -- sidecar node files ------------------------------------------------------


def write_node_file(system_path: Optional[str], node_id: str, state: dict) -> bool:
    """Atomically overwrite this node's sidecar file with its cumulative
    coherence state. Returns False (and counts) on failure."""
    if not system_path:
        return False
    payload = dict(state)
    payload["origin"] = node_id
    payload["updatedAt"] = time.time()
    try:
        write_atomic(
            os.path.join(nodes_dir(system_path), f"{_safe_name(node_id)}.json"),
            json.dumps(payload, sort_keys=True).encode("utf-8"),
        )
        return True
    except Exception:
        _count_record_error("node-write")
        return False


def read_peer_node_files(system_path: Optional[str], node_id: str) -> Dict[str, dict]:
    """Every peer's sidecar state keyed by origin, excluding our own file
    and anything unparseable."""
    if not system_path:
        return {}
    d = nodes_dir(system_path)
    try:
        names = [n for n in os.listdir(d) if n.endswith(".json")]
    except OSError:
        return {}
    out: Dict[str, dict] = {}
    for name in names:
        try:
            with open(os.path.join(d, name), "rb") as f:
                state = json.loads(f.read().decode("utf-8"))
        except Exception:
            _count_record_error("node-read")
            continue
        origin = state.get("origin")
        if origin and origin != node_id:
            out[str(origin)] = state
    return out


def _safe_name(node_id: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_.") else "_" for c in node_id)
