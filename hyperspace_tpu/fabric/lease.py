"""Lake-persisted lease with fencing tokens: crash-tolerant single writers.

The fabric's refresh single-writer guarantee was an in-process
``threading.Lock`` — two fabric *processes* could still race a refresh,
and a process killed mid-refresh held nothing a peer could observe. This
module puts the mutex on the lake itself, built from the same primitives
as the operation log:

- **claim = create-exclusive**: a lease on ``name`` is a directory of
  numbered token files ``<system.path>/_fabric/leases/<name>/t-NNNNNNNN``;
  the *highest-numbered parseable file is the current lease*. Acquiring
  claims token ``current+1`` with ``write_atomic_exclusive`` — exactly one
  of any number of racing processes wins the claim, with no coordinator.
- **fencing token = the claim number**: monotonically increasing across
  the lease's whole history, including takeovers. A holder presents its
  token at commit time (:func:`fence_scope` wraps the refresh and
  ``IndexLogManager.write_log`` calls :meth:`Lease.verify`); a zombie —
  paused past expiry and taken over — sees a higher token on the lake and
  its late commit raises :class:`LeaseLostError` instead of landing.
- **heartbeat renewal**: the holder periodically rewrites its own token
  file with an extended expiry (atomic temp+rename overwrite;
  ``lease.renew`` is a fault-injection seam). Renewal re-lists the
  directory first, so a fenced holder *learns* it lost rather than
  resurrecting a stolen lease.
- **expiry takeover**: an expired current token makes the lease claimable
  by anyone; the claim race above picks exactly one successor.

Clocks are injected (``clock=time.time``) so expiry and takeover are
deterministic under test; production uses wall time, and a skewed clock
can only make takeover *late* (a peer's unexpired view wins) — fencing,
not time, protects the commit itself.

All crash cases degrade safely: a holder that dies simply stops renewing
and is taken over after TTL; a claimant that dies between claim and use
*is* the holder and expires like any other. Dead token files below the
current one are garbage-collected by :mod:`hyperspace_tpu.fabric.fsck`.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from hyperspace_tpu.fabric.records import FABRIC_DIR, _safe_name
from hyperspace_tpu.utils.file_utils import write_atomic, write_atomic_exclusive

__all__ = [
    "Lease",
    "LeaseLostError",
    "acquire",
    "current_fence",
    "fence_scope",
    "leases_dir",
    "read_state",
]

#: zero-padded token ids keep lexicographic == numeric ordering in listings
_TOKEN_WIDTH = 8
_TOKEN_PREFIX = "t-"


class LeaseLostError(RuntimeError):
    """The holder's fencing token is no longer current: a peer took over
    after expiry. Raised at renewal and — via :func:`fence_scope` — at the
    operation-log commit point, so a zombie's late commit never lands."""

    def __init__(self, name: str, held_token: int, current_token: int):
        super().__init__(
            f"lease {name!r} lost: held token {held_token}, "
            f"lake shows token {current_token}"
        )
        self.name = name
        self.held_token = held_token
        self.current_token = current_token


def _count_acquire(outcome: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_lease_acquires_total",
        "lake lease acquisition attempts (acquired | takeover | busy)",
        outcome=outcome,
    ).inc()


def _count_renewal(outcome: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_lease_renewals_total",
        "lake lease heartbeat renewals (ok | lost | error)",
        outcome=outcome,
    ).inc()


def _count_fenced() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_lease_fenced_total",
        "commits rejected by the lease fencing check (zombie writers)",
    ).inc()


def leases_dir(system_path: str, name: Optional[str] = None) -> str:
    d = os.path.join(str(system_path), FABRIC_DIR, "leases")
    return d if name is None else os.path.join(d, _safe_name(name))


def _token_path(lease_dir: str, token: int) -> str:
    return os.path.join(lease_dir, f"{_TOKEN_PREFIX}{token:0{_TOKEN_WIDTH}d}")


def _list_tokens(lease_dir: str) -> List[int]:
    try:
        names = os.listdir(lease_dir)
    except OSError:
        return []
    out = []
    for n in names:
        if n.startswith(_TOKEN_PREFIX) and n[len(_TOKEN_PREFIX):].isdigit():
            out.append(int(n[len(_TOKEN_PREFIX):]))
    return sorted(out)


def read_state(
    system_path: str, name: str
) -> Tuple[int, Optional[Dict]]:
    """``(current_token, state)`` for a lease — the highest-numbered
    parseable token file, or ``(0, None)`` for a never-claimed lease. A
    torn/corrupt current file still *counts* for the token sequence
    (claimants must number past it) but reads as an expired state, so it
    is immediately claimable rather than wedging the lease forever."""
    d = leases_dir(system_path, name)
    tokens = _list_tokens(d)
    if not tokens:
        return 0, None
    current = tokens[-1]
    try:
        with open(_token_path(d, current), "rb") as f:
            return current, json.loads(f.read().decode("utf-8"))
    except Exception:
        return current, None


class Lease:
    """A held lease: fencing token + renewal/verify/release handles.

    Constructed by :func:`acquire` only. Thread-safe for the intended
    pattern (owner thread works, heartbeat thread renews)."""

    def __init__(
        self,
        system_path: str,
        name: str,
        holder: str,
        token: int,
        ttl_s: float,
        expires_at: float,
        clock: Callable[[], float],
    ):
        self.system_path = str(system_path)
        self.name = str(name)
        self.holder = str(holder)
        self.token = int(token)
        self.ttl_s = float(ttl_s)
        self.expires_at = float(expires_at)
        self._clock = clock
        self._lost = False
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()

    @property
    def path(self) -> str:
        return _token_path(leases_dir(self.system_path, self.name), self.token)

    @property
    def lost(self) -> bool:
        return self._lost

    def _payload(self, expires_at: float) -> bytes:
        return json.dumps(
            {
                "holder": self.holder,
                "token": self.token,
                "expiresAt": expires_at,
                "ttlSeconds": self.ttl_s,
            },
            sort_keys=True,
        ).encode("utf-8")

    # -- heartbeat renewal ---------------------------------------------------
    def renew(self) -> bool:
        """Extend the lease by one TTL from now. Returns False — and marks
        the lease lost — when a peer's takeover token is on the lake; a
        fenced holder must stop, not re-assert itself."""
        from hyperspace_tpu.reliability.faults import FAULTS

        if self._lost:
            return False
        current, _ = read_state(self.system_path, self.name)
        if current != self.token:
            self._lost = True
            _count_renewal("lost")
            return False
        try:
            if FAULTS.active:
                FAULTS.check("lease.renew", self.path)
            now = self._clock()
            write_atomic(self.path, self._payload(now + self.ttl_s))
            self.expires_at = now + self.ttl_s
        except OSError:
            # a failed renewal write is not a loss: the prior expiry still
            # stands and the next beat retries. Only takeover loses a lease.
            _count_renewal("error")
            return True
        _count_renewal("ok")
        return True

    def start_heartbeat(self, interval_s: float) -> "Lease":
        """Renew every ``interval_s`` on a daemon thread until released,
        fenced, or stopped (tests drive :meth:`renew` directly instead)."""
        if self._hb_thread is None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._hb_run,
                args=(float(interval_s),),
                name=f"hs-lease-{_safe_name(self.name)}",
                daemon=True,
            )
            self._hb_thread.start()
        return self

    def _hb_run(self, interval_s: float) -> None:
        while not self._hb_stop.wait(interval_s):
            try:
                if not self.renew():
                    return
            except Exception:
                # an unclassifiable renewal failure (injected corrupt, lake
                # error) ends the heartbeat but not the lease: the holder
                # keeps its current expiry and the fence still governs
                return

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        thread = self._hb_thread
        self._hb_thread = None
        if thread is not None:
            thread.join(timeout=5)

    # -- fencing -------------------------------------------------------------
    def verify(self) -> None:
        """The fencing check: raise :class:`LeaseLostError` unless this
        token is still the lease's current one on the lake. Called at the
        operation-log commit point via :func:`fence_scope`, so a zombie
        writer fails *before* its entry lands."""
        current, _ = read_state(self.system_path, self.name)
        if current != self.token:
            self._lost = True
            _count_fenced()
            raise LeaseLostError(self.name, self.token, current)

    def release(self) -> None:
        """Zero the expiry so the next acquirer takes over immediately.
        The token file stays — the fencing sequence must never restart
        while successors can still race (fsck GCs superseded tokens)."""
        self.stop_heartbeat()
        if self._lost:
            return
        current, _ = read_state(self.system_path, self.name)
        if current != self.token:
            self._lost = True
            return
        try:
            write_atomic(self.path, self._payload(0.0))
        except OSError:
            pass  # unreleased = held until TTL; safe, just slower takeover
        self.expires_at = 0.0

    def __repr__(self) -> str:
        return (
            f"Lease({self.name!r}, holder={self.holder!r}, token={self.token}, "
            f"expires_at={self.expires_at:.3f}, lost={self._lost})"
        )


def acquire(
    system_path: str,
    name: str,
    holder: str,
    ttl_s: float,
    clock: Callable[[], float] = time.time,
) -> Optional[Lease]:
    """Try to acquire the lease once (non-blocking). Returns the held
    :class:`Lease` or None when a live holder exists or a racer won the
    claim. Counted in ``hs_fabric_lease_acquires_total`` by outcome."""
    now = clock()
    current, state = read_state(system_path, name)
    if state is not None and float(state.get("expiresAt", 0.0)) > now:
        _count_acquire("busy")
        return None
    token = current + 1
    lease = Lease(system_path, name, holder, token, ttl_s, now + float(ttl_s), clock)
    if not write_atomic_exclusive(lease.path, lease._payload(lease.expires_at)):
        # a racing claimant took this exact token between our read and claim
        _count_acquire("busy")
        return None
    _count_acquire("takeover" if current > 0 else "acquired")
    return lease


# -- the commit-time fencing hook --------------------------------------------

_FENCE: "contextvars.ContextVar[Optional[Lease]]" = contextvars.ContextVar(
    "hs_fabric_lease_fence", default=None
)


def current_fence() -> Optional[Lease]:
    """The lease guarding the current refresh, or None. Consulted by
    ``IndexLogManager.write_log`` — one contextvar read when no lease is
    in scope, so the default-off path stays free."""
    return _FENCE.get()


class fence_scope:
    """Bind a lease as the commit fence for the ``with`` block. Entering
    with ``None`` is a no-op, so callers don't branch on lease mode."""

    def __init__(self, lease: Optional[Lease]):
        self._lease = lease
        self._token = None

    def __enter__(self) -> Optional[Lease]:
        if self._lease is not None:
            self._token = _FENCE.set(self._lease)
        return self._lease

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _FENCE.reset(self._token)
            self._token = None
