"""CommitWatcher: tail peer commit records and replay them locally.

The watcher is the read half of log-driven coherence: every poll it sweeps
``<system.path>/<index>/_hyperspace_log/_commits/`` for records it has not
replayed, skips its own (``origin`` == local node id — a process must not
re-purge for its own publish), and replays the rest onto the session's
:class:`InvalidationBus`. Replay runs the exact invalidation path a local
commit runs — roster TTL clear, targeted bucket/IO/device byte-cache
purges, subscriber fan-out — and advances the local commit sequence to the
record's persisted sequence, so brand rotation and session tokens change in
this process within one poll interval of the remote commit.

Cost model: the steady-state poll is one ``stat`` per index commit
directory (the mtime fast-path); records are listed and read only when a
directory actually changed. A directory whose mtime is within
``_MTIME_SETTLE_S`` of now is always re-listed — directory mtime
granularity is coarse enough that two records landing in one tick around a
poll could otherwise leave the second invisible until the next commit.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, Optional

from hyperspace_tpu.fabric import records
from hyperspace_tpu.lifecycle.invalidation import CommitEvent

__all__ = ["CommitWatcher"]

#: re-list a commit dir whose mtime is this recent even if unchanged
_MTIME_SETTLE_S = 2.0


def _registry():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY


class CommitWatcher:
    """Poll-driven replay of peer commit records (one per session).

    ``poll_once`` is the deterministic unit tests drive directly; ``start``
    runs it on a daemon thread every ``interval`` seconds. The watcher holds
    only a weakref to its session: a dropped session ends the thread on its
    next wakeup instead of leaking through the poll loop.
    """

    def __init__(
        self,
        session,
        node_id: Optional[str] = None,
        interval: Optional[float] = None,
    ):
        self._session_ref = weakref.ref(session)
        self.node_id = node_id or records.local_node_id(session.conf)
        self.interval = float(
            session.conf.fabric_poll_interval_seconds if interval is None else interval
        )
        self._cursors: Dict[str, int] = {}  # index name -> last replayed record id
        self._mtimes: Dict[str, int] = {}  # commits dir -> st_mtime_ns at last list
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # per-node staleness observability (docs/scale-out.md): how long ago
        # this node's watcher finished a sweep, and how far behind the log it
        # was when it did — together they bound observable staleness against
        # the configured poll interval. -1 = never polled.
        self._last_poll_at: Optional[float] = None
        reg = _registry()
        # a stable unixtime, NOT a live age: re-rendering the exposition
        # must be byte-identical between sweeps (the /metrics endpoint
        # contract); scrapers compute age as time() - value
        reg.gauge(
            "hs_fabric_watcher_last_poll_seconds",
            "unixtime at which this node's commit watcher completed its "
            "latest poll sweep (-1 before the first sweep)",
            fn=lambda: (
                -1.0 if self._last_poll_at is None else self._last_poll_at
            ),
            server=self.node_id,
        )

    # -- polling -------------------------------------------------------------
    def poll_once(self) -> int:
        """One sweep over every index's commit directory; returns the number
        of remote records replayed."""
        session = self._session_ref()
        if session is None:
            return 0
        root = session.conf.system_path
        if not root or not os.path.isdir(root):
            return 0
        reg = _registry()
        reg.counter("hs_fabric_polls_total", "commit-watcher poll sweeps").inc()
        replayed = 0
        newest_ts: Optional[float] = None
        for name in sorted(os.listdir(root)):
            if name.startswith((".", "_")):
                continue
            cdir = records.commits_dir(root, name)
            try:
                st = os.stat(cdir)
            except OSError:
                continue  # index without commit records (or gone)
            settled = (time.time() - st.st_mtime) > _MTIME_SETTLE_S
            if self._mtimes.get(cdir) == st.st_mtime_ns and settled:
                reg.counter(
                    "hs_fabric_poll_skips_total",
                    "commit directories skipped by the mtime fast-path",
                ).inc()
                continue
            self._mtimes[cdir] = st.st_mtime_ns
            cursor = self._cursors.get(name, -1)
            for rid, rec in records.read_commit_records(cdir, after_id=cursor):
                self._cursors[name] = rid
                if rec.get("origin") == self.node_id:
                    # our own publish already purged these caches
                    reg.counter(
                        "hs_fabric_self_skips_total",
                        "own commit records skipped by the watcher (dedupe)",
                    ).inc()
                    continue
                event = CommitEvent(
                    rec.get("index", name),
                    rec.get("logId"),
                    rec.get("kind", "remote"),
                    rec.get("affectedFiles") or (),
                    origin=rec.get("origin"),
                )
                session.lifecycle_bus.replay(event, seq=rec.get("seq"))
                ts = rec.get("ts")
                if ts is not None:
                    reg.gauge(
                        "hs_fabric_replay_lag_seconds",
                        "commit-to-replay lag of the most recent replayed record",
                    ).set(max(0.0, time.time() - float(ts)))
                    if newest_ts is None or float(ts) > newest_ts:
                        newest_ts = float(ts)
                replayed += 1
        # per-node commit lag: distance between remote publish and this
        # sweep's replay. A sweep that found nothing to replay means this
        # node is caught up with every record it can see — lag 0, which is
        # what makes the gauge a staleness BOUND rather than a last-event
        # memory (docs/scale-out.md).
        reg.gauge(
            "hs_fabric_commit_lag_seconds",
            "publish-to-replay lag of this node against the commit log "
            "(0 when the last sweep found nothing left to replay)",
            server=self.node_id,
        ).set(
            max(0.0, time.time() - newest_ts) if newest_ts is not None else 0.0
        )
        self._last_poll_at = time.time()
        return replayed

    # -- thread lifecycle ----------------------------------------------------
    def start(self) -> "CommitWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="hs-fabric-watcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if self._session_ref() is None:
                return
            try:
                self.poll_once()
            except Exception:  # pragma: no cover — a bad poll must not kill the loop
                pass
