"""FrontDoor: spread tenants across fabric worker processes.

A thin routing front with no query smarts of its own: it picks a worker
per tenant with **rendezvous (highest-random-weight) hashing** — stable
under worker join/leave (only the departed worker's tenants move), no
shared state, no coordinator — forwards the query text plus tenant id and
deadline, and aggregates the workers' ``/metrics`` into one exposition
(worker series stay distinguishable by their per-process ``server="qsN"``
labels, which is why ``QueryServer`` accepts an explicit ``name``).

Workers come in two flavors, freely mixed:

- an in-process :class:`~hyperspace_tpu.serving.server.QueryServer`
  (tests, single-process topologies);
- a base URL of a :class:`WorkerEndpoint` — the stdlib-HTTP shim that
  exposes one QueryServer to other processes (``GET/POST /query``,
  ``/metrics``, ``/statusz``, ``/healthz``). Results travel as JSON
  columns and come back as numpy arrays, same shape ``collect()`` returns.

Crash tolerance (``hyperspace.fabric.health.*``, default off — at
defaults routing is the original single-candidate raise-on-failure):

- **typed errors over the wire**: a worker failure is classified through
  ``reliability.errors.classify`` *on the worker*, serialized in the JSON
  body (``errorType``/``kind``/``retryable``), and rehydrated here as
  :class:`WorkerUnavailable` (retry elsewhere may help) or
  :class:`WorkerError` (the query itself is bad — retrying rereads the
  same wrong bytes), so retry/no-retry decisions survive the process hop.
- **health-aware membership**: a :class:`~hyperspace_tpu.fabric.health.HealthTracker`
  ejects workers on consecutive failures, missed sidecar heartbeats
  (:meth:`FrontDoor.check_beats`), or ``/healthz`` commit-seq staleness
  (:meth:`FrontDoor.probe`); tenants re-hash to the survivors and the
  ejected worker returns via a half-open probe.
- **deadline-aware failover**: ``query`` walks the tenant's rendezvous
  preference order, retrying a :class:`WorkerUnavailable` on the next
  candidate while the caller's deadline allows
  (``hs_frontdoor_failover_retries_total``).
- **hedged reads**: with ``hedgeMs`` set, a primary silent past the hedge
  delay gets its (idempotent) query mirrored to the next candidate;
  first answer wins (``hs_frontdoor_failover_hedges_total``).

Distributed observability (``hyperspace.obs.fabric.*``, see
docs/observability.md "Distributed tracing"): with tracing on, every routed
request roots a ``frontdoor-request`` trace whose ``route`` children record
each attempt (worker, outcome, hedge/retry siblings); propagation stamps a
W3C ``traceparent`` header (plus the ``x-hs-stitch`` byte budget when
stitching is on) so the worker's tree carries the router's trace id, and
stitching grafts the worker's returned span tree under the attempt span —
``last_query_profile()`` and the Chrome export then show ONE end-to-end
trace with per-process attribution. ``/profilez``/``/statusz`` federation
merges the workers' profile histories and SLO burn views.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence

from hyperspace_tpu.obs import spans

__all__ = [
    "FrontDoor",
    "WorkerEndpoint",
    "WorkerError",
    "WorkerUnavailable",
    "rendezvous_pick",
    "rendezvous_order",
    "merge_prometheus_texts",
]


def _rendezvous_weight(key: str, node: str) -> bytes:
    return hashlib.sha256(f"{key}|{node}".encode("utf-8")).digest()


def rendezvous_pick(key: str, nodes: Sequence[str]) -> str:
    """The highest-random-weight node for ``key``: every participant
    computes the same winner from the membership list alone."""
    if not nodes:
        raise ValueError("rendezvous_pick needs at least one node")
    return max(nodes, key=lambda n: _rendezvous_weight(key, n))


def rendezvous_order(key: str, nodes: Sequence[str]) -> List[str]:
    """All nodes in descending rendezvous weight — the key's full failover
    preference order. ``rendezvous_order(k, ns)[0] == rendezvous_pick(k, ns)``,
    and removing the winner promotes exactly the next entry, so failover
    lands where the tenant would re-hash anyway."""
    if not nodes:
        raise ValueError("rendezvous_order needs at least one node")
    return sorted(nodes, key=lambda n: _rendezvous_weight(key, n), reverse=True)


def merge_prometheus_texts(texts: Sequence[str]) -> str:
    """Merge several Prometheus 0.0.4 expositions into one: each family's
    ``# HELP``/``# TYPE`` header appears once, with every worker's samples
    (already disjoint by their ``server`` labels) concatenated under it."""
    headers: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []
    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                family = parts[2] if len(parts) >= 3 else ""
            else:
                family = line.split("{", 1)[0].split(None, 1)[0]
            if family not in headers:
                headers[family] = []
                samples[family] = []
                order.append(family)
            if line.startswith("#"):
                if line not in headers[family]:
                    headers[family].append(line)
            elif line not in samples[family]:
                samples[family].append(line)
    out: List[str] = []
    for family in order:
        out.extend(headers[family])
        out.extend(samples[family])
    return "\n".join(out) + ("\n" if out else "")


class WorkerUnavailable(RuntimeError):
    """The worker could not answer (transport failure, injected/classified
    transient, admission shed): the *same* query on another worker may
    succeed, so this is the failover-retryable wire error."""

    def __init__(self, message: str, error_type: str = "", kind: str = "transient"):
        super().__init__(message)
        self.error_type = error_type
        self.kind = kind


class WorkerError(RuntimeError):
    """The worker answered with a non-retryable typed error (bad SQL,
    corrupt data): every worker would fail identically, so the error goes
    straight to the caller instead of burning failover attempts."""

    def __init__(self, message: str, error_type: str = "", kind: str = "error"):
        super().__init__(message)
        self.error_type = error_type
        self.kind = kind


def _registry():
    from hyperspace_tpu.obs.metrics import REGISTRY

    return REGISTRY


def _count_route(worker: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_frontdoor_requests_total",
        "requests routed through the FrontDoor, by worker",
        worker=worker,
    ).inc()


def _count_failover_retry(worker: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_frontdoor_failover_retries_total",
        "failed attempts rerouted to the next rendezvous candidate, by "
        "the worker that failed",
        worker=worker,
    ).inc()


def _count_failover_exhausted() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_frontdoor_failover_exhausted_total",
        "requests that failed every eligible candidate (or ran out of "
        "deadline) and surfaced a typed error",
    ).inc()


def _count_hedge() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_frontdoor_failover_hedges_total",
        "hedged requests fired to a backup worker after the primary "
        "stayed silent past the hedge delay",
    ).inc()


def _retryable(exc: BaseException, worker: Any) -> bool:
    """May the same query succeed on another worker? Wire errors carry the
    answer; in-process exceptions are classified locally with the same
    ``reliability.errors`` taxonomy the worker side uses."""
    if isinstance(exc, WorkerError):
        return False
    if isinstance(exc, WorkerUnavailable):
        return True
    if isinstance(worker, str):
        return False  # HTTP path always raises the two typed errors above
    from hyperspace_tpu.reliability import errors as rel_errors

    return not rel_errors.is_corrupt(exc)


class FrontDoor:
    """Tenant-affine router over a fixed worker set (see module docstring).

    ``health``/``failover``/``hedge_ms`` default to the PR-13 behavior
    (single candidate, raise on failure). Pass ``conf`` (a session conf
    with ``hyperspace.fabric.health.enabled``) or explicit kwargs to turn
    the crash-tolerance machinery on.
    """

    def __init__(
        self,
        workers: Sequence[Any],
        *,
        health: Optional[Any] = None,
        failover: bool = False,
        hedge_ms: float = 0.0,
        system_path: Optional[str] = None,
        clock=time.monotonic,
        conf: Optional[Any] = None,
    ):
        if not workers:
            raise ValueError("FrontDoor needs at least one worker")
        self._workers: Dict[str, Any] = {}
        for i, w in enumerate(workers):
            if isinstance(w, str):
                self._workers[f"w{i}:{w}"] = w.rstrip("/")
            else:
                self._workers[getattr(w, "server_name", f"w{i}")] = w
        self._ids = sorted(self._workers)
        self._clock = clock
        if conf is not None and conf.fabric_health_enabled and health is None:
            from hyperspace_tpu.fabric.health import HealthTracker

            health = HealthTracker(
                failure_threshold=conf.fabric_health_failure_threshold,
                probe_interval_s=conf.fabric_health_probe_interval_seconds,
                heartbeat_interval_s=conf.fabric_health_heartbeat_interval_seconds,
                missed_beats=conf.fabric_health_missed_beats,
                max_commit_lag=conf.fabric_health_max_commit_lag,
            )
            failover = True
            hedge_ms = conf.fabric_health_hedge_ms
            system_path = system_path or conf.system_path
        self._health = health
        self._failover = bool(failover) or health is not None
        self._hedge_s = float(hedge_ms) / 1000.0
        self._system_path = system_path
        #: worker id -> fabric node id, learned from /healthz bodies; maps
        #: sidecar heartbeat ledgers back onto rendezvous members
        self._nodes: Dict[str, str] = {}
        # distributed observability (hyperspace.obs.fabric.*): without a
        # conf the router stays untraced with propagation semantics at their
        # defaults (headers on when a trace exists, stitching off)
        self._tracing = bool(conf.obs_tracing_enabled) if conf is not None else False
        self._trace_max_spans = conf.obs_trace_max_spans if conf is not None else 100_000
        self._propagate = bool(conf.obs_fabric_propagate) if conf is not None else True
        self._stitch = bool(conf.obs_fabric_stitch_enabled) if conf is not None else False
        self._stitch_max_spans = conf.obs_fabric_stitch_max_spans if conf is not None else 512
        self._stitch_max_bytes = conf.obs_fabric_stitch_max_bytes if conf is not None else 262_144
        self._fed_timeout = (
            conf.obs_fabric_federation_timeout_seconds if conf is not None else 30.0
        )
        self._profiles: "deque" = deque(
            maxlen=max(1, conf.obs_profile_history) if conf is not None else 16
        )
        self.flight = None
        self._slow_s = None
        if conf is not None and conf.obs_slow_query_ms > 0:
            from hyperspace_tpu.obs.history import FlightRecorder

            self._slow_s = conf.obs_slow_query_ms / 1000.0
            self.flight = FlightRecorder(
                max_entries=conf.obs_slow_query_max_entries,
                directory=conf.obs_slow_query_dir or None,
                registry=_registry(),
                server="frontdoor",
            )

    @property
    def worker_ids(self) -> List[str]:
        return list(self._ids)

    @property
    def health(self) -> Optional[Any]:
        return self._health

    def pick(self, tenant: str) -> str:
        return rendezvous_pick(str(tenant), self._ids)

    def _candidates(self, tenant: str) -> List[str]:
        """The tenant's failover preference order over the currently-live
        membership. Without failover this is the single PR-13 pick."""
        ids = self._ids
        if self._health is not None:
            ids = self._health.live(ids)
        order = rendezvous_order(str(tenant), ids)
        return order if self._failover else order[:1]

    # -- queries -------------------------------------------------------------
    def query(
        self,
        sql: str,
        tenant: str = "default",
        timeout: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Route one SQL query to the tenant's worker and return the
        collected batch (dict of numpy arrays, like ``collect()``). With
        failover on, a retryable failure moves to the next rendezvous
        candidate while the deadline allows; a non-retryable one raises
        immediately. With tracing on, the request roots a
        ``frontdoor-request`` trace carrying every attempt (and, when
        stitching is on, the workers' grafted span trees)."""
        if not self._tracing and self.flight is None:
            return self._route(sql, tenant, timeout, None)
        root = None
        ctx = None
        if self._tracing:
            ctx = spans.TraceContext.new()
            root = spans.start_trace(
                "frontdoor-request",
                cat="fabric",
                max_spans=self._trace_max_spans,
                query=sql,
                tenant=tenant,
            )
            root.attrs["trace_id"] = ctx.trace_id
        info: Dict[str, Any] = {"retries": 0, "hedged": False, "worker": None}
        t0 = time.monotonic()
        error: Optional[str] = None
        try:
            with spans.attach(root), spans.bind_context(ctx):
                return self._route(sql, tenant, timeout, info)
        except Exception as exc:
            error = type(exc).__name__
            raise
        finally:
            self._seal_route(root, sql, tenant, time.monotonic() - t0, error, info)

    def _route(
        self,
        sql: str,
        tenant: str,
        timeout: Optional[float],
        info: Optional[Dict[str, Any]],
    ) -> Dict[str, Any]:
        candidates = self._candidates(tenant)
        if self._hedge_s > 0 and len(candidates) > 1:
            return self._hedged_query(candidates, sql, tenant, timeout, info)
        deadline = None if timeout is None else self._clock() + timeout
        last_exc: Optional[BaseException] = None
        for i, wid in enumerate(candidates):
            remaining = timeout
            if deadline is not None:
                remaining = deadline - self._clock()
                if i > 0 and remaining <= 0:
                    break  # deadline spent: don't start an attempt that can't finish
            _count_route(wid)
            worker = self._workers[wid]
            try:
                out = self._attempt(wid, worker, sql, tenant, remaining)
            except Exception as exc:
                if not self._failover or not _retryable(exc, worker):
                    if self._health is not None and _retryable(exc, worker):
                        self._health.note_failure(wid)
                    raise
                if self._health is not None:
                    self._health.note_failure(wid)
                _count_failover_retry(wid)
                if info is not None:
                    info["retries"] += 1
                last_exc = exc
                continue
            if self._health is not None:
                self._health.note_ok(wid)
            if info is not None:
                info["worker"] = wid
            return out
        _count_failover_exhausted()
        if last_exc is not None:
            raise last_exc
        raise WorkerUnavailable(
            f"no candidate answered for tenant {tenant!r} within the deadline"
        )

    def _attempt(
        self, wid: str, worker: Any, sql: str, tenant: str, timeout: Optional[float]
    ) -> Dict[str, Any]:
        """One dispatch wrapped in a ``route`` span — the per-attempt node
        that failover retries and hedges appear as siblings of. The hop gets
        a child TraceContext so the worker's tree records WHICH attempt
        parented it (``span_id`` here == the worker root's
        ``parent_span_id``)."""
        ctx = spans.current_context()
        hop = ctx.child() if ctx is not None else None
        with spans.span("route", cat="fabric", worker=wid) as att:
            if hop is not None:
                att.set(span_id=hop.span_id)
            with spans.bind_context(hop):
                try:
                    out = self._dispatch(worker, sql, tenant, timeout)
                except Exception as exc:
                    att.set(outcome="error", error=type(exc).__name__)
                    raise
                att.set(outcome="ok")
                return out

    def _dispatch(
        self, worker: Any, sql: str, tenant: str, timeout: Optional[float]
    ) -> Dict[str, Any]:
        if isinstance(worker, str):
            return self._http_query(worker, sql, tenant, timeout)
        cur = spans.current_span()
        if cur is None:
            return worker.query(sql, timeout=timeout, tenant=tenant)
        # traced in-process dispatch: go through submit() so the worker's
        # span tree (fut.request_root) is graftable; same-process trees share
        # a perf_counter domain, so anchoring at the worker root's own t0
        # keeps the stitched alignment exact
        fut = worker.submit(sql, timeout=timeout, tenant=tenant)
        t = worker.admission.default_timeout if timeout is None else timeout
        try:
            return fut.result(timeout=None if t is None else t + 5.0)
        finally:
            wroot = getattr(fut, "request_root", None)
            if wroot is not None:
                wire = spans.to_wire(
                    wroot, self._stitch_max_spans, self._stitch_max_bytes
                )
                spans.graft_remote(cur, wire, anchor_t0=wroot.t0)

    def _hedged_query(
        self,
        candidates: List[str],
        sql: str,
        tenant: str,
        timeout: Optional[float],
        info: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Primary + (on silence or failure) one backup, first answer wins.
        Safe because FrontDoor queries are idempotent reads — both answers
        are correct, we just keep whichever lands first. Each runner carries
        the caller's span context across its thread (``spans.attach``), so
        primary and hedge show as sibling ``route`` spans on one tree."""
        results: "queue.Queue" = queue.Queue()
        parent = spans.current_span()
        ctx = spans.current_context()

        def run(wid: str, hedge: bool) -> None:
            _count_route(wid)
            hop = ctx.child() if ctx is not None else None
            with spans.attach(parent), spans.span(
                "route", cat="fabric", worker=wid, hedge=hedge
            ) as att:
                if hop is not None:
                    att.set(span_id=hop.span_id)
                try:
                    with spans.bind_context(hop):
                        out = self._dispatch(self._workers[wid], sql, tenant, timeout)
                except Exception as exc:  # delivered to the caller via the queue
                    att.set(outcome="error", error=type(exc).__name__)
                    results.put((wid, exc, None))
                else:
                    att.set(outcome="ok")
                    results.put((wid, None, out))

        def spawn(wid: str, hedge: bool = False) -> None:
            threading.Thread(target=run, args=(wid, hedge), daemon=True).start()

        spawn(candidates[0])
        outstanding, hedged = 1, False
        first_exc: Optional[BaseException] = None
        while outstanding:
            try:
                wid, exc, out = results.get(timeout=None if hedged else self._hedge_s)
            except queue.Empty:
                hedged = True
                outstanding += 1
                _count_hedge()
                if info is not None:
                    info["hedged"] = True
                spawn(candidates[1], hedge=True)
                continue
            outstanding -= 1
            if exc is None:
                if self._health is not None:
                    self._health.note_ok(wid)
                if info is not None:
                    info["worker"] = wid
                return out
            if self._health is not None and _retryable(exc, self._workers[wid]):
                self._health.note_failure(wid)
            if first_exc is None or not isinstance(exc, WorkerUnavailable):
                first_exc = exc
            if not hedged:
                # the primary failed outright before the hedge delay: the
                # backup is now a failover attempt, not a hedge
                hedged = True
                outstanding += 1
                _count_failover_retry(wid)
                if info is not None:
                    info["retries"] += 1
                spawn(candidates[1])
        _count_failover_exhausted()
        assert first_exc is not None
        raise first_exc

    def _trace_headers(self) -> Dict[str, str]:
        """Propagation headers for one worker hop: the current context's
        ``traceparent`` plus the stitch byte budget when stitched-tree
        shipping is on. Empty (no extra request bytes at all) when no trace
        is active or propagation is conf'd off."""
        headers: Dict[str, str] = {}
        ctx = spans.current_context()
        if self._propagate and ctx is not None:
            headers["traceparent"] = ctx.to_traceparent()
            if self._stitch:
                headers["x-hs-stitch"] = str(self._stitch_max_bytes)
        return headers

    def _http_query(
        self, base: str, sql: str, tenant: str, timeout: Optional[float]
    ) -> Dict[str, Any]:
        import numpy as np

        from hyperspace_tpu.reliability.faults import FAULTS

        params = {"sql": sql, "tenant": tenant}
        if timeout is not None:
            params["timeoutMs"] = str(int(timeout * 1000))
        url = f"{base}/query?{urllib.parse.urlencode(params)}"
        http_timeout = 300.0 if timeout is None else timeout + 5.0
        try:
            # the seam lives inside the handler so an injected transient
            # (an OSError subclass) surfaces as WorkerUnavailable, exactly
            # like the real connection failure it stands in for
            if FAULTS.active:
                FAULTS.check("fabric.http", f"{base}/query")
            request = urllib.request.Request(url, headers=self._trace_headers())
            with urllib.request.urlopen(request, timeout=http_timeout) as resp:
                body = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            # the endpoint replies with a typed JSON error body on 4xx/5xx;
            # surface it instead of the bare transport error
            try:
                body = json.loads(exc.read().decode("utf-8"))
            except Exception:
                raise WorkerUnavailable(
                    f"worker {base} failed: HTTP {exc.code}",
                    error_type="HTTPError",
                ) from exc
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            # connection refused / reset / timed out: the process is gone or
            # unreachable — exactly what failover exists for
            raise WorkerUnavailable(
                f"worker {base} unreachable: {exc}", error_type=type(exc).__name__
            ) from exc
        remote_trace = body.get("trace")
        if remote_trace:
            # stitch the worker's serialized tree under the live attempt
            # span; anchoring at the attempt's start folds the network hop
            # into the alignment error (documented in observability.md)
            cur = spans.current_span()
            if cur is not None:
                spans.graft_remote(
                    cur, remote_trace,
                    pid=remote_trace.get("pid"), anchor_t0=cur.t0,
                )
        if "error" in body:
            message = f"worker {base} failed: {body['error']}"
            error_type = str(body.get("errorType", ""))
            kind = str(body.get("kind", ""))
            # re-classification point (reliability.errors taxonomy, serialized
            # by WorkerEndpoint._query): transient → retry elsewhere may help;
            # corrupt/error → every worker fails identically, don't retry
            if body.get("retryable", kind == "transient"):
                raise WorkerUnavailable(message, error_type=error_type,
                                        kind=kind or "transient")
            raise WorkerError(message, error_type=error_type, kind=kind or "error")
        return {k: np.asarray(v) for k, v in body["columns"].items()}

    def _seal_route(
        self,
        root: Optional[Any],
        sql: str,
        tenant: str,
        latency_s: float,
        error: Optional[str],
        info: Dict[str, Any],
    ) -> None:
        """Routing completion hook (mirrors ``QueryServer._seal``): finish
        the router-side tree, publish the end-to-end profile, and
        flight-record slow/errored routed requests with their failover and
        hedge outcomes."""
        profile = None
        if root is not None:
            root.attrs.update(
                retries=info["retries"], hedged=info["hedged"],
                worker=info["worker"],
            )
            from hyperspace_tpu.obs.profile import build_profile

            profile = build_profile(root, query=sql, error=error)
            self._profiles.append(profile)
        if self.flight is not None and (
            error is not None
            or (self._slow_s is not None and latency_s >= self._slow_s)
        ):
            self.flight.record(
                "error" if error is not None else "slow",
                latency_s,
                query=sql,
                tenant=tenant,
                profile=profile,
                route=dict(info),
            )

    # -- routed-request observability ----------------------------------------
    def last_profiles(self) -> List[Any]:
        """Most recent routed-request profiles (end-to-end stitched trees
        when stitching is on), oldest first; empty without tracing."""
        return list(self._profiles)

    def last_query_profile(self) -> Optional[Any]:
        """The most recent routed request's :class:`QueryProfile` — the ONE
        stitched router+worker tree when stitching is on."""
        return self._profiles[-1] if self._profiles else None

    def last_slow_queries(self) -> List[Any]:
        """Routed flight-recorder entries (slow/errored), oldest first."""
        return [] if self.flight is None else self.flight.last_slow_queries()

    # -- federation ----------------------------------------------------------
    def profilez(self) -> Dict[str, Any]:
        """Federated ``/profilez``: every worker's ProfileHistory snapshot
        merged into one fleet view (``obs.history.merge_history_snapshots``
        — P² sketches combine via n-weighted quantile averaging; see the
        documented error model). Per-worker reachability rides along under
        ``workers``."""
        from hyperspace_tpu.obs.history import merge_history_snapshots

        snaps: Dict[str, Optional[Dict[str, Any]]] = {}
        for wid, worker in self._workers.items():
            try:
                if isinstance(worker, str):
                    with urllib.request.urlopen(
                        f"{worker}/profilez", timeout=self._fed_timeout
                    ) as resp:
                        snaps[wid] = json.loads(resp.read().decode("utf-8"))
                else:
                    history = getattr(worker, "history", None)
                    snaps[wid] = None if history is None else history.snapshot()
            except Exception:
                if self._health is None:
                    raise
                self._health.note_failure(wid)
                snaps[wid] = None
        merged = merge_history_snapshots([s for s in snaps.values() if s])
        merged["workers"] = {
            wid: None if s is None else {
                "fingerprints": int(s.get("fingerprints", 0) or 0),
                "evicted": int(s.get("evicted", 0) or 0),
            }
            for wid, s in snaps.items()
        }
        return merged

    def federated_statusz(self) -> Dict[str, Any]:
        """Fleet ``/statusz``: the per-worker bodies (:meth:`statusz`,
        shape unchanged) plus a merged per-tenant SLO view — summed
        good/bad, fleet compliance, and the WORST per-window burn rate
        across workers (the alerting-relevant aggregate: one burning worker
        must not be averaged away by idle peers)."""
        per = self.statusz()
        tenants: Dict[str, Dict[str, Any]] = {}
        for wid, body in per.items():
            if not isinstance(body, dict):
                continue
            slo = body.get("slo") or {}
            for tenant, st in (slo.get("tenants") or {}).items():
                cur = tenants.setdefault(
                    tenant, {"good": 0, "bad": 0, "burnRates": {}}
                )
                cur["good"] += int(st.get("good", 0) or 0)
                cur["bad"] += int(st.get("bad", 0) or 0)
                for window, rate in (st.get("burnRates") or {}).items():
                    prev = cur["burnRates"].get(window)
                    rate = float(rate)
                    if prev is None or rate > prev:
                        cur["burnRates"][window] = rate
        for cur in tenants.values():
            total = cur["good"] + cur["bad"]
            cur["compliance"] = (cur["good"] / total) if total else None
        return {"workers": per, "slo": {"tenants": tenants}}

    # -- health observation --------------------------------------------------
    def probe(self, timeout: float = 5.0) -> Dict[str, Optional[dict]]:
        """One ``/healthz`` sweep over every worker: successes feed
        ``note_ok`` (which is also how an ejected worker's half-open probe
        passes), failures feed ``note_failure``, and the reported
        last-applied ``commitSeq`` values are compared across the fleet to
        eject wedged-but-alive workers (``note_stale``). Returns the
        healthz bodies by worker id (None for unreachable)."""
        out: Dict[str, Optional[dict]] = {}
        seqs: Dict[str, int] = {}
        for wid, worker in self._workers.items():
            if isinstance(worker, str):
                try:
                    with urllib.request.urlopen(
                        f"{worker}/healthz", timeout=timeout
                    ) as resp:
                        body = json.loads(resp.read().decode("utf-8"))
                except Exception:
                    out[wid] = None
                    if self._health is not None:
                        self._health.note_failure(wid)
                    continue
            else:
                body = _local_healthz(worker)
            out[wid] = body
            node = body.get("node")
            if node:
                self._nodes[wid] = str(node)
            if self._health is not None and body.get("ok"):
                self._health.note_ok(wid)
            if "commitSeq" in body:
                seqs[wid] = int(body["commitSeq"])
        if self._health is not None and len(seqs) > 1:
            fleet_max = max(seqs.values())
            for wid, seq in seqs.items():
                self._health.note_stale(wid, fleet_max - seq)
        return out

    def check_beats(self) -> Dict[str, float]:
        """Judge sidecar-heartbeat ages: each worker whose fabric node id
        is known (learned via :meth:`probe`) is checked against its
        ``_fabric/nodes/<node>.json`` ledger's ``updatedAt``. Needs
        ``system_path``; returns observed ages by worker id."""
        ages: Dict[str, float] = {}
        if self._health is None or not self._system_path:
            return ages
        from hyperspace_tpu.fabric import records

        ledgers = records.read_peer_node_files(self._system_path, "")
        now = time.time()
        for wid, node in self._nodes.items():
            state = ledgers.get(node)
            if state is None:
                continue
            age = max(0.0, now - float(state.get("updatedAt", 0.0)))
            ages[wid] = age
            self._health.note_beat(wid, age)
        return ages

    # -- aggregation ---------------------------------------------------------
    def metrics_text(self) -> str:
        """One merged Prometheus exposition over every worker. With health
        tracking on, an unreachable worker is skipped (and noted) instead
        of failing the whole merge."""
        texts = []
        for wid, worker in self._workers.items():
            try:
                if isinstance(worker, str):
                    with urllib.request.urlopen(f"{worker}/metrics", timeout=30) as resp:
                        texts.append(resp.read().decode("utf-8"))
                else:
                    texts.append(worker.prometheus_text())
            except Exception:
                if self._health is None:
                    raise
                self._health.note_failure(wid)
        return merge_prometheus_texts(texts)

    def statusz(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for wid, worker in self._workers.items():
            try:
                if isinstance(worker, str):
                    with urllib.request.urlopen(f"{worker}/statusz", timeout=30) as resp:
                        out[wid] = json.loads(resp.read().decode("utf-8"))
                else:
                    out[wid] = worker.statusz()
            except Exception:
                if self._health is None:
                    raise
                self._health.note_failure(wid)
                out[wid] = None
        return out


def _local_healthz(server, started_at: Optional[float] = None) -> Dict[str, Any]:
    """The /healthz body for one QueryServer — shared by WorkerEndpoint and
    the FrontDoor's in-process probe so both paths report identically:
    admission queue depth (shed pressure), last-applied commit_seq (watcher
    wedge detection), uptime, and the fabric node id (heartbeat mapping)."""
    session = getattr(server, "session", None)
    fabric = getattr(session, "_fabric", None) if session is not None else None
    bus = getattr(session, "lifecycle_bus", None) if session is not None else None
    admission = getattr(server, "admission", None)
    body: Dict[str, Any] = {
        "ok": True,
        "server": getattr(server, "server_name", "?"),
        "queueDepth": int(getattr(admission, "queued", 0) or 0),
        "commitSeq": int(getattr(bus, "commit_seq", 0) or 0),
    }
    if fabric is not None:
        body["node"] = fabric.node_id
    if started_at is not None:
        body["uptimeSeconds"] = max(0.0, time.time() - started_at)
    return body


class WorkerEndpoint:
    """Expose one QueryServer to FrontDoors in other processes over stdlib
    HTTP. Read-mostly by design: ``/query`` executes through the server's
    normal admission path (deadline and tenant forwarded), everything else
    is a snapshot. ``port=0`` binds an ephemeral port (read ``.port``)."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self.server = server
        self._started_at = time.time()
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            daemon_threads = True

            def log_message(self, fmt, *args):  # no stderr chatter per request
                pass

            def do_GET(self):
                try:
                    endpoint._handle(self)
                except BrokenPipeError:
                    pass
                except Exception as exc:  # defensive: never kill the accept loop
                    try:
                        self.send_error(500, explain=str(exc))
                    except Exception:
                        pass

            do_POST = do_GET

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "WorkerEndpoint":
        if self._thread is None:
            self._started_at = time.time()
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"hs-fabric-worker-{self.port}",
                daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "WorkerEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request handling ----------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urllib.parse.urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/query":
            self._query(req, urllib.parse.parse_qs(parsed.query))
        elif path == "/metrics":
            body = self.server.prometheus_text().encode("utf-8")
            self._reply(req, 200, "text/plain; version=0.0.4; charset=utf-8", body)
        elif path == "/statusz":
            self._reply_json(req, 200, self.server.statusz())
        elif path == "/profilez":
            history = getattr(self.server, "history", None)
            if history is None:
                self._reply_json(req, 404, {"error": "profile history disabled"})
            else:
                self._reply_json(req, 200, history.snapshot())
        elif path == "/healthz":
            self._reply_json(
                req, 200, _local_healthz(self.server, started_at=self._started_at)
            )
        else:
            self._reply_json(
                req, 404,
                {"error": "not found",
                 "endpoints": ["/query", "/metrics", "/statusz", "/profilez",
                               "/healthz"]},
            )

    def _stitch_payload(self, fut, stitch_budget: Optional[str]) -> Optional[Dict[str, Any]]:
        """The bounded span-tree payload for a ``/query`` response, or None
        when the router did not ask (no ``x-hs-stitch`` header), the budget
        is malformed, or this worker produced no tree (tracing off).
        Responses without the header stay byte-identical to a build without
        stitching."""
        if not stitch_budget or fut is None:
            return None
        root = getattr(fut, "request_root", None)
        if root is None:
            return None
        try:
            budget = int(stitch_budget)
        except ValueError:
            return None
        conf = self.server.session.conf
        wire = spans.to_wire(
            root,
            max_spans=conf.obs_fabric_stitch_max_spans,
            max_bytes=max(1, min(budget, conf.obs_fabric_stitch_max_bytes)),
        )
        wire["pid"] = os.getpid()
        wire["server"] = self.server.server_name
        return wire

    def _query(self, req: BaseHTTPRequestHandler, query: Dict[str, list]) -> None:
        sql = (query.get("sql") or [None])[0]
        if not sql:
            self._reply_json(
                req, 400,
                {"error": "missing sql parameter", "errorType": "ValueError",
                 "kind": "error", "retryable": False},
            )
            return
        tenant = (query.get("tenant") or ["default"])[0]
        timeout_ms = (query.get("timeoutMs") or [None])[0]
        timeout = None if timeout_ms is None else float(timeout_ms) / 1000.0
        # inbound trace identity: a router's traceparent parents this
        # worker's span tree; malformed headers degrade to untraced
        ctx = spans.parse_traceparent(req.headers.get("traceparent"))
        stitch_budget = req.headers.get("x-hs-stitch")
        fut = None
        try:
            fut = self.server.submit(
                sql, timeout=timeout, tenant=tenant, trace_context=ctx
            )
            t = self.server.admission.default_timeout if timeout is None else timeout
            batch = fut.result(timeout=None if t is None else t + 5.0)
        except Exception as exc:
            # serialize the reliability classification so the FrontDoor can
            # rebuild the retry/no-retry decision on its side of the wire
            from hyperspace_tpu.reliability import errors as rel_errors

            retryable = not rel_errors.is_corrupt(exc)
            body: Dict[str, Any] = {
                "error": f"{type(exc).__name__}: {exc}",
                "errorType": type(exc).__name__,
                "kind": "transient" if retryable else "corrupt",
                "retryable": retryable,
            }
            trace = self._stitch_payload(fut, stitch_budget)
            if trace is not None:
                body["trace"] = trace
            self._reply_json(req, 503 if retryable else 400, body)
            return
        body = {"columns": {k: v.tolist() for k, v in batch.items()}}
        trace = self._stitch_payload(fut, stitch_budget)
        if trace is not None:
            body["trace"] = trace
        self._reply_json(req, 200, body)

    @staticmethod
    def _reply(req: BaseHTTPRequestHandler, code: int, ctype: str, body: bytes) -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    @classmethod
    def _reply_json(cls, req: BaseHTTPRequestHandler, code: int, obj: Any) -> None:
        cls._reply(req, code, "application/json; charset=utf-8",
                   json.dumps(obj, default=str).encode("utf-8"))
