"""FrontDoor: spread tenants across fabric worker processes.

A thin routing front with no query smarts of its own: it picks a worker
per tenant with **rendezvous (highest-random-weight) hashing** — stable
under worker join/leave (only the departed worker's tenants move), no
shared state, no coordinator — forwards the query text plus tenant id and
deadline, and aggregates the workers' ``/metrics`` into one exposition
(worker series stay distinguishable by their per-process ``server="qsN"``
labels, which is why ``QueryServer`` accepts an explicit ``name``).

Workers come in two flavors, freely mixed:

- an in-process :class:`~hyperspace_tpu.serving.server.QueryServer`
  (tests, single-process topologies);
- a base URL of a :class:`WorkerEndpoint` — the stdlib-HTTP shim that
  exposes one QueryServer to other processes (``GET/POST /query``,
  ``/metrics``, ``/statusz``, ``/healthz``). Results travel as JSON
  columns and come back as numpy arrays, same shape ``collect()`` returns.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["FrontDoor", "WorkerEndpoint", "rendezvous_pick", "merge_prometheus_texts"]


def rendezvous_pick(key: str, nodes: Sequence[str]) -> str:
    """The highest-random-weight node for ``key``: every participant
    computes the same winner from the membership list alone."""
    if not nodes:
        raise ValueError("rendezvous_pick needs at least one node")
    return max(
        nodes,
        key=lambda n: hashlib.sha256(f"{key}|{n}".encode("utf-8")).digest(),
    )


def merge_prometheus_texts(texts: Sequence[str]) -> str:
    """Merge several Prometheus 0.0.4 expositions into one: each family's
    ``# HELP``/``# TYPE`` header appears once, with every worker's samples
    (already disjoint by their ``server`` labels) concatenated under it."""
    headers: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []
    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                family = parts[2] if len(parts) >= 3 else ""
            else:
                family = line.split("{", 1)[0].split(None, 1)[0]
            if family not in headers:
                headers[family] = []
                samples[family] = []
                order.append(family)
            if line.startswith("#"):
                if line not in headers[family]:
                    headers[family].append(line)
            elif line not in samples[family]:
                samples[family].append(line)
    out: List[str] = []
    for family in order:
        out.extend(headers[family])
        out.extend(samples[family])
    return "\n".join(out) + ("\n" if out else "")


def _count_route(worker: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_frontdoor_requests_total",
        "requests routed through the FrontDoor, by worker",
        worker=worker,
    ).inc()


class FrontDoor:
    """Tenant-affine router over a fixed worker set (see module docstring)."""

    def __init__(self, workers: Sequence[Any]):
        if not workers:
            raise ValueError("FrontDoor needs at least one worker")
        self._workers: Dict[str, Any] = {}
        for i, w in enumerate(workers):
            if isinstance(w, str):
                self._workers[f"w{i}:{w}"] = w.rstrip("/")
            else:
                self._workers[getattr(w, "server_name", f"w{i}")] = w
        self._ids = sorted(self._workers)

    @property
    def worker_ids(self) -> List[str]:
        return list(self._ids)

    def pick(self, tenant: str) -> str:
        return rendezvous_pick(str(tenant), self._ids)

    # -- queries -------------------------------------------------------------
    def query(
        self,
        sql: str,
        tenant: str = "default",
        timeout: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Route one SQL query to the tenant's worker and return the
        collected batch (dict of numpy arrays, like ``collect()``)."""
        wid = self.pick(tenant)
        _count_route(wid)
        worker = self._workers[wid]
        if isinstance(worker, str):
            return self._http_query(worker, sql, tenant, timeout)
        return worker.query(sql, timeout=timeout, tenant=tenant)

    @staticmethod
    def _http_query(
        base: str, sql: str, tenant: str, timeout: Optional[float]
    ) -> Dict[str, Any]:
        import numpy as np

        params = {"sql": sql, "tenant": tenant}
        if timeout is not None:
            params["timeoutMs"] = str(int(timeout * 1000))
        url = f"{base}/query?{urllib.parse.urlencode(params)}"
        http_timeout = 300.0 if timeout is None else timeout + 5.0
        try:
            with urllib.request.urlopen(url, timeout=http_timeout) as resp:
                body = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            # the endpoint replies with a typed JSON error body on 4xx/5xx;
            # surface it instead of the bare transport error
            try:
                body = json.loads(exc.read().decode("utf-8"))
            except Exception:
                raise RuntimeError(f"worker {base} failed: HTTP {exc.code}") from exc
        if "error" in body:
            raise RuntimeError(f"worker {base} failed: {body['error']}")
        return {k: np.asarray(v) for k, v in body["columns"].items()}

    # -- aggregation ---------------------------------------------------------
    def metrics_text(self) -> str:
        """One merged Prometheus exposition over every worker."""
        texts = []
        for worker in self._workers.values():
            if isinstance(worker, str):
                with urllib.request.urlopen(f"{worker}/metrics", timeout=30) as resp:
                    texts.append(resp.read().decode("utf-8"))
            else:
                texts.append(worker.prometheus_text())
        return merge_prometheus_texts(texts)

    def statusz(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for wid, worker in self._workers.items():
            if isinstance(worker, str):
                with urllib.request.urlopen(f"{worker}/statusz", timeout=30) as resp:
                    out[wid] = json.loads(resp.read().decode("utf-8"))
            else:
                out[wid] = worker.statusz()
        return out


class WorkerEndpoint:
    """Expose one QueryServer to FrontDoors in other processes over stdlib
    HTTP. Read-mostly by design: ``/query`` executes through the server's
    normal admission path (deadline and tenant forwarded), everything else
    is a snapshot. ``port=0`` binds an ephemeral port (read ``.port``)."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self.server = server
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            daemon_threads = True

            def log_message(self, fmt, *args):  # no stderr chatter per request
                pass

            def do_GET(self):
                try:
                    endpoint._handle(self)
                except BrokenPipeError:
                    pass
                except Exception as exc:  # defensive: never kill the accept loop
                    try:
                        self.send_error(500, explain=str(exc))
                    except Exception:
                        pass

            do_POST = do_GET

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "WorkerEndpoint":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"hs-fabric-worker-{self.port}",
                daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "WorkerEndpoint":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request handling ----------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urllib.parse.urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/query":
            self._query(req, urllib.parse.parse_qs(parsed.query))
        elif path == "/metrics":
            body = self.server.prometheus_text().encode("utf-8")
            self._reply(req, 200, "text/plain; version=0.0.4; charset=utf-8", body)
        elif path == "/statusz":
            self._reply_json(req, 200, self.server.statusz())
        elif path == "/healthz":
            self._reply_json(req, 200, {"ok": True, "server": self.server.server_name})
        else:
            self._reply_json(
                req, 404,
                {"error": "not found",
                 "endpoints": ["/query", "/metrics", "/statusz", "/healthz"]},
            )

    def _query(self, req: BaseHTTPRequestHandler, query: Dict[str, list]) -> None:
        sql = (query.get("sql") or [None])[0]
        if not sql:
            self._reply_json(req, 400, {"error": "missing sql parameter"})
            return
        tenant = (query.get("tenant") or ["default"])[0]
        timeout_ms = (query.get("timeoutMs") or [None])[0]
        timeout = None if timeout_ms is None else float(timeout_ms) / 1000.0
        try:
            batch = self.server.query(sql, timeout=timeout, tenant=tenant)
        except Exception as exc:
            self._reply_json(
                req, 503, {"error": f"{type(exc).__name__}: {exc}"}
            )
            return
        self._reply_json(
            req, 200, {"columns": {k: v.tolist() for k, v in batch.items()}}
        )

    @staticmethod
    def _reply(req: BaseHTTPRequestHandler, code: int, ctype: str, body: bytes) -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    @classmethod
    def _reply_json(cls, req: BaseHTTPRequestHandler, code: int, obj: Any) -> None:
        cls._reply(req, code, "application/json; charset=utf-8",
                   json.dumps(obj, default=str).encode("utf-8"))
