"""Node liveness for the FrontDoor: ejection, half-open probes, heartbeats.

The FrontDoor's rendezvous set was static — a dead worker kept receiving
its tenants' requests forever. :class:`HealthTracker` makes membership
react to observed health, mirroring the quarantine breaker's
closed → open → half-open shape (``reliability/degrade.py``):

- **live**: requests flow; every success resets the failure streak.
- **ejected**: ``failure_threshold`` consecutive transport/transient
  failures — or a heartbeat older than ``missed_beats`` sidecar publish
  intervals, or a ``/healthz`` commit-seq lag past ``max_commit_lag``
  (a *wedged* watcher looks alive but serves stale) — removes the worker
  from the rendezvous set; its tenants re-hash to survivors.
- **half-open**: after ``probe_interval_s`` one request is allowed
  through as a probe; success re-admits (``hs_fabric_node_readmissions_total``),
  failure re-ejects and restarts the cooldown.

Heartbeats ride the coherence sidecar: every fabric node's ledger file
carries ``updatedAt`` (and now a ``heartbeat`` payload); the FrontDoor
maps workers to node ids via ``/healthz`` and treats ledger age as beat
age, so a SIGKILLed process is detected without any new write path.

Fail-open by design: if *every* worker is ejected the tracker returns the
full set — routing to a probably-dead worker beats routing to nobody.
Clock injected for deterministic tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["HealthTracker"]

STATE_LIVE = "live"
STATE_EJECTED = "ejected"
STATE_HALF_OPEN = "half-open"


def _count_ejection(worker: str, reason: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_node_ejections_total",
        "workers ejected from the FrontDoor rendezvous set, by reason "
        "(errors | missed-beats | stale | probe-failed)",
        worker=worker,
        reason=reason,
    ).inc()


def _count_readmission(worker: str) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_node_readmissions_total",
        "ejected workers re-admitted after a successful half-open probe",
        worker=worker,
    ).inc()


def _gauge_live(n: int) -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.gauge(
        "hs_fabric_node_live",
        "workers currently in the FrontDoor's live rendezvous set",
    ).set(float(n))


class _Node:
    __slots__ = ("state", "failures", "ejected_at", "last_beat", "probing")

    def __init__(self):
        self.state = STATE_LIVE
        self.failures = 0
        self.ejected_at = 0.0
        self.last_beat: Optional[float] = None
        self.probing = False


class HealthTracker:
    """Per-worker breaker state for FrontDoor membership (module docstring)."""

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        probe_interval_s: float = 5.0,
        heartbeat_interval_s: float = 1.0,
        missed_beats: int = 3,
        max_commit_lag: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.probe_interval_s = float(probe_interval_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.missed_beats = max(1, int(missed_beats))
        self.max_commit_lag = int(max_commit_lag)  # 0 disables stale ejection
        self._clock = clock
        self._lock = threading.Lock()
        self._nodes: Dict[str, _Node] = {}

    def _node(self, worker: str) -> _Node:
        node = self._nodes.get(worker)
        if node is None:
            node = self._nodes[worker] = _Node()
        return node

    # -- observations --------------------------------------------------------
    def note_ok(self, worker: str) -> None:
        with self._lock:
            node = self._node(worker)
            was = node.state
            node.failures = 0
            node.probing = False
            node.state = STATE_LIVE
        if was != STATE_LIVE:
            _count_readmission(worker)

    def note_failure(self, worker: str, reason: str = "errors") -> None:
        eject, why = False, reason
        with self._lock:
            node = self._node(worker)
            node.failures += 1
            if node.state == STATE_HALF_OPEN or node.probing:
                # the probe itself failed: back to ejected, cooldown restarts
                eject, why = True, "probe-failed"
            elif node.state == STATE_LIVE and node.failures >= self.failure_threshold:
                eject = True
            if eject:
                node.state = STATE_EJECTED
                node.probing = False
                node.ejected_at = self._clock()
        if eject:
            _count_ejection(worker, why)

    def note_beat(self, worker: str, age_s: float) -> None:
        """Observe a sidecar-heartbeat age (seconds since the node's ledger
        was written). A fresh beat re-admits a beats-ejected worker
        directly — the process provably lives — while an overdue one ejects."""
        overdue = age_s > self.heartbeat_interval_s * self.missed_beats
        with self._lock:
            node = self._node(worker)
            node.last_beat = self._clock() - age_s
            if overdue and node.state == STATE_LIVE:
                node.state = STATE_EJECTED
                node.ejected_at = self._clock()
                eject = True
                readmit = False
            elif not overdue and node.state != STATE_LIVE and not node.probing:
                node.state = STATE_LIVE
                node.failures = 0
                eject = False
                readmit = True
            else:
                eject = readmit = False
        if eject:
            _count_ejection(worker, "missed-beats")
        if readmit:
            _count_readmission(worker)

    def note_stale(self, worker: str, lag: int) -> None:
        """Observe a worker's last-applied commit_seq lag behind the fleet
        max. Past ``max_commit_lag`` the worker is serving stale answers —
        alive but wedged — and is ejected like a dead one."""
        if self.max_commit_lag <= 0 or lag <= self.max_commit_lag:
            return
        with self._lock:
            node = self._node(worker)
            if node.state != STATE_LIVE:
                return
            node.state = STATE_EJECTED
            node.ejected_at = self._clock()
        _count_ejection(worker, "stale")

    # -- membership ----------------------------------------------------------
    def state_of(self, worker: str) -> str:
        with self._lock:
            node = self._nodes.get(worker)
            return node.state if node else STATE_LIVE

    def live(self, workers: Sequence[str]) -> List[str]:
        """The rendezvous-eligible subset: live workers plus ejected ones
        whose probe cooldown elapsed (admitted half-open, one at a time).
        Empty never happens: with everyone ejected, everyone is returned
        (fail open) — a guess beats a guaranteed refusal."""
        now = self._clock()
        out: List[str] = []
        with self._lock:
            for w in workers:
                node = self._nodes.get(w)
                if node is None or node.state == STATE_LIVE:
                    out.append(w)
                elif now - node.ejected_at >= self.probe_interval_s:
                    node.state = STATE_HALF_OPEN
                    node.probing = True
                    out.append(w)
        if not out:
            out = list(workers)
        _gauge_live(len(out))
        return out
