"""Fabric log recovery and compaction: ``python -m hyperspace_tpu.fsck``.

The fabric's lake state only ever grows: every published commit leaves a
``_commits/`` record, every lease takeover leaves a superseded token
file, and every node that ever joined leaves a ``_fabric/nodes/`` ledger.
:func:`fsck` is the startup/periodic garbage collector that walks one
lake and removes, per kind:

``torn-record``
    ``_commits/`` entries whose bytes don't parse — impossible under the
    rename protocol, possible under lake-level corruption. Readers
    already skip them; fsck removes them so they stop being re-skipped
    every poll.
``old-record``
    parseable commit records older than the retention horizon
    (``hyperspace.fabric.fsck.retentionSeconds``). The **newest record of
    every index is always kept** regardless of age: record numbering
    derives from the directory listing (max+1), so compacting the whole
    directory would restart ids at 0 *behind* live ``CommitWatcher``
    cursors and new commits would replay nowhere. Keeping the high-water
    record keeps every cursor — live or stale — monotonic.
``stale-claim``
    lease token files below the current (highest) token: history of
    settled takeover races, never read again.
``expired-lease``
    a current lease token whose expiry is a full retention horizon in the
    past — nobody is coming back for it, so the whole lease directory
    (token sequence included) resets.
``dead-node``
    sidecar ledgers not rewritten for ``hyperspace.fabric.fsck.deadNodeSeconds``.
    Safe because sidecar merges are delta-based: if the node does return,
    its restarted ledger contributes nothing until it grows again.

Every removal passes the ``record.compact`` fault-injection seam; an
injected (or real) failure skips that file and the pass continues —
fsck must never wedge on the lake state it exists to clean. Removals land
in ``hs_fabric_fsck_removed_total{kind}``, passes in
``hs_fabric_fsck_runs_total``. ``dry_run`` reports without deleting.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Optional

from hyperspace_tpu import config as C
from hyperspace_tpu.fabric import lease as lease_mod
from hyperspace_tpu.fabric.records import COMMITS_DIR, nodes_dir

__all__ = ["fsck", "main"]

KINDS = ("torn-record", "old-record", "stale-claim", "expired-lease", "dead-node")


def _count_run() -> None:
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_fsck_runs_total",
        "fabric fsck passes completed",
    ).inc()


def _count_removed(kind: str, n: int = 1) -> None:
    if n <= 0:
        return
    from hyperspace_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "hs_fabric_fsck_removed_total",
        "lake files garbage-collected by fabric fsck, by kind",
        kind=kind,
    ).inc(n)


class _Pass:
    """One fsck pass's bookkeeping + guarded removal."""

    def __init__(self, dry_run: bool):
        self.dry_run = dry_run
        self.removed: Dict[str, int] = {k: 0 for k in KINDS}
        self.scanned = 0
        self.skipped = 0

    def remove(self, path: str, kind: str) -> bool:
        from hyperspace_tpu.reliability.faults import FAULTS

        try:
            if FAULTS.active:
                FAULTS.check("record.compact", path)
            if not self.dry_run:
                os.remove(path)
        except OSError:
            self.skipped += 1
            return False
        self.removed[kind] += 1
        if not self.dry_run:
            _count_removed(kind)
        return True


def fsck(
    system_path: str,
    *,
    retention_s: float = 3600.0,
    dead_node_s: float = 600.0,
    dry_run: bool = False,
    clock: Callable[[], float] = time.time,
) -> dict:
    """One garbage-collection pass over ``system_path`` (module docstring).
    Returns the report dict the CLI prints as JSON."""
    now = clock()
    p = _Pass(dry_run)
    _fsck_commit_records(p, system_path, now - retention_s)
    _fsck_leases(p, system_path, now, retention_s)
    _fsck_nodes(p, system_path, now - dead_node_s)
    _count_run()
    return {
        "systemPath": str(system_path),
        "dryRun": bool(dry_run),
        "scanned": p.scanned,
        "skipped": p.skipped,
        "removed": p.removed,
        "removedTotal": sum(p.removed.values()),
    }


def _fsck_commit_records(p: _Pass, system_path: str, horizon: float) -> None:
    try:
        index_names = sorted(os.listdir(str(system_path)))
    except OSError:
        return
    for name in index_names:
        if name.startswith((".", "_")):
            continue
        d = os.path.join(str(system_path), name, C.HYPERSPACE_LOG_DIR, COMMITS_DIR)
        try:
            rids = sorted(int(n) for n in os.listdir(d) if n.isdigit())
        except OSError:
            continue
        if not rids:
            continue
        # the high-water record anchors id monotonicity for every cursor
        for rid in rids[:-1]:
            path = os.path.join(d, f"{rid:010d}")
            p.scanned += 1
            try:
                with open(path, "rb") as f:
                    rec = json.loads(f.read().decode("utf-8"))
            except OSError:
                p.skipped += 1
                continue
            except Exception:
                p.remove(path, "torn-record")
                continue
            if float(rec.get("ts", 0.0)) < horizon:
                p.remove(path, "old-record")


def _fsck_leases(
    p: _Pass, system_path: str, now: float, retention_s: float
) -> None:
    root = lease_mod.leases_dir(str(system_path))
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return
    for name in names:
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            continue
        tokens = lease_mod._list_tokens(d)
        if not tokens:
            continue
        for token in tokens[:-1]:
            p.scanned += 1
            p.remove(lease_mod._token_path(d, token), "stale-claim")
        current = tokens[-1]
        path = lease_mod._token_path(d, current)
        p.scanned += 1
        try:
            with open(path, "rb") as f:
                state = json.loads(f.read().decode("utf-8"))
            expires_at = float(state.get("expiresAt", 0.0))
        except OSError:
            p.skipped += 1
            continue
        except Exception:
            expires_at = 0.0  # torn current token reads as long-expired
        if expires_at < now - retention_s:
            if p.remove(path, "expired-lease") and not p.dry_run:
                try:
                    os.rmdir(d)  # resets the token sequence with no live racers
                except OSError:
                    pass


def _fsck_nodes(p: _Pass, system_path: str, horizon: float) -> None:
    d = nodes_dir(str(system_path))
    try:
        names = sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError:
        return
    for name in names:
        path = os.path.join(d, name)
        p.scanned += 1
        try:
            with open(path, "rb") as f:
                state = json.loads(f.read().decode("utf-8"))
            updated = float(state.get("updatedAt", 0.0))
        except OSError:
            p.skipped += 1
            continue
        except Exception:
            updated = 0.0  # an unparseable ledger is as dead as an old one
        if updated < horizon:
            p.remove(path, "dead-node")


def main(argv: Optional[list] = None) -> int:
    """CLI entry point: ``python -m hyperspace_tpu.fsck <system-path>``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.fsck",
        description="Garbage-collect fabric lake state: torn/old commit "
        "records, superseded lease tokens, expired leases, dead-node ledgers.",
    )
    ap.add_argument("system_path", help="the lake root (hyperspace.system.path)")
    ap.add_argument(
        "--retention-seconds", type=float, default=3600.0,
        help="commit records older than this are compacted (default 3600)",
    )
    ap.add_argument(
        "--dead-node-seconds", type=float, default=600.0,
        help="node ledgers silent longer than this are removed (default 600)",
    )
    ap.add_argument(
        "--dry-run", action="store_true",
        help="report what would be removed without removing anything",
    )
    args = ap.parse_args(argv)
    report = fsck(
        args.system_path,
        retention_s=args.retention_seconds,
        dead_node_s=args.dead_node_seconds,
        dry_run=args.dry_run,
    )
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0
