"""Scale-out serving fabric: multi-process serving over one lake.

The operation log is the coherence transport (docs/scale-out.md):

- every committed mutation persists a **commit record** beside the log
  entry it describes (``lifecycle/invalidation.py`` writes it inside
  ``publish``), stamped with the publisher's node id and Lamport commit
  sequence;
- a :class:`CommitWatcher` in every process tails those records and
  replays remote commits onto the local invalidation bus — brand rotation,
  roster-TTL clears, and targeted byte-cache purges fire everywhere within
  one poll interval;
- a :class:`CoherenceSidecar` shares the state invalidation can't carry:
  quarantine strikes and per-tenant SLO / token-bucket accounting;
- a :class:`FrontDoor` spreads tenants across worker processes with
  rendezvous hashing and aggregates their ``/metrics``.

Everything is behind ``hyperspace.fabric.*``, all default-off: at defaults
``configure`` returns None without touching the lake, and single-process
behavior is byte-identical to a build without this package.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional

from hyperspace_tpu.fabric.coherence import CoherenceSidecar
from hyperspace_tpu.fabric.frontdoor import (
    FrontDoor,
    WorkerEndpoint,
    WorkerError,
    WorkerUnavailable,
    merge_prometheus_texts,
    rendezvous_order,
    rendezvous_pick,
)
from hyperspace_tpu.fabric.health import HealthTracker
from hyperspace_tpu.fabric.lease import Lease, LeaseLostError, fence_scope
from hyperspace_tpu.fabric.lease import acquire as acquire_lease
from hyperspace_tpu.fabric.records import local_node_id
from hyperspace_tpu.fabric.watcher import CommitWatcher

__all__ = [
    "CommitWatcher",
    "CoherenceSidecar",
    "FabricRuntime",
    "FrontDoor",
    "HealthTracker",
    "Lease",
    "LeaseLostError",
    "WorkerEndpoint",
    "WorkerError",
    "WorkerUnavailable",
    "acquire_lease",
    "configure",
    "fence_scope",
    "local_node_id",
    "merge_prometheus_texts",
    "rendezvous_order",
    "rendezvous_pick",
]


class FabricRuntime:
    """One session's fabric wiring: node identity + watcher + sidecar.

    Constructed (and its threads started) by :func:`configure` when
    ``hyperspace.fabric.enabled`` is on. ``attach_server``/``detach_server``
    are called from ``QueryServer.start``/``shutdown`` so the sidecar always
    accounts against the live serving stack, and a bus subscription merges
    remote quarantine *trips* the instant their commit records replay —
    strike-level sharing rides the slower sidecar loop.
    """

    def __init__(self, session, autostart: bool = True):
        conf = session.conf
        self._session_ref = weakref.ref(session)
        self.node_id = local_node_id(conf)
        self.watcher = CommitWatcher(session, node_id=self.node_id)
        self.sidecar = CoherenceSidecar(session, node_id=self.node_id)
        self.share_quarantine = bool(conf.fabric_quarantine_shared)
        self._fsck_thread: Optional[threading.Thread] = None
        self._fsck_stop = threading.Event()
        session.lifecycle_bus.subscribe(self._on_commit)
        if autostart:
            if conf.fabric_watcher_enabled:
                self.watcher.start()
            # health-aware FrontDoors read the node files' updatedAt as the
            # fleet heartbeat, so the sidecar also runs for health alone
            if (
                self.share_quarantine
                or conf.fabric_slo_shared
                or conf.fabric_health_enabled
            ):
                self.sidecar.start()
            if conf.fabric_fsck_enabled and conf.system_path:
                self.fsck_once()
                self._start_fsck_loop(conf.fabric_fsck_interval_seconds)

    # -- lake garbage collection ---------------------------------------------
    def fsck_once(self) -> Optional[dict]:
        """One fsck pass over this session's lake (fabric/fsck.py); a
        failing pass is swallowed — GC must never take down serving."""
        session = self._session_ref()
        if session is None:
            return None
        conf = session.conf
        from hyperspace_tpu.fabric.fsck import fsck

        try:
            return fsck(
                conf.system_path,
                retention_s=conf.fabric_fsck_retention_seconds,
                dead_node_s=conf.fabric_fsck_dead_node_seconds,
            )
        except Exception:
            return None

    def _start_fsck_loop(self, interval_s: float) -> None:
        if self._fsck_thread is not None:
            return
        self._fsck_stop.clear()

        def _run() -> None:
            while not self._fsck_stop.wait(interval_s):
                if self._session_ref() is None:
                    return
                self.fsck_once()

        self._fsck_thread = threading.Thread(
            target=_run, name="hs-fabric-fsck", daemon=True
        )
        self._fsck_thread.start()

    # -- serving attachment --------------------------------------------------
    def attach_server(self, server) -> None:
        self.sidecar.attach_server(server)

    def detach_server(self, server) -> None:
        self.sidecar.detach_server(server)

    # -- remote trip propagation ---------------------------------------------
    def _on_commit(self, event) -> None:
        if not self.share_quarantine or event.kind != "quarantine":
            return
        origin = getattr(event, "origin", None)
        if origin is None or origin == self.node_id:
            return  # local trip: the registry already opened the breaker
        from hyperspace_tpu.reliability.degrade import QUARANTINE

        if QUARANTINE.merge_remote_trip(event.index_name):
            from hyperspace_tpu.obs.metrics import REGISTRY

            REGISTRY.counter(
                "hs_fabric_quarantine_merged_total",
                "quarantine trips caused or propagated by remote strikes",
                index=event.index_name,
            ).inc()

    def stop(self) -> None:
        self.watcher.stop()
        self.sidecar.stop()
        self._fsck_stop.set()
        if self._fsck_thread is not None:
            self._fsck_thread.join(timeout=5)
            self._fsck_thread = None
        session = self._session_ref()
        if session is not None:
            session.lifecycle_bus.unsubscribe(self._on_commit)


def configure(session) -> Optional[FabricRuntime]:
    """Session wiring hook (mirrors ``reliability.configure``): a no-op
    returning None while ``hyperspace.fabric.enabled`` is off."""
    if not session.conf.fabric_enabled:
        return None
    return FabricRuntime(session)
