"""Absolute-performance framing: measure the bounds, then place the
framework's headline numbers against them (VERDICT r4 item 5).

Ratios against a host baseline say nothing about whether the chip is busy
or starved; this probe measures the two bounds that govern every number
this framework publishes through a tunneled chip:

- host<->device link bandwidth (device_put up / np.asarray down, 64 MiB
  int64 arrays, best of N) — the ceiling for build key upload + perm
  download and for any device-join transfer;
- device sort throughput on the build kernel's own shapes (keys already
  resident: the pure-compute bound of the build's device stage);
- host parquet decode throughput (pyarrow + native path on index-dialect
  files) — the build pipeline's host-side bound.

Prints ONE JSON line with the measured bounds plus derived
fraction-of-bound figures for a given build rate (BENCH_BUILD_RATE env,
rows/s, e.g. the latest bench.py headline).

Run on the chip with nothing else holding the tunnel:
    python benchmarks/roofline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import bench

    bench._honor_cpu_request()
    bench._backend_watchdog(
        emit=lambda reason: print(json.dumps({"error": reason}), flush=True)
    )
    import jax

    dev = jax.devices()[0]
    out = {"device": str(dev)}

    # --- link bandwidth, 64 MiB payloads, best of 5 ------------------------
    nbytes = 64 << 20
    arr = np.random.default_rng(0).integers(0, 1 << 62, nbytes // 8, dtype=np.int64)
    ups, downs = [], []
    d = jax.device_put(arr)  # warm path + allocator
    d.block_until_ready()
    for _ in range(5):
        t0 = time.perf_counter()
        d = jax.device_put(arr)
        d.block_until_ready()
        ups.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _ = np.asarray(d)
        downs.append(time.perf_counter() - t0)
    out["h2d_gbps"] = round(nbytes / min(ups) / 1e9, 3)
    out["d2h_gbps"] = round(nbytes / min(downs) / 1e9, 3)

    # --- device build-kernel compute bound (keys resident, no transfers) ---
    from hyperspace_tpu.ops.sort import bucket_sort_build, padded_size

    n = 2_000_000  # one default build chunk
    rng = np.random.default_rng(1)
    np2 = padded_size(n)
    keys = [jax.device_put(np.pad(rng.integers(0, 10**9, n), (0, np2 - n)))]
    # int builds reconstruct their hash plane ON device (_device_hash32) —
    # host_hashes is only consumed for string columns
    perm, counts = bucket_sort_build(keys, (), ("i",), 64, n)  # compile
    perm.block_until_ready()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        perm, counts = bucket_sort_build(keys, (), ("i",), 64, n)
        perm.block_until_ready()
        times.append(time.perf_counter() - t0)
    out["device_sort_rows_per_s"] = round(n / min(times), 1)

    # --- host parquet decode bound (the build's other pipeline stage) ------
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.exec.io import read_parquet_batch

    with tempfile.TemporaryDirectory(prefix="hs_roofline_") as td:
        path = os.path.join(td, "f.parquet")
        t = pa.table({
            "k": rng.integers(0, 10**9, 4_000_000).astype(np.int64),
            "a": rng.uniform(0, 1, 4_000_000),
            "b": rng.uniform(0, 1, 4_000_000),
            "c": rng.uniform(0, 1, 4_000_000),
        })
        pq.write_table(t, path, use_dictionary=False, compression="NONE")
        file_bytes = os.stat(path).st_size
        read_parquet_batch([path], None)  # warm (native mmap path)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            read_parquet_batch([path], None)
            times.append(time.perf_counter() - t0)
        out["host_decode_gbps"] = round(file_bytes / min(times) / 1e9, 3)

    # --- place a build rate against the bounds -----------------------------
    # per-row traffic of the default build (single int64 key index):
    #   up: 8 B sort key + 4 B hash plane (uint32) per row (padded ~+6%)
    #   down: 4 B perm + negligible counts
    rate = float(os.environ.get("BENCH_BUILD_RATE", 0) or 0)
    if rate > 0:
        up_bps = rate * 12 * 1.06
        down_bps = rate * 4
        out["build_rate_rows_per_s"] = rate
        out["link_utilization_up"] = round(up_bps / (out["h2d_gbps"] * 1e9), 4)
        out["link_utilization_down"] = round(down_bps / (out["d2h_gbps"] * 1e9), 4)
        out["device_sort_utilization"] = round(rate / out["device_sort_rows_per_s"], 4)
        # end-to-end build moves ~32 B/row of parquet on each side of the
        # device stage (decode in, bucket write out)
        out["host_decode_utilization"] = round(
            (rate * 32) / (out["host_decode_gbps"] * 1e9), 4
        )
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
