"""Benchmark runner for the five BASELINE.md configs.

Usage:
    python benchmarks/run.py [config1|config2|config3|config4|config5|all] [--sf 0.1]

Each config prints one JSON line:
    {"config": N, "metric": ..., "value": ..., "unit": ..., "speedup_vs_noindex": ...}

Methodology: every query is executed once to warm jit compiles and OS caches,
then timed over ``--reps`` repetitions (median). The no-index baseline is the
same query with hyperspace disabled in the same process (the Spark-CPU
baseline of BASELINE.md must be measured on a Spark cluster; the speedups
reported here are vs this framework's own non-indexed execution path).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks import datagen  # noqa: E402


def _session(root, num_buckets=64):
    import hyperspace_tpu as hst

    sysd = os.path.join(root, "_indexes")
    os.makedirs(sysd, exist_ok=True)
    sess = hst.Session(
        conf={
            hst.keys.SYSTEM_PATH: sysd,
            hst.keys.NUM_BUCKETS: num_buckets,
            # equality/IN filters on the indexed column read only their hash
            # bucket's files (same knob as the reference's useBucketSpec)
            hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True,
        }
    )
    hst.set_session(sess)
    return sess, hst.Hyperspace(sess), hst


def _time_query(q, reps: int):
    """(median, IQR) seconds over ``reps`` timed runs after one warm run.
    IQR (p75-p25) is reported alongside the median so run-to-run ambient
    variance on shared machines is visible in every published number."""
    q.collect()  # warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        q.collect()
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    if len(times) >= 4:
        qs = statistics.quantiles(times, n=4)
        iqr = qs[2] - qs[0]
    else:
        iqr = max(times) - min(times)
    return med, iqr


def _ab(sess, q, reps: int):
    """((indexed_median, iqr), (plain_median, iqr)) in the same process."""
    sess.enable_hyperspace()
    ti = _time_query(q, reps)
    sess.disable_hyperspace()
    tp = _time_query(q, reps)
    sess.enable_hyperspace()
    return ti, tp


def _emit(config: int, metric: str, ti, tp, extra=None):
    """One JSON line per config: indexed median (ms) ± IQR, plain median,
    speedup, and the 1-minute loadavg for cross-run comparability."""
    (med_i, iqr_i), (med_p, iqr_p) = ti, tp
    row = {
        "config": config,
        "metric": metric,
        "value": round(med_i * 1000, 4),
        "unit": "ms",
        "speedup_vs_noindex": round(med_p / med_i, 3),
        "iqr_ms": round(iqr_i * 1000, 4),
        "noindex_ms": round(med_p * 1000, 4),
        "noindex_iqr_ms": round(iqr_p * 1000, 4),
        "loadavg_1m": round(os.getloadavg()[0], 2),
    }
    if extra:
        row.update(extra)
    print(json.dumps(row), flush=True)


def config1(root, args):
    """Covering index on sample data; single filter query (BASELINE config 1)."""
    data = datagen.gen_sample(root)
    sess, hs, hst = _session(root, num_buckets=16)
    df = sess.read_parquet(data)
    hs.create_index(df, hst.CoveringIndexConfig("sample_idx", ["dept"], ["value", "name"]))
    q = df.filter(hst.col("dept") == 7).select("value", "name")
    ti, tp = _ab(sess, q, args.reps)
    _emit(1, "sample_filter_query_latency", ti, tp)


def config2(root, args):
    """TPC-H lineitem covering index on l_shipdate; FilterIndexRule (config 2)."""
    data = datagen.gen_lineitem(root, args.sf)
    sess, hs, hst = _session(root)
    df = sess.read_parquet(data)
    t0 = time.perf_counter()
    hs.create_index(
        df,
        hst.CoveringIndexConfig(
            "li_shipdate", ["l_shipdate"], ["l_orderkey", "l_extendedprice", "l_discount"]
        ),
    )
    build_s = time.perf_counter() - t0
    day = np.datetime64("1995-06-15")
    q = df.filter(hst.col("l_shipdate") == day).select("l_orderkey", "l_extendedprice")
    ti, tp = _ab(sess, q, args.reps)
    n = int(datagen.LINEITEM_ROWS_SF1 * args.sf)
    _emit(2, "tpch_shipdate_filter_latency", ti, tp,
          {"sf": args.sf, "build_rows_per_s": round(n / build_s, 1)})


def config3(root, args):
    """lineitem JOIN orders shuffle-free bucketed SMJ via JoinIndexRule (config 3)."""
    li_d = datagen.gen_lineitem(root, args.sf)
    o_d = datagen.gen_orders(root, args.sf)
    sess, hs, hst = _session(root)
    li = sess.read_parquet(li_d)
    o = sess.read_parquet(o_d)
    hs.create_index(
        li, hst.CoveringIndexConfig("li_ok", ["l_orderkey"], ["l_extendedprice", "l_discount"])
    )
    hs.create_index(o, hst.CoveringIndexConfig("o_ok", ["o_orderkey"], ["o_totalprice"]))
    q = li.join(o, on=hst.col("l_orderkey") == hst.col("o_orderkey")).select(
        "l_extendedprice", "o_totalprice"
    )
    ti, tp = _ab(sess, q, args.reps)
    _emit(3, "tpch_indexed_join_latency", ti, tp, {"sf": args.sf})


def config4(root, args):
    """Multi-way join + hybrid scan over appended files (config 4)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    li_d = datagen.gen_lineitem(root, args.sf)
    o_d = datagen.gen_orders(root, args.sf)
    sess, hs, hst = _session(root)
    li = sess.read_parquet(li_d)
    o = sess.read_parquet(o_d)
    hs.create_index(
        li, hst.CoveringIndexConfig("li_ok4", ["l_orderkey"], ["l_extendedprice"])
    )
    hs.create_index(o, hst.CoveringIndexConfig("o_ok4", ["o_orderkey"], ["o_totalprice"]))
    # append ~5% new lineitem rows AFTER indexing -> hybrid scan path
    rng = np.random.default_rng(99)
    n_app = max(1000, int(datagen.LINEITEM_ROWS_SF1 * args.sf * 0.05))
    base = np.datetime64("1992-01-01")
    t = pa.table(
        {
            "l_orderkey": rng.integers(0, int(datagen.ORDERS_ROWS_SF1 * args.sf), n_app).astype(np.int64),
            "l_partkey": rng.integers(0, 200_000, n_app).astype(np.int64),
            "l_quantity": rng.integers(1, 51, n_app).astype(np.int64),
            "l_extendedprice": np.round(rng.uniform(900.0, 105000.0, n_app), 2),
            "l_discount": np.round(rng.uniform(0.0, 0.1, n_app), 2),
            "l_tax": np.round(rng.uniform(0.0, 0.08, n_app), 2),
            "l_shipdate": base + rng.integers(0, 2526, n_app).astype("timedelta64[D]"),
        }
    )
    pq.write_table(t, os.path.join(li_d, "part-appended.parquet"))
    sess.conf.set(hst.keys.HYBRID_SCAN_ENABLED, True)
    li2 = sess.read_parquet(li_d)
    q = li2.join(o, on=hst.col("l_orderkey") == hst.col("o_orderkey")).select(
        "l_extendedprice", "o_totalprice"
    )
    ti, tp = _ab(sess, q, args.reps)
    _emit(4, "hybrid_scan_join_latency", ti, tp, {"sf": args.sf, "appended_rows": n_app})


def config5(root, args):
    """Delta source + incremental refresh + data-skipping index (config 5)."""
    import pyarrow as pa

    from hyperspace_tpu.sources.delta import write_delta_table

    sess, hs, hst = _session(root)
    rng = np.random.default_rng(5)
    n = max(10_000, int(1_000_000 * args.sf))
    d = os.path.join(root, "delta_li")

    def batch(seed):
        r = np.random.default_rng(seed)
        return pa.table(
            {
                "k": r.integers(0, 1_000_000, n // 2).astype(np.int64),
                "price": np.round(r.uniform(0, 1000, n // 2), 2),
            }
        )

    write_delta_table(batch(0), d)
    df = sess.read_delta(d)
    hs.create_index(df, hst.CoveringIndexConfig("delta_ci", ["k"], ["price"]))
    hs.create_index(
        df,
        hst.DataSkippingIndexConfig(
            "delta_ds", hst.MinMaxSketch("k"), hst.BloomFilterSketch("k", expected_items=n)
        ),
    )
    # new delta version, then incremental refresh
    write_delta_table(batch(1), d)
    t0 = time.perf_counter()
    hs.refresh_index("delta_ci", "incremental")
    hs.refresh_index("delta_ds", "incremental")
    refresh_s = time.perf_counter() - t0
    df2 = sess.read_delta(d)
    probe = int(np.asarray(batch(1)["k"])[0])
    q = df2.filter(hst.col("k") == probe).select("price")
    ti, tp = _ab(sess, q, args.reps)
    _emit(5, "delta_incremental_plus_skipping_latency", ti, tp,
          {"sf": args.sf, "incremental_refresh_s": round(refresh_s, 3)})


def config6(root, args):
    """String-payload-heavy indexed join (round-3 VERDICT item 7): orders
    joined to customer carrying c_name/c_address/c_mktsegment as included
    columns. Device materialization gathers numeric columns on device but
    string columns host-side by downloaded index arrays (exec/device.py);
    this config measures that cost so the decision to (not) dictionary-code
    device string gathers is recorded with a number."""
    o_d = datagen.gen_orders(root, args.sf)
    c_d = datagen.gen_customer(root, args.sf)
    sess, hs, hst = _session(root)
    o = sess.read_parquet(o_d)
    c = sess.read_parquet(c_d)
    hs.create_index(
        o, hst.CoveringIndexConfig("o_ck6", ["o_custkey"], ["o_totalprice"])
    )
    hs.create_index(
        c,
        hst.CoveringIndexConfig(
            "c_ck6", ["c_custkey"], ["c_name", "c_address", "c_mktsegment", "c_acctbal"]
        ),
    )
    q = o.join(c, on=hst.col("o_custkey") == hst.col("c_custkey")).select(
        "o_totalprice", "c_name", "c_address", "c_mktsegment"
    )
    ti, tp = _ab(sess, q, args.reps)
    # numeric-only variant of the same join sizes the string-gather delta
    qn = o.join(c, on=hst.col("o_custkey") == hst.col("c_custkey")).select(
        "o_totalprice", "c_acctbal"
    )
    tin, _ = _ab(sess, qn, args.reps)
    _emit(6, "string_payload_join_latency", ti, tp,
          {"sf": args.sf, "numeric_only_ms": round(tin[0] * 1000, 4),
           "string_gather_overhead_x": round(ti[0] / max(tin[0], 1e-9), 3)})


def config7(root, args):
    """Real TPC-H q3 text through the SQL front-end with covering indexes on
    the join keys — the end-to-end SQL+optimizer+engine latency on the
    benchmark family's own query, not a synthetic shape."""
    li_d = datagen.gen_lineitem(root, args.sf)
    o_d = datagen.gen_orders(root, args.sf)
    c_d = datagen.gen_customer(root, args.sf)
    sess, hs, hst = _session(root)
    li = sess.read_parquet(li_d)
    o = sess.read_parquet(o_d)
    c = sess.read_parquet(c_d)
    hs.create_index(
        li, hst.CoveringIndexConfig("li_ok7", ["l_orderkey"], ["l_extendedprice", "l_discount", "l_shipdate"])
    )
    # the round-4 tpch22 lesson: the selective l_shipdate filter leg must be
    # covered by a filter index that also carries the downstream join key,
    # else the lineitem leg stays a raw scan (benchmarks/RESULTS.md round 4)
    hs.create_index(
        li, hst.CoveringIndexConfig("li_sd7", ["l_shipdate"], ["l_orderkey", "l_extendedprice", "l_discount"])
    )
    hs.create_index(
        o, hst.CoveringIndexConfig("o_ok7", ["o_orderkey"], ["o_custkey", "o_orderdate", "o_shippriority"])
    )
    # the customer join needs orders bucketed by o_custkey (JoinIndexRule
    # requires indexed cols == join cols on both sides)
    hs.create_index(
        o, hst.CoveringIndexConfig("o_ck7", ["o_custkey"], ["o_orderkey", "o_orderdate", "o_shippriority"])
    )
    hs.create_index(c, hst.CoveringIndexConfig("c_ck7", ["c_custkey"], ["c_mktsegment"]))
    li.create_or_replace_temp_view("lineitem")
    o.create_or_replace_temp_view("orders")
    c.create_or_replace_temp_view("customer")
    q = sess.sql("""
      select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
             o_orderdate, o_shippriority
      from customer, orders, lineitem
      where c_mktsegment = 'AUTOMOBILE'
        and c_custkey = o_custkey
        and l_orderkey = o_orderkey
        and o_orderdate < date '1995-03-15'
        and l_shipdate > date '1995-03-15'
      group by l_orderkey, o_orderdate, o_shippriority
      order by revenue desc, o_orderdate
      limit 10
    """)
    ti, tp = _ab(sess, q, args.reps)
    _emit(7, "tpch_q3_sql_latency", ti, tp, {"sf": args.sf})


CONFIGS = {"config1": config1, "config2": config2, "config3": config3,
           "config4": config4, "config5": config5, "config6": config6,
           "config7": config7}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("which", nargs="?", default="all", choices=[*CONFIGS, "all"])
    ap.add_argument("--sf", type=float, default=float(os.environ.get("BENCH_SF", 0.1)))
    ap.add_argument("--reps", type=int, default=int(os.environ.get("BENCH_REPS", 10)))
    ap.add_argument("--keep", action="store_true", help="keep generated data dir")
    args = ap.parse_args()

    # fail fast on an unreachable TPU tunnel instead of hanging in
    # jax.devices() (same watchdog as bench.py, suite-schema error line)
    import bench

    bench._honor_cpu_request()
    bench._backend_watchdog(
        emit=lambda reason: print(json.dumps({"config": None, "error": reason}), flush=True)
    )

    root = tempfile.mkdtemp(prefix="hs_bench_suite_")
    try:
        for name in ([args.which] if args.which != "all" else list(CONFIGS)):
            CONFIGS[name](os.path.join(root, name), args)
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
