"""A/B the HBM-resident join-input cache on the benchmark join configs.

Runs config3 (bucketed SMJ), config6 (string-payload join), and config7
(TPC-H q3 via SQL) twice in SEPARATE subprocesses — once with the device
cache disabled (HS_DEVICE_CACHE_BYTES=0) and once enabled — so each arm
is a fresh process with identical warmup discipline. Prints one JSON line
per (config, arm).

Usage: python benchmarks/ab_join_cache.py [--sf 0.2] [--reps 10]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.2)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--configs", default="config3,config6,config7")
    args = ap.parse_args()

    for config in args.configs.split(","):
        for arm, cache_bytes in (("nocache", "0"), ("cache", str(1 << 31))):
            env = dict(os.environ, HS_DEVICE_CACHE_BYTES=cache_bytes)
            r = subprocess.run(
                [
                    sys.executable,
                    os.path.join(HERE, "run.py"),
                    config,
                    "--sf",
                    str(args.sf),
                    "--reps",
                    str(args.reps),
                ],
                env=env,
                capture_output=True,
                text=True,
                timeout=1800,
            )
            line = next(
                (ln for ln in r.stdout.splitlines() if ln.startswith("{")), None
            )
            print(
                json.dumps(
                    {
                        "config": config,
                        "arm": arm,
                        "result": json.loads(line) if line else None,
                        "rc": r.returncode,
                        "err": None if r.returncode == 0 else r.stderr.strip()[-400:],
                    }
                ),
                flush=True,
            )


if __name__ == "__main__":
    main()
