"""Vectorized full-schema TPC-H data generation (all 8 tables), sized by
scale factor (SF 1 ~= 6M lineitem rows, official row-count scaling). Not
dbgen: value distributions follow what the 22 query texts predicate on, the
same shaping as the correctness fixture (tests/test_tpch_queries.py) but
vectorized for millions of rows.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

ROWS_SF1 = {
    "region": 5,
    "nation": 25,
    "supplier": 10_000,
    "customer": 150_000,
    "part": 200_000,
    "partsupp": 800_000,
    "orders": 1_500_000,
    "lineitem": 6_000_000,
}

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "AIR REG", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_TYPES = [
    f"{a} {b} {c}"
    for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO")
    for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
    for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")
]
_CONTAINERS = [
    f"{a} {b}"
    for a in ("SM", "MED", "LG", "JUMBO", "WRAP")
    for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM")
]
_BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
_P_NAME_WORDS = np.array(
    ["almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
     "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
     "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
     "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
     "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
     "hot", "hotpink", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
     "lemon", "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
     "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange"]
)


def _write_chunked(d: str, n: int, num_files: int, make_chunk) -> str:
    os.makedirs(d, exist_ok=True)
    per = max(1, n // num_files)
    off = 0
    i = 0
    while off < n:
        rows = min(per, n - off) if i < num_files - 1 else n - off
        t = make_chunk(off, rows)
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
        off += rows
        i += 1
    return d


def _rows(name: str, sf: float) -> int:
    if name in ("region", "nation"):
        return ROWS_SF1[name]
    return max(20, int(ROWS_SF1[name] * sf))


def _comments(rng, rows, special_frac=0.1):
    base = np.array([f"notes {i}" for i in range(97)], dtype=object)
    out = base[rng.integers(0, len(base), rows)]
    hits = rng.random(rows) < special_frac
    # q13/q16/q19-class LIKE patterns need occupants
    specials = np.array(
        ["special requests handle", "pending deposits accounts",
         "unusual packages wake", "express Customer Complaints"], dtype=object
    )
    out[hits] = specials[rng.integers(0, len(specials), int(hits.sum()))]
    return out


def gen_all(root: str, sf: float, seed: int = 7) -> dict:
    """Generate all 8 tables under ``root``; returns {table: dir}."""
    rng = np.random.default_rng(seed)
    dirs = {}
    n_cust = _rows("customer", sf)
    n_supp = _rows("supplier", sf)
    n_part = _rows("part", sf)
    n_ord = _rows("orders", sf)
    n_li = _rows("lineitem", sf)
    n_ps = _rows("partsupp", sf)
    base = np.datetime64("1992-01-01")

    # region / nation (fixed)
    dirs["region"] = _write_chunked(
        os.path.join(root, "region"), 5, 1,
        lambda off, rows: pa.table({
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(_REGIONS, dtype=object),
            "r_comment": np.array([f"region {i}" for i in range(5)], dtype=object),
        }),
    )
    nat_names = np.array([n for n, _ in _NATIONS], dtype=object)
    nat_regions = np.array([r for _, r in _NATIONS], dtype=np.int64)
    dirs["nation"] = _write_chunked(
        os.path.join(root, "nation"), 25, 1,
        lambda off, rows: pa.table({
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": nat_names,
            "n_regionkey": nat_regions,
            "n_comment": np.array([f"nation {i}" for i in range(25)], dtype=object),
        }),
    )

    def supplier_chunk(off, rows):
        k = np.arange(off, off + rows, dtype=np.int64)
        return pa.table({
            "s_suppkey": k,
            "s_name": np.array([f"Supplier#{v:09d}" for v in k], dtype=object),
            "s_address": np.array([f"{v % 9999} Dock Rd" for v in k], dtype=object),
            "s_nationkey": rng.integers(0, 25, rows).astype(np.int64),
            "s_phone": np.array([f"{13 + (v % 20)}-{v % 997:03d}-55" for v in k], dtype=object),
            "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, rows), 2),
            "s_comment": _comments(rng, rows),
        })

    dirs["supplier"] = _write_chunked(os.path.join(root, "supplier"), n_supp, 2, supplier_chunk)

    segs = np.array(_SEGMENTS, dtype=object)

    def customer_chunk(off, rows):
        k = np.arange(off, off + rows, dtype=np.int64)
        return pa.table({
            "c_custkey": k,
            "c_name": np.array([f"Customer#{v:09d}" for v in k], dtype=object),
            "c_address": np.array([f"{v % 9999} Market St" for v in k], dtype=object),
            "c_nationkey": rng.integers(0, 25, rows).astype(np.int64),
            "c_phone": np.array([f"{13 + (v % 20)}-{v % 997:03d}-55" for v in k], dtype=object),
            "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, rows), 2),
            "c_mktsegment": segs[rng.integers(0, 5, rows)],
            "c_comment": _comments(rng, rows),
        })

    dirs["customer"] = _write_chunked(os.path.join(root, "customer"), n_cust, 4, customer_chunk)

    types = np.array(_TYPES, dtype=object)
    containers = np.array(_CONTAINERS, dtype=object)
    brands = np.array(_BRANDS, dtype=object)

    def part_chunk(off, rows):
        k = np.arange(off, off + rows, dtype=np.int64)
        w1 = _P_NAME_WORDS[rng.integers(0, len(_P_NAME_WORDS), rows)]
        w2 = _P_NAME_WORDS[rng.integers(0, len(_P_NAME_WORDS), rows)]
        return pa.table({
            "p_partkey": k,
            "p_name": np.array([f"{a} {b}" for a, b in zip(w1, w2)], dtype=object),
            "p_mfgr": np.array([f"Manufacturer#{1 + (v % 5)}" for v in k], dtype=object),
            "p_brand": brands[rng.integers(0, len(brands), rows)],
            "p_type": types[rng.integers(0, len(types), rows)],
            "p_size": rng.integers(1, 51, rows).astype(np.int64),
            "p_container": containers[rng.integers(0, len(containers), rows)],
            "p_retailprice": np.round(rng.uniform(900.0, 2000.0, rows), 2),
            "p_comment": _comments(rng, rows),
        })

    dirs["part"] = _write_chunked(os.path.join(root, "part"), n_part, 4, part_chunk)

    def partsupp_chunk(off, rows):
        return pa.table({
            "ps_partkey": rng.integers(0, n_part, rows).astype(np.int64),
            "ps_suppkey": rng.integers(0, n_supp, rows).astype(np.int64),
            "ps_availqty": rng.integers(1, 10_000, rows).astype(np.int64),
            "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, rows), 2),
            "ps_comment": _comments(rng, rows),
        })

    dirs["partsupp"] = _write_chunked(os.path.join(root, "partsupp"), n_ps, 4, partsupp_chunk)

    prios = np.array(_PRIORITIES, dtype=object)
    stats = np.array(["F", "O", "P"], dtype=object)

    def orders_chunk(off, rows):
        k = np.arange(off, off + rows, dtype=np.int64)
        return pa.table({
            "o_orderkey": k,
            "o_custkey": rng.integers(0, max(1, int(n_cust * 0.85)), rows).astype(np.int64),
            "o_orderstatus": stats[rng.integers(0, 3, rows)],
            "o_totalprice": np.round(rng.uniform(800.0, 600000.0, rows), 2),
            "o_orderdate": base + rng.integers(0, 2406, rows).astype("timedelta64[D]"),
            "o_orderpriority": prios[rng.integers(0, 5, rows)],
            "o_clerk": np.array([f"Clerk#{v % 1000:09d}" for v in k], dtype=object),
            "o_shippriority": np.zeros(rows, dtype=np.int64),
            "o_comment": _comments(rng, rows),
        })

    dirs["orders"] = _write_chunked(os.path.join(root, "orders"), n_ord, 8, orders_chunk)

    modes = np.array(_SHIPMODES, dtype=object)
    instr = np.array(_INSTRUCT, dtype=object)
    flags = np.array(["A", "N", "R"], dtype=object)
    lstat = np.array(["F", "O"], dtype=object)

    def lineitem_chunk(off, rows):
        ship = base + rng.integers(366, 2526, rows).astype("timedelta64[D]")
        commit = ship + rng.integers(7, 30, rows).astype("timedelta64[D]")
        late = rng.random(rows) < 0.2
        receipt = commit + np.where(
            late, rng.integers(1, 6, rows), rng.integers(-5, 1, rows)
        ).astype("timedelta64[D]")
        okeys = rng.integers(0, n_ord, rows).astype(np.int64)
        heavy = rng.random(rows) < 0.02  # q18's heavy orders
        okeys[heavy] = rng.integers(0, max(1, n_ord // 1000), int(heavy.sum()))
        return pa.table({
            "l_orderkey": okeys,
            "l_partkey": rng.integers(0, n_part, rows).astype(np.int64),
            "l_suppkey": rng.integers(0, n_supp, rows).astype(np.int64),
            "l_linenumber": rng.integers(1, 8, rows).astype(np.int64),
            "l_quantity": rng.integers(1, 51, rows).astype(np.int64),
            "l_extendedprice": np.round(rng.uniform(900.0, 105000.0, rows), 2),
            "l_discount": np.round(rng.integers(0, 11, rows) / 100.0, 2),
            "l_tax": np.round(rng.integers(0, 9, rows) / 100.0, 2),
            "l_returnflag": flags[rng.integers(0, 3, rows)],
            "l_linestatus": lstat[rng.integers(0, 2, rows)],
            "l_shipdate": ship,
            "l_commitdate": commit,
            "l_receiptdate": receipt,
            "l_shipinstruct": instr[rng.integers(0, 4, rows)],
            "l_shipmode": modes[rng.integers(0, 8, rows)],
            "l_comment": _comments(rng, rows),
        })

    dirs["lineitem"] = _write_chunked(os.path.join(root, "lineitem"), n_li, 16, lineitem_chunk)
    return dirs
