"""TPC-H SF100 out-of-core proof (BASELINE.md:24's own scale class).

Runs the BASELINE config3 shape (lineitem JOIN orders on l_orderkey) at a
scale where nothing may materialize a full table: SF100 lineitem is 600M
rows (~34 GB raw). The round-5 streaming layer carries it end to end:

- the covering-index BUILD streams source files in ~batchRows groups
  (indexes/covering.py write) — peak RAM is O(2 chunks);
- the indexed JOIN streams bucket-by-bucket above
  ``hyperspace.exec.stream.joinMinBytes`` (exec/device.py
  stream_bucketed_join) — peak RAM is O(bucket pair + output);
- the non-indexed baseline runs the partitioned (grace) merge above
  ``hyperspace.exec.join.spillMinRows`` and streams its scans.

The reference inherits all three properties from Spark's streaming
executors (HS/index/covering/JoinIndexRule.scala:604-705 is valid at any
SF); this framework owns them explicitly, and this benchmark proves them
with numbers: peak RSS is recorded for every phase, and an optional
--rss-budget makes exceeding it a hard failure.

Usage:
    python benchmarks/sf100.py --sf 100 [--reps 1] [--rss-budget-gb 48]
        [--skip-baseline] [--agg-probe]

Prints one JSON line per phase (datagen / build / indexed query /
baseline query), each with elapsed seconds and peak RSS.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks import datagen  # noqa: E402


def peak_rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1024**2)


def emit(phase: str, seconds: float, extra=None) -> None:
    row = {
        "phase": phase,
        "seconds": round(seconds, 2),
        "peak_rss_gb": round(peak_rss_gb(), 2),
        "loadavg_1m": round(os.getloadavg()[0], 2),
    }
    if extra:
        row.update(extra)
    print(json.dumps(row), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=float(os.environ.get("BENCH_SF", 100)))
    ap.add_argument("--reps", type=int, default=1)
    ap.add_argument("--root", default=None, help="data dir (default: temp; reused if it exists)")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--skip-baseline", action="store_true")
    ap.add_argument("--skip-datagen", action="store_true", help="reuse --root's existing data")
    ap.add_argument(
        "--rss-budget-gb", type=float, default=None,
        help="fail the run if peak RSS exceeds this (the bounded-memory proof)",
    )
    ap.add_argument(
        "--agg-probe", action="store_true",
        help="also run a streamed full-scan aggregate (partial-agg merge proof)",
    )
    args = ap.parse_args()

    import bench

    bench._honor_cpu_request()
    bench._backend_watchdog(
        emit=lambda reason: print(json.dumps({"phase": "backend", "error": reason}), flush=True)
    )

    root = args.root or tempfile.mkdtemp(prefix="hs_sf100_")
    os.makedirs(root, exist_ok=True)
    n_li = int(datagen.LINEITEM_ROWS_SF1 * args.sf)

    # --- datagen (file count scales so each file stays ~8M rows: the
    # streaming build's decode bound is one file group) ---------------------
    t0 = time.perf_counter()
    li_files = max(16, int(np.ceil(n_li / 8_000_000)))
    o_files = max(8, li_files // 4)
    if args.skip_datagen and os.path.isdir(os.path.join(root, "lineitem")):
        li_d = os.path.join(root, "lineitem")
        o_d = os.path.join(root, "orders")
        emit("datagen", 0.0, {"sf": args.sf, "rows": n_li, "reused": True})
    else:
        li_d = datagen.gen_lineitem(root, args.sf, num_files=li_files)
        o_d = datagen.gen_orders(root, args.sf, num_files=o_files)
        emit("datagen", time.perf_counter() - t0, {"sf": args.sf, "rows": n_li,
                                                   "files": li_files + o_files})

    import hyperspace_tpu as hst

    sysd = os.path.join(root, "_indexes")
    os.makedirs(sysd, exist_ok=True)
    sess = hst.Session(conf={
        hst.keys.SYSTEM_PATH: sysd,
        hst.keys.NUM_BUCKETS: 64,
    })
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    li = sess.read_parquet(li_d)
    o = sess.read_parquet(o_d)

    # --- streaming index builds -------------------------------------------
    ix_df = hs.indexes()
    existing = set(ix_df["name"]) if len(ix_df) else set()
    t0 = time.perf_counter()
    if "li_ok_sf" not in existing:
        hs.create_index(
            li, hst.CoveringIndexConfig("li_ok_sf", ["l_orderkey"],
                                        ["l_extendedprice", "l_discount"])
        )
    li_build_s = time.perf_counter() - t0
    li_skipped = "li_ok_sf" in existing
    emit("build_lineitem", li_build_s,
         {"rows": n_li,
          "rows_per_s": None if li_skipped else round(n_li / max(li_build_s, 1e-9), 1),
          "skipped": li_skipped})
    t0 = time.perf_counter()
    n_o = int(datagen.ORDERS_ROWS_SF1 * args.sf)
    if "o_ok_sf" not in existing:
        hs.create_index(
            o, hst.CoveringIndexConfig("o_ok_sf", ["o_orderkey"], ["o_totalprice"])
        )
    o_build_s = time.perf_counter() - t0
    o_skipped = "o_ok_sf" in existing
    emit("build_orders", o_build_s,
         {"rows": n_o,
          "rows_per_s": None if o_skipped else round(n_o / max(o_build_s, 1e-9), 1),
          "skipped": o_skipped})

    # --- the config3 query, indexed (streaming bucketed SMJ) ---------------
    sess.enable_hyperspace()
    q = li.join(o, on=hst.col("l_orderkey") == hst.col("o_orderkey")).select(
        "l_extendedprice", "o_totalprice"
    )
    from hyperspace_tpu.exec import trace

    times = []
    out_rows = 0
    for _ in range(args.reps):
        t0 = time.perf_counter()
        with trace.recording() as rec:
            # drain through the local iterator: the full output never has to
            # sit in one allocation (sum as we go to prove the rows moved)
            out_rows = 0
            checksum = 0.0
            for chunk in q.to_local_iterator():
                out_rows += len(chunk["l_extendedprice"])
                checksum += float(np.sum(chunk["o_totalprice"][:100]))
        times.append(time.perf_counter() - t0)
    emit("indexed_join", min(times),
         {"reps": args.reps, "out_rows": out_rows,
          "dispatch": sorted({f"{k}:{v}" for k, v in rec}),
          "checksum": round(checksum, 2)})

    # --- streamed full-scan aggregate probe --------------------------------
    if args.agg_probe:
        qa = li.agg(s=("l_extendedprice", "sum"), n=("*", "count"),
                    mx=("l_extendedprice", "max"))
        t0 = time.perf_counter()
        with trace.recording() as rec:
            got = qa.collect()
        emit("streamed_aggregate", time.perf_counter() - t0,
             {"n": int(got["n"][0]), "dispatch": sorted({f"{k}:{v}" for k, v in rec})})

    # --- the non-indexed baseline (largest SF it can run) ------------------
    if not args.skip_baseline:
        sess.disable_hyperspace()
        times_b = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            rows_b = 0
            for chunk in q.to_local_iterator():
                rows_b += len(chunk["l_extendedprice"])
            times_b.append(time.perf_counter() - t0)
        emit("baseline_join", min(times_b), {"reps": args.reps, "out_rows": rows_b,
                                             "speedup_indexed": round(min(times_b) / min(times), 3)})

    if args.rss_budget_gb is not None and peak_rss_gb() > args.rss_budget_gb:
        print(json.dumps({"phase": "rss_budget", "error":
                          f"peak RSS {peak_rss_gb():.1f} GB exceeded budget {args.rss_budget_gb} GB"}),
              flush=True)
        sys.exit(3)

    if not args.keep and args.root is None:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
