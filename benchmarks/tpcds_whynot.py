"""TPC-DS index-leverage loop: point whyNot at every non-rewriting query.

The reference built whyNot precisely for this workflow
(ref: index/plananalysis/CandidateIndexAnalyzer.scala:29-346): run the
workload, ask "why didn't an index apply HERE", grow the index roster from
the answers, re-run. This script automates the loop over the reference's own
103 gold-standard texts (src/test/resources/tpcds/queries):

    python benchmarks/tpcds_whynot.py [--details-dir OUT]

Prints one JSON summary line (rewriting count, per-reason histogram) and
writes a per-query whyNot report for every non-rewriter. The test suite's
roster (tests/test_tpcds_queries.py INDEXES) is the roster under test.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import tempfile
from collections import Counter

import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

QUERIES_DIR = "/root/reference/src/test/resources/tpcds/queries"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--details-dir", default=None)
    ap.add_argument("--queries", nargs="*", default=None)
    args = ap.parse_args()

    import hyperspace_tpu as hst
    from tpcds_data import arrow_tables
    from test_tpcds_queries import INDEXES, _all_query_names, _query_text

    root = tempfile.mkdtemp(prefix="hs_tpcds_whynot_")
    sysp = os.path.join(root, "_indexes")
    os.makedirs(sysp)
    sess = hst.Session(conf={hst.keys.SYSTEM_PATH: sysp, hst.keys.NUM_BUCKETS: 4})
    hst.set_session(sess)
    hs = hst.Hyperspace(sess)
    for name, table in arrow_tables().items():
        d = os.path.join(root, name)
        os.makedirs(d)
        pq.write_table(table, os.path.join(d, "part-00000.parquet"))
        sess.read_parquet(d).create_or_replace_temp_view(name)
    for table, idx_name, indexed, included in INDEXES:
        hs.create_index(
            sess._temp_views[table], hst.CoveringIndexConfig(idx_name, indexed, included)
        )
    sess.enable_hyperspace()

    from hyperspace_tpu.plan import logical as L

    names = args.queries or _all_query_names()
    rewriting, plain = [], []
    reasons = Counter()
    details_dir = args.details_dir
    if details_dir:
        os.makedirs(details_dir, exist_ok=True)
    for qname in names:
        try:
            q = sess.sql(_query_text(qname))
            scans = L.collect(
                q.optimized_plan(), lambda p: isinstance(p, (L.IndexScan, L.FileScan))
            )
            index_hits = [
                s for s in scans
                if isinstance(s, L.IndexScan) or getattr(s, "via_index", None)
            ]
        except Exception as e:  # a text that fails to plan is its own reason
            plain.append(qname)
            reasons[f"plan-error: {type(e).__name__}"] += 1
            continue
        if index_hits:
            rewriting.append(qname)
            continue
        plain.append(qname)
        try:
            report = hs.why_not(q, extended=True)
        except Exception as e:
            report = f"whyNot failed: {e}"
        # histogram the dominant reason lines
        for line in report.splitlines():
            m = re.search(r"reason=\[?([A-Z_]+)", line)
            if m:
                reasons[m.group(1)] += 1
        if details_dir:
            with open(os.path.join(details_dir, f"{qname}.txt"), "w") as f:
                f.write(report)
    print(json.dumps({
        "total": len(names),
        "rewriting": len(rewriting),
        "rewriting_names": rewriting,
        "non_rewriting": plain,
        "reason_histogram": dict(reasons.most_common()),
    }), flush=True)


if __name__ == "__main__":
    main()
