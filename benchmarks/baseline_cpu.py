"""CPU-engine baseline for the BASELINE.md configs (Spark-CPU stand-in).

BASELINE.md's >=5x target is framed against a Spark-CPU baseline, but this
environment cannot install pyspark (no package installs; not in the image).
The closest measurable stand-in available is a pandas/pyarrow pipeline doing
exactly the same work per query — full scan + filter/join with no index —
which is what Spark's executors do for these shapes on a single node, minus
JVM/task-scheduling overhead (i.e. this baseline is, if anything, FASTER
than single-node Spark would be, making the reported speedups conservative).

Usage:
    python benchmarks/baseline_cpu.py [config1|config2|config3|config5|all] [--sf 0.2]

Each config prints one JSON line with cold (scan parquet + compute, what a
Spark query does) and warm (table already in memory) latencies.

Methodology mirror of benchmarks/run.py: one warm-up, then median of --reps.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks import datagen  # noqa: E402


def _read(path_dir: str):
    import pyarrow.dataset as pads

    return pads.dataset(path_dir, format="parquet").to_table()


def _timed(fn, reps: int) -> float:
    fn()  # warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _emit(config: int, metric: str, cold_ms: float, warm_ms: float, extra=None):
    row = {
        "config": config,
        "engine": "pandas-cpu (Spark-CPU stand-in; see module docstring)",
        "metric": metric,
        "cold_ms": round(cold_ms, 4),
        "warm_ms": round(warm_ms, 4),
    }
    if extra:
        row.update(extra)
    print(json.dumps(row), flush=True)


def config1(root, args):
    data = datagen.gen_sample(root)

    def cold():
        t = _read(data).to_pandas(date_as_object=False)
        return t[t["dept"] == 7][["value", "name"]]

    warm_t = _read(data).to_pandas(date_as_object=False)

    def warm():
        return warm_t[warm_t["dept"] == 7][["value", "name"]]

    _emit(1, "sample_filter_query_latency", _timed(cold, args.reps) * 1000,
          _timed(warm, args.reps) * 1000)


def config2(root, args):
    data = datagen.gen_lineitem(root, args.sf)
    day = np.datetime64("1995-06-15")

    def cold():
        t = _read(data).to_pandas(date_as_object=False)
        return t[t["l_shipdate"] == day][["l_orderkey", "l_extendedprice"]]

    warm_t = _read(data).to_pandas(date_as_object=False)

    def warm():
        return warm_t[warm_t["l_shipdate"] == day][["l_orderkey", "l_extendedprice"]]

    _emit(2, "tpch_shipdate_filter_latency", _timed(cold, args.reps) * 1000,
          _timed(warm, args.reps) * 1000, {"sf": args.sf})


def config3(root, args):
    li_d = datagen.gen_lineitem(root, args.sf)
    o_d = datagen.gen_orders(root, args.sf)

    def cold():
        li = _read(li_d).to_pandas(date_as_object=False)
        o = _read(o_d).to_pandas(date_as_object=False)
        return li.merge(o, left_on="l_orderkey", right_on="o_orderkey")[
            ["l_extendedprice", "o_totalprice"]
        ]

    li_t, o_t = _read(li_d).to_pandas(date_as_object=False), _read(o_d).to_pandas(date_as_object=False)

    def warm():
        return li_t.merge(o_t, left_on="l_orderkey", right_on="o_orderkey")[
            ["l_extendedprice", "o_totalprice"]
        ]

    _emit(3, "tpch_indexed_join_latency", _timed(cold, args.reps) * 1000,
          _timed(warm, args.reps) * 1000, {"sf": args.sf})


def config5(root, args):
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = max(10_000, int(1_000_000 * args.sf))
    d = os.path.join(root, "plain_li")
    os.makedirs(d)
    for seed in (0, 1):
        r = np.random.default_rng(seed)
        pq.write_table(
            pa.table(
                {
                    "k": r.integers(0, 1_000_000, n // 2).astype(np.int64),
                    "price": np.round(r.uniform(0, 1000, n // 2), 2),
                }
            ),
            os.path.join(d, f"part-{seed}.parquet"),
        )
    probe = int(np.random.default_rng(1).integers(0, 1_000_000, n // 2)[0])

    def cold():
        t = _read(d).to_pandas(date_as_object=False)
        return t[t["k"] == probe][["price"]]

    warm_t = _read(d).to_pandas(date_as_object=False)

    def warm():
        return warm_t[warm_t["k"] == probe][["price"]]

    _emit(5, "delta_incremental_plus_skipping_latency", _timed(cold, args.reps) * 1000,
          _timed(warm, args.reps) * 1000, {"sf": args.sf})


CONFIGS = {"config1": config1, "config2": config2, "config3": config3, "config5": config5}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("which", nargs="?", default="all")
    ap.add_argument("--sf", type=float, default=0.2)
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()
    which = list(CONFIGS) if args.which == "all" else [args.which]
    for name in which:
        root = tempfile.mkdtemp(prefix=f"hs_base_{name}_")
        try:
            CONFIGS[name](root, args)
        finally:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
