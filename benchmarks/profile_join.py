"""Profile where the indexed bucketed-join latency goes, by module.

Builds the config3 shape (lineitem ⋈ orders on l_orderkey with covering
indexes both sides), runs the indexed query under cProfile, and prints a
phase breakdown: cumulative time grouped by the package module that owns
each frame (decode/IO, device exec, plan/optimizer, numpy glue). The same
grouping runs for the non-indexed side so the two columns are comparable.

Usage: python benchmarks/profile_join.py [--sf 0.2] [--reps 3]
(JAX_PLATFORMS=cpu for the CPU engine; default drives the chip.)
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import os
import pstats
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks import datagen  # noqa: E402
from benchmarks.run import _session  # noqa: E402

GROUPS = [
    ("native-decode", ("hyperspace_tpu/native", "hs_native")),
    ("pyarrow-decode", ("pyarrow",)),
    ("device-exec", ("exec/device", "ops/bucketize", "ops/sort", "ops/kernels")),
    ("jax-dispatch", ("jax/", "jaxlib")),
    ("executor-host", ("exec/executor", "exec/batch")),
    ("plan+optimizer", ("rules/", "plan/", "analysis/")),
    ("index-metadata", ("models/", "indexes/", "sources/", "manager", "hyperspace.py")),
    ("pandas-glue", ("pandas",)),
]


def _group(path: str) -> str:
    for name, pats in GROUPS:
        if any(p in path for p in pats):
            return name
    return "other"


def _breakdown(pr: cProfile.Profile):
    st = pstats.Stats(pr, stream=io.StringIO())
    tot = {}
    for (path, _line, _fn), (_cc, _nc, tt, _ct, _callers) in st.stats.items():
        tot[_group(path)] = tot.get(_group(path), 0.0) + tt
    return dict(sorted(tot.items(), key=lambda kv: -kv[1]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.2)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    root = tempfile.mkdtemp(prefix="hs_prof_")
    try:
        li_d = datagen.gen_lineitem(root, args.sf)
        o_d = datagen.gen_orders(root, args.sf)
        sess, hs, hst = _session(root)
        li = sess.read_parquet(li_d)
        o = sess.read_parquet(o_d)
        hs.create_index(
            li,
            hst.CoveringIndexConfig(
                "li_ok", ["l_orderkey"], ["l_extendedprice", "l_discount"]
            ),
        )
        hs.create_index(
            o, hst.CoveringIndexConfig("o_ok", ["o_orderkey"], ["o_orderdate"])
        )
        q = li.join(o, on=hst.col("l_orderkey") == hst.col("o_orderkey")).select(
            "l_extendedprice", "l_discount", "o_orderdate"
        )

        for label, enabled in (("indexed", True), ("noindex", False)):
            (sess.enable_hyperspace if enabled else sess.disable_hyperspace)()
            q.collect()  # warm: jit compiles + OS caches out of the profile
            pr = cProfile.Profile()
            pr.enable()
            for _ in range(args.reps):
                q.collect()
            pr.disable()
            bd = _breakdown(pr)
            total = sum(bd.values())
            print(
                json.dumps(
                    {
                        "side": label,
                        "total_s": round(total, 3),
                        "per_rep_ms": round(total / args.reps * 1000, 1),
                        "by_module_ms": {
                            k: round(v / args.reps * 1000, 1) for k, v in bd.items()
                        },
                    }
                ),
                flush=True,
            )
            st = pstats.Stats(pr, stream=sys.stdout)
            st.sort_stats("tottime")
            print(f"--- top functions ({label}) ---")
            st.print_stats(12)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
