"""Benchmark every TPC-H query text (q1-q22) indexed vs non-indexed.

Round-4 VERDICT item 4: the q3-only config 7 left 21 of 22 texts never
benchmarked. This runs the full family over the scaled full-schema generator
(benchmarks/tpch_full.py), with the same covering-index roster the
correctness suite proves rewrites fire for (tests/test_tpch_queries.py), and
attaches whyNot output for every query where no rewrite fired.

Usage:
    python benchmarks/tpch22.py [--sf 0.05] [--reps 3] [--queries q3,q12]

One JSON line per query:
    {"query": "q3", "indexed_ms": ..., "plain_ms": ..., "speedup": ...,
     "rows": N, "indexes_used": [...]}
plus a final markdown table on stderr for RESULTS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from benchmarks import tpch_full  # noqa: E402

# the roster the correctness suite uses (wide vertical slices; dispatch
# goldens prove which queries rewrite under it)
INDEXES = [
    ("lineitem", "li_ok", ["l_orderkey"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_tax", "l_shipdate",
      "l_commitdate", "l_receiptdate", "l_shipmode", "l_returnflag",
      "l_linestatus", "l_suppkey", "l_partkey"]),
    ("lineitem", "li_sd", ["l_shipdate"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_orderkey"]),
    ("lineitem", "li_pk", ["l_partkey"],
     ["l_extendedprice", "l_discount", "l_quantity", "l_shipdate",
      "l_shipmode", "l_shipinstruct"]),
    ("orders", "o_ok", ["o_orderkey"],
     ["o_custkey", "o_orderdate", "o_totalprice", "o_orderpriority",
      "o_orderstatus", "o_shippriority"]),
    ("orders", "o_ck", ["o_custkey"],
     ["o_orderkey", "o_orderdate", "o_totalprice", "o_shippriority",
      "o_comment"]),
    ("customer", "c_ck", ["c_custkey"],
     ["c_name", "c_acctbal", "c_mktsegment", "c_nationkey", "c_phone",
      "c_address", "c_comment"]),
    ("part", "p_pk", ["p_partkey"],
     ["p_name", "p_mfgr", "p_brand", "p_type", "p_size", "p_container",
      "p_retailprice"]),
    ("supplier", "s_sk", ["s_suppkey"],
     ["s_name", "s_address", "s_nationkey", "s_phone", "s_acctbal",
      "s_comment"]),
    ("partsupp", "ps_pk", ["ps_partkey"],
     ["ps_suppkey", "ps_availqty", "ps_supplycost"]),
]


def _median_iqr(times):
    med = statistics.median(times)
    if len(times) >= 4:
        qs = statistics.quantiles(times, n=4)
        return med, qs[2] - qs[0]
    return med, max(times) - min(times)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=float(os.environ.get("BENCH_SF", 0.05)))
    ap.add_argument("--reps", type=int, default=int(os.environ.get("BENCH_REPS", 3)))
    ap.add_argument("--queries", default="")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()

    import bench

    bench._honor_cpu_request()
    bench._backend_watchdog(
        emit=lambda reason: print(json.dumps({"query": None, "error": reason}), flush=True)
    )

    from tpch_queries import TPCH_QUERIES  # noqa: E402 (tests/ on path)

    import hyperspace_tpu as hst

    want = [q.strip() for q in args.queries.split(",") if q.strip()] or sorted(
        TPCH_QUERIES, key=lambda s: int(s[1:])
    )

    root = tempfile.mkdtemp(prefix="hs_tpch22_")
    table_rows = []
    try:
        t0 = time.time()
        dirs = tpch_full.gen_all(root, args.sf)
        print(json.dumps({"event": "datagen_done", "sf": args.sf,
                          "seconds": round(time.time() - t0, 1)}), flush=True)
        sysd = os.path.join(root, "_indexes")
        os.makedirs(sysd, exist_ok=True)
        sess = hst.Session(conf={
            hst.keys.SYSTEM_PATH: sysd,
            hst.keys.NUM_BUCKETS: 16,
            hst.keys.FILTER_RULE_USE_BUCKET_SPEC: True,
        })
        hst.set_session(sess)
        hs = hst.Hyperspace(sess)
        for name, d in dirs.items():
            sess.read_parquet(d).create_or_replace_temp_view(name)
        t0 = time.time()
        for table, idx_name, indexed, included in INDEXES:
            hs.create_index(
                sess._temp_views[table], hst.CoveringIndexConfig(idx_name, indexed, included)
            )
        print(json.dumps({"event": "index_build_done",
                          "seconds": round(time.time() - t0, 1)}), flush=True)
        sess.enable_hyperspace()

        for qname in want:
            text = TPCH_QUERIES[qname]
            try:
                q = sess.sql(text)
                plan = q.optimized_plan().pretty()
                used = sorted(set(
                    part.split("Name: ")[1].split(",")[0]
                    for part in plan.split("Hyperspace(")[1:]
                ))
                # timed runs: one warm + reps, indexed then plain
                q.collect()
                ts = []
                for _ in range(args.reps):
                    s = time.perf_counter()
                    got = q.collect()
                    ts.append(time.perf_counter() - s)
                rows = len(next(iter(got.values()))) if got else 0
                ti, ti_iqr = _median_iqr(ts)
                sess.disable_hyperspace()
                try:
                    qp = sess.sql(text)
                    qp.collect()
                    ts = []
                    for _ in range(args.reps):
                        s = time.perf_counter()
                        qp.collect()
                        ts.append(time.perf_counter() - s)
                finally:
                    # a mid-query failure must not leave every later query
                    # running its "indexed" measurement unindexed
                    sess.enable_hyperspace()
                tp, tp_iqr = _median_iqr(ts)
                row = {
                    "query": qname,
                    "indexed_ms": round(ti * 1000, 2),
                    "indexed_iqr_ms": round(ti_iqr * 1000, 2),
                    "plain_ms": round(tp * 1000, 2),
                    "plain_iqr_ms": round(tp_iqr * 1000, 2),
                    "speedup": round(tp / ti, 3) if ti > 0 else None,
                    "rows": rows,
                    "indexes_used": used,
                }
                if not used:
                    why = hs.why_not(q)
                    # the summary sections only: keep the JSON line readable
                    row["why_not"] = " | ".join(
                        ln for ln in why.splitlines()
                        if ln.startswith("- ") or ln.endswith(":")
                    )[:500]
                print(json.dumps(row), flush=True)
                table_rows.append(row)
            except Exception as e:  # noqa: BLE001 — record and continue
                print(json.dumps({"query": qname, "error": f"{type(e).__name__}: {e}"[:300]}),
                      flush=True)
    finally:
        if not args.keep:
            import shutil

            shutil.rmtree(root, ignore_errors=True)

    if table_rows:
        print("\n| query | indexed ms | plain ms | speedup | rows | indexes |",
              file=sys.stderr)
        print("|---|---|---|---|---|---|", file=sys.stderr)
        for r in table_rows:
            print(
                f"| {r['query']} | {r['indexed_ms']}±{r['indexed_iqr_ms']} | "
                f"{r['plain_ms']}±{r['plain_iqr_ms']} | {r['speedup']}x | "
                f"{r['rows']} | {','.join(r['indexes_used']) or '-'} |",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
