"""TPC-H-like data generation for the benchmark configs (BASELINE.md).

Not the official dbgen: columns and value distributions follow the TPC-H
schema shapes the queries need (lineitem, orders), sized by a scale factor
where SF 1 ~= 6M lineitem rows, matching TPC-H's row-count scaling.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

LINEITEM_ROWS_SF1 = 6_000_000
ORDERS_ROWS_SF1 = 1_500_000


def gen_lineitem(root: str, sf: float, num_files: int = 16, seed: int = 0) -> str:
    d = os.path.join(root, "lineitem")
    os.makedirs(d, exist_ok=True)
    n = int(LINEITEM_ROWS_SF1 * sf)
    per = max(1, n // num_files)
    rng = np.random.default_rng(seed)
    base = np.datetime64("1992-01-01")
    n_orders = max(1, int(ORDERS_ROWS_SF1 * sf))
    for i in range(num_files):
        rows = per if i < num_files - 1 else n - per * (num_files - 1)
        if rows <= 0:
            continue
        t = pa.table(
            {
                "l_orderkey": rng.integers(0, n_orders, rows).astype(np.int64),
                "l_partkey": rng.integers(0, int(200_000 * max(sf, 0.01)), rows).astype(np.int64),
                "l_quantity": rng.integers(1, 51, rows).astype(np.int64),
                "l_extendedprice": np.round(rng.uniform(900.0, 105000.0, rows), 2),
                "l_discount": np.round(rng.uniform(0.0, 0.1, rows), 2),
                "l_tax": np.round(rng.uniform(0.0, 0.08, rows), 2),
                "l_shipdate": base + rng.integers(0, 2526, rows).astype("timedelta64[D]"),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def gen_orders(root: str, sf: float, num_files: int = 8, seed: int = 1) -> str:
    d = os.path.join(root, "orders")
    os.makedirs(d, exist_ok=True)
    n = max(1, int(ORDERS_ROWS_SF1 * sf))
    per = max(1, n // num_files)
    rng = np.random.default_rng(seed)
    base = np.datetime64("1992-01-01")
    for i in range(num_files):
        rows = per if i < num_files - 1 else n - per * (num_files - 1)
        if rows <= 0:
            continue
        t = pa.table(
            {
                "o_orderkey": np.arange(i * per, i * per + rows, dtype=np.int64),
                "o_custkey": rng.integers(0, int(150_000 * max(sf, 0.01)), rows).astype(np.int64),
                "o_totalprice": np.round(rng.uniform(800.0, 600000.0, rows), 2),
                "o_orderdate": base + rng.integers(0, 2406, rows).astype("timedelta64[D]"),
                "o_shippriority": rng.integers(0, 2, rows).astype(np.int64),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


def gen_sample(root: str, n: int = 100_000, num_files: int = 4, seed: int = 2) -> str:
    """Small sample dataset for config 1 (the reference's examples/ data shape)."""
    d = os.path.join(root, "sample")
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    per = n // num_files
    for i in range(num_files):
        t = pa.table(
            {
                "id": rng.integers(0, n, per).astype(np.int64),
                "dept": rng.integers(0, 50, per).astype(np.int64),
                "value": rng.standard_normal(per),
                "name": np.array([f"emp_{j % 991}" for j in range(per)]),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d


CUSTOMER_ROWS_SF1 = 150_000


def gen_customer(root: str, sf: float, num_files: int = 4, seed: int = 3) -> str:
    """TPC-H-like customer with string-heavy payload columns (name, address,
    market segment) for the string-payload join benchmark (round-3 VERDICT
    item: size the host-side string-gather cost of device materialization)."""
    d = os.path.join(root, "customer")
    os.makedirs(d, exist_ok=True)
    # key-domain floor must match gen_orders' o_custkey domain
    # (150_000 * max(sf, 0.01)) or small-sf joins silently lose most matches
    n = max(1, int(CUSTOMER_ROWS_SF1 * max(sf, 0.01)))
    per = max(1, n // num_files)
    rng = np.random.default_rng(seed)
    segments = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"])
    for i in range(num_files):
        rows = per if i < num_files - 1 else n - per * (num_files - 1)
        if rows <= 0:
            continue
        keys = np.arange(i * per, i * per + rows, dtype=np.int64)
        t = pa.table(
            {
                "c_custkey": keys,
                "c_name": np.array([f"Customer#{k:09d}" for k in keys]),
                "c_address": np.array(
                    [f"{rng.integers(1, 9999)} Market St Apt {k % 97}" for k in keys]
                ),
                "c_mktsegment": segments[rng.integers(0, 5, rows)],
                "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, rows), 2),
            }
        )
        pq.write_table(t, os.path.join(d, f"part-{i:05d}.parquet"))
    return d
